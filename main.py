#!/usr/bin/env python3
"""Entry point: ./main.py {train, evaluate, checkpoint, gencfg} ...

(reference main.py:1-6)
"""

from raft_meets_dicl_tpu.main import main

if __name__ == "__main__":
    main()
