"""Benchmark: RAFT training throughput in image-pairs/sec/chip.

Mirrors the reference's FlyingThings3D training configuration (batch 6,
720x400 crops, 12 GRU iterations, AdamW + grad clip —
cfg/strategy/baseline/raft/s1-things.yaml) as a synthetic-data training-step
benchmark on one chip. Prints ONE JSON line.

``vs_baseline`` compares against the north-star target of 400 image-pairs/s
on a v4-32 (32 chips) => 12.5 pairs/s/chip (BASELINE.json; the reference
repo publishes no throughput numbers of its own).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_PAIRS_PER_SEC_PER_CHIP = 400.0 / 32.0


def main():
    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import parallel

    batch = int(os.environ.get("BENCH_BATCH", "6"))
    height = int(os.environ.get("BENCH_HEIGHT", "400"))
    width = int(os.environ.get("BENCH_WIDTH", "720"))
    iters = int(os.environ.get("BENCH_ITERS", "12"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))

    if jax.default_backend() == "cpu":
        # CPU fallback (no TPU attached): tiny shapes, still one JSON line
        batch, height, width, iters, steps = 2, 64, 96, 4, 3

    spec = models.load({
        "name": "bench", "id": "bench",
        # mixed-precision bf16 is the TPU-native policy (the reference's
        # autocast equivalent). Profiling history at this config:
        # - scalar-gather corr lookup: ~17 s/step; einsum lookup: 0.67 s
        # - convex Up8 hoisted out of the remat'd scan (batched over
        #   iterations, compact (s,k) mask layout): 0.45 s
        # - remat policy saving the per-iteration corr lookups: 0.43 s
        "model": {"type": "raft/baseline", "parameters": {"mixed-precision": True}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })
    model, loss = spec.model, spec.loss

    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    img2 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(batch, height, width, 2), jnp.float32)
    valid = jnp.ones((batch, height, width), bool)

    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1], iterations=2)

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(4e-4))
    state = parallel.TrainState.create(variables, tx)

    step = parallel.make_train_step(
        model, loss, tx, model_args={"iterations": iters}
    )

    # warmup / compile; sync by fetching the scalar — on the tunneled axon
    # backend block_until_ready does not reliably wait, value transfer does
    state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])
    dt = time.perf_counter() - t0

    pairs_per_sec = batch * steps / dt

    print(json.dumps({
        "metric": "train-throughput-raft-things",
        "value": round(pairs_per_sec, 3),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
