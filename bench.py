"""Benchmark: RAFT training throughput in image-pairs/sec/chip.

Mirrors the reference's FlyingThings3D training configuration (batch 6,
720x400 crops, 12 GRU iterations, AdamW + grad clip —
cfg/strategy/baseline/raft/s1-things.yaml) as a synthetic-data training-step
benchmark on one chip. Prints the primary metric as a JSON line as soon
as it is measured, then (flagship enabled) a second, enriched JSON line
with the thesis flagship's (raft+dicl/ctf-l3) throughput added —
consumers read the LAST line, which is always the most complete.

``vs_baseline`` compares against the north-star target of 400 image-pairs/s
on a v4-32 (32 chips) => 12.5 pairs/s/chip (BASELINE.json; the reference
repo publishes no throughput numbers of its own).
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from raft_meets_dicl_tpu.utils import env

BASELINE_PAIRS_PER_SEC_PER_CHIP = 400.0 / 32.0


def _emit(result):
    """Print one cumulative JSON result line with the goodput breakdown
    attached: every BENCH_* line carries the wall-clock ledger
    (productive vs compile vs data-starved vs ... seconds) so a slow
    bench is attributable without re-running under a profiler."""
    from raft_meets_dicl_tpu.telemetry import goodput

    # every BENCH_* row names its augmentation arm ("off" unless a bench
    # sets one), so result consumers can split host/device/synth series
    result.setdefault("augment", "off")
    ledger = goodput.get()
    if ledger.enabled:
        snap = ledger.snapshot()
        result["goodput"] = {
            "total_s": snap["total"],
            "goodput": snap["goodput"],
            "classes_s": snap["classes"],
        }
    print(json.dumps(result), flush=True)
    return result


def _profile_step(run):
    """Measured graftprof attribution of one profiled step execution —
    the per-op-class receipt every BENCH_* line carries (BENCH_PROFILE=0
    disables). Advisory: returns an ``{"error": ...}`` stub instead of
    raising, so a profiler/parser failure never loses the bench line."""
    import shutil
    import tempfile

    from raft_meets_dicl_tpu.analysis import profile as prof

    tmp = tempfile.mkdtemp(prefix="rmd-bench-prof-")
    try:
        jax.profiler.start_trace(tmp)
        try:
            out = run()
            jax.block_until_ready(out)
        finally:
            jax.profiler.stop_trace()
        summary = prof.attribute_trace(tmp)
        classes = {}
        for m in summary["modules"]:
            for c, s in m["classes"].items():
                classes[c] = round(classes.get(c, 0.0) + s, 6)
        return {
            "device_seconds": summary["device_seconds"],
            "source": summary["source"],
            "classes": dict(sorted(classes.items(),
                                   key=lambda kv: -kv[1])),
            "modules": [{"module": m["module"], "program": m["program"],
                         "seconds": m["seconds"]}
                        for m in summary["modules"][:4]],
        }
    except Exception as e:  # noqa: BLE001 - attribution is advisory
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _measure(model_cfg, loss_cfg, batch, height, width, model_args, steps,
             nonfinite=None):
    """One synthetic training-step throughput measurement; all device
    state is local, so buffers free when it returns.

    Returns (pairs_per_sec, peak_bytes, telemetry_summary) — the summary
    carries compile/cache counts from the active telemetry sink plus
    dispatch-time stats, so BENCH_*.json records more than one number.
    ``nonfinite='skip'`` builds the step with the non-finite skip guard
    (BENCH_FAULT overhead measurement)."""
    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import parallel, telemetry

    spec = models.load({
        "name": "bench", "id": "bench",
        "model": model_cfg, "loss": loss_cfg, "input": None,
    })
    model, loss = spec.model, spec.loss

    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    img2 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(batch, height, width, 2), jnp.float32)
    valid = jnp.ones((batch, height, width), bool)

    init_args = dict(model_args)
    if "iterations" in init_args:
        init_args["iterations"] = (
            (1,) * len(model_args["iterations"])
            if isinstance(model_args["iterations"], tuple) else 1)
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1],
                           **init_args)

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(4e-4))
    state = parallel.TrainState.create(variables, tx)
    step = parallel.make_train_step(model, loss, tx, model_args=model_args,
                                    nonfinite=nonfinite)

    tele = telemetry.get()
    tail0 = len(getattr(tele, "events", ()))

    # warmup / compile; sync by fetching the scalar — on the tunneled axon
    # backend block_until_ready does not reliably wait, value transfer does
    t0 = time.perf_counter()
    state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])
    compile_wall = time.perf_counter() - t0

    # per-step dispatch timing only when telemetry is on: RMD_TELEMETRY=0
    # must restore the bare measurement loop
    dispatch = []
    t0 = time.perf_counter()
    if tele.enabled:
        for _ in range(steps):
            ts = time.perf_counter()
            state, aux = step(state, img1, img2, flow, valid)
            dispatch.append(time.perf_counter() - ts)
    else:
        for _ in range(steps):
            state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])
    dt = time.perf_counter() - t0

    summary = None
    if tele.enabled:
        # the bench sink is memory-only: the tail since tail0 is exactly
        # this measurement's compile/cache activity
        tail = getattr(tele, "events", [])[tail0:]
        compiles = [e for e in tail if e["kind"] == "compile"]
        caches = [e for e in tail if e["kind"] == "cache"]
        dispatch.sort()
        summary = {
            "compiles": len(compiles),
            "compile_s": round(sum(e["seconds"] for e in compiles), 3),
            "cache_hits": sum(1 for e in caches if e["event"] == "hit"),
            "cache_misses": sum(1 for e in caches if e["event"] == "miss"),
            "warmup_wall_s": round(compile_wall, 3),
            "step_ms_mean": round(dt / steps * 1e3, 3),
            "dispatch_ms_mean": round(sum(dispatch) / steps * 1e3, 3),
            "dispatch_ms_p95": round(
                dispatch[min(steps - 1, int(round(0.95 * (steps - 1))))]
                * 1e3, 3),
        }
        # measured device-time attribution (graftprof): one extra
        # profiled step, parsed into per-op-class seconds
        if os.environ.get("BENCH_PROFILE", "1") != "0":
            summary["profile"] = _profile_step(
                lambda: step(state, img1, img2, flow, valid))

    # peak_bytes_in_use is a process-lifetime high-water mark: meaningful
    # for the first measurement in a process, an upper bound afterwards
    stats = jax.local_devices()[0].memory_stats() or {}
    return batch * steps / dt, stats.get("peak_bytes_in_use", 0), summary


def _bench_input():
    """Standalone input-pipeline benchmark (``BENCH_INPUT=1``): for each
    wire preset, decode throughput through the adapter+loader path,
    collate time, and the wire volume one bench-shaped batch moves across
    the host→device boundary. Host-only — no device work, so the numbers
    isolate the pipeline from the step it feeds. ``RMD_LOADER_PROCS``
    selects the decode-process pool; prints one (cumulative) JSON line
    per preset."""
    from raft_meets_dicl_tpu.data.collection import (
        Metadata, SampleArgs, SampleId,
    )
    from raft_meets_dicl_tpu.models import input as minput
    from raft_meets_dicl_tpu.models.wire import WireFormat

    batch = int(os.environ.get("BENCH_BATCH", "6"))
    height = int(os.environ.get("BENCH_HEIGHT", "400"))
    width = int(os.environ.get("BENCH_WIDTH", "720"))
    n = int(os.environ.get("BENCH_INPUT_SAMPLES", "48"))
    procs = env.get_int("RMD_LOADER_PROCS")

    class Synth:
        """Raw [0, 1] pairs generated per access — a stand-in for the
        decoded-dataset read the real pipeline amortizes via `cache`."""

        def __init__(self, n, h, w):
            self.n, self.h, self.w = n, h, w

        def __getitem__(self, index):
            rng = np.random.RandomState(index)
            img1 = rng.rand(1, self.h, self.w, 3).astype(np.float32)
            img2 = rng.rand(1, self.h, self.w, 3).astype(np.float32)
            flow = rng.randn(1, self.h, self.w, 2).astype(np.float32)
            valid = np.ones((1, self.h, self.w), bool)
            meta = [Metadata(True, "synth",
                             SampleId("s", SampleArgs(), SampleArgs()),
                             ((0, self.h), (0, self.w)))]
            return img1, img2, flow, valid, meta

        def __len__(self):
            return self.n

    spec = minput.InputSpec(clip=(0, 1), range=(-1, 1))
    result = {
        "metric": "input-pipeline",
        "batch": batch, "height": height, "width": width, "samples": n,
        "loader_procs": procs,
    }
    for preset in (None, "f32", "bf16", "u8"):
        wire = WireFormat.from_config(preset, clip=spec.clip,
                                      range=spec.range)
        adapter = spec.apply(Synth(n, height, width),
                             normalize=wire is None).jax(wire=wire)
        loader = adapter.loader(batch_size=batch, shuffle=False,
                                procs=procs)

        t0 = time.perf_counter()
        decoded, last = 0, None
        for b in loader:
            decoded += b[0].shape[0]
            last = b
        dt = time.perf_counter() - t0

        samples = [adapter[i] for i in range(min(batch, n))]
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            minput.collate(samples)
        collate_ms = (time.perf_counter() - t0) / reps * 1e3

        wire_batch = (last[:4] if wire is None
                      else wire.encode_batch(last[:4]))
        wire_mb = sum(a.nbytes for a in wire_batch
                      if a is not None) / 2 ** 20

        result[preset or "host-f32"] = {
            "samples_per_sec": round(decoded / dt, 2),
            "collate_ms": round(collate_ms, 2),
            "wire_mb_per_step": round(wire_mb, 3),
        }
        _emit(result)

    # augmentation arms (PR 19): the same raw source decoded three ways —
    # "host" augments inside the decode path (seeded-Generator numpy
    # transforms), "device" ships raw batches and runs the jitted
    # DeviceAugment pipeline on the accelerator, "synth" renders
    # exact-flow pairs on device and never decodes at all. samples/s is
    # end-to-end; data_wait_share is the fraction of wall time spent
    # outside device compute (what a training step would stall on).
    from raft_meets_dicl_tpu.data import augment as haug
    from raft_meets_dicl_tpu.data import synth as dsynth
    from raft_meets_dicl_tpu.data.device_augment import DeviceAugment

    def _collate_ms(adapter):
        samples = [adapter[i] for i in range(min(batch, n))]
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            minput.collate(samples)
        return (time.perf_counter() - t0) / reps * 1e3

    host_src = haug.Augment(
        [haug.ColorJitter(0.2, 0.4, 0.4, 0.4, 0.1),
         haug.Flip([0.5, 0.1]),
         haug.NoiseNormal([0.0, 0.02]),
         haug.OcclusionForward(0.5, [1, 3], [10, 10], [30, 30])],
        Synth(n, height, width), sync=True)
    adapter = spec.apply(host_src, normalize=True).jax()
    loader = adapter.loader(batch_size=batch, shuffle=False, procs=procs)
    t0 = time.perf_counter()
    decoded = 0
    for b in loader:
        decoded += b[0].shape[0]
    dt = time.perf_counter() - t0
    result["augment"] = "host"
    result["host-augment"] = {
        "samples_per_sec": round(decoded / dt, 2),
        "collate_ms": round(_collate_ms(adapter), 2),
        "data_wait_share": 1.0,
    }
    _emit(result)

    dev = DeviceAugment(occlusion_size=(10, 30))
    dev_fn = jax.jit(lambda ids, a, b, f, v: dev.apply(
        dev.batch_keys(ids, 0), a, b, f, v))
    adapter = spec.apply(Synth(n, height, width), normalize=True).jax()
    loader = adapter.loader(batch_size=batch, shuffle=False, procs=procs)
    warm = [jnp.asarray(a) for a in next(iter(loader))[:4]]
    jax.block_until_ready(dev_fn(
        jnp.arange(warm[0].shape[0], dtype=jnp.uint32), *warm))
    t0 = time.perf_counter()
    decoded, device_s = 0, 0.0
    for i, b in enumerate(loader):
        arrs = [jnp.asarray(a) for a in b[:4]]
        ids = jnp.arange(i * batch, i * batch + arrs[0].shape[0],
                         dtype=jnp.uint32)
        t1 = time.perf_counter()
        out = dev_fn(ids, *arrs)
        jax.block_until_ready(out)
        device_s += time.perf_counter() - t1
        decoded += arrs[0].shape[0]
    dt = time.perf_counter() - t0
    result["augment"] = "device"
    result["device-augment"] = {
        "samples_per_sec": round(decoded / dt, 2),
        "collate_ms": round(_collate_ms(adapter), 2),
        "device_ms_per_batch": round(
            device_s / max(1, decoded // batch) * 1e3, 2),
        "data_wait_share": round(max(0.0, 1.0 - device_s / dt), 4),
    }
    _emit(result)

    render = jax.jit(lambda k: dsynth.render_pair(k, (height, width)))
    k0 = jax.random.PRNGKey(0)
    jax.block_until_ready(render(k0))
    t0 = time.perf_counter()
    for i in range(n):
        out = render(jax.random.fold_in(k0, i))
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    result["augment"] = "synth"
    result["synth-source"] = {
        "samples_per_sec": round(n / dt, 2),
        "collate_ms": 0.0,
        "data_wait_share": 0.0,
    }
    _emit(result)
    return result


def _bench_eval():
    """Shape-bucketed evaluation benchmark (``BENCH_EVAL=1``): a synthetic
    mixed-resolution eval set (three distinct raw shapes, KITTI-style) run
    through (a) the batch-1 unbucketed baseline — one jit compile per
    distinct padded shape — and (b) the bucketed pipeline (ShapeBuckets +
    shape-grouping loader + partial-batch padding + precompile warmup).
    Reports samples/s end-to-end (compiles included: that is what a
    validation sweep costs), steady-state samples/s, compile counts, and
    the pad-overhead ratio per preset. One cumulative JSON line per
    measurement; consumers read the last."""
    import jax

    from raft_meets_dicl_tpu import evaluation, telemetry
    from raft_meets_dicl_tpu.data.collection import (
        Metadata, SampleArgs, SampleId,
    )
    from raft_meets_dicl_tpu.models import input as minput
    import raft_meets_dicl_tpu.models as models

    # KITTI's per-image resolutions: many *slightly different* raw shapes
    # (375x1242, 370x1224, 374x1238, ...) — the baseline compiles one
    # program per distinct padded shape, bucketing quantizes them all
    # onto two canonical sizes
    cpu = jax.default_backend() == "cpu"
    if cpu:
        shapes = [(64, 96), (64, 88), (64, 80), (56, 88), (56, 80),
                  (56, 72), (48, 72), (48, 64)]
        bucket_sizes = [(64, 96), (56, 88)]
        per_shape = int(os.environ.get("BENCH_EVAL_SAMPLES", "6"))
        batch = int(os.environ.get("BENCH_EVAL_BATCH", "4"))
        iters = 2
        model_params = {"corr-levels": 2, "corr-radius": 2,
                        "corr-channels": 32, "context-channels": 16,
                        "recurrent-channels": 16}
    else:
        shapes = [(376, 1248), (376, 1232), (368, 1232), (368, 1224),
                  (360, 1224), (352, 1216)]
        bucket_sizes = [(376, 1248), (368, 1232)]
        per_shape = int(os.environ.get("BENCH_EVAL_SAMPLES", "8"))
        batch = int(os.environ.get("BENCH_EVAL_BATCH", "8"))
        iters = 12
        model_params = {}

    spec = models.load({
        "name": "bench-eval", "id": "bench-eval",
        "model": {"type": "raft/baseline", "parameters": model_params,
                  "arguments": {"iterations": iters}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    })
    model = spec.model

    class Synth:
        """Mixed-shape raw samples, round-robin over the shape list."""

        def __init__(self, shapes, per_shape):
            self.items = [s for s in shapes for _ in range(per_shape)]

        def __getitem__(self, index):
            h, w = self.items[index]
            rng = np.random.RandomState(index)
            img1 = rng.rand(1, h, w, 3).astype(np.float32)
            img2 = rng.rand(1, h, w, 3).astype(np.float32)
            flow = rng.randn(1, h, w, 2).astype(np.float32)
            valid = np.ones((1, h, w), bool)
            meta = [Metadata(True, "synth-mixed",
                             SampleId(f"s{index}", SampleArgs(), SampleArgs()),
                             ((0, h), (0, w)))]
            return img1, img2, flow, valid, meta

        def __len__(self):
            return len(self.items)

    source = Synth(shapes, per_shape)
    init = source[0]
    variables = model.init(jax.random.PRNGKey(0), init[0], init[1])

    buckets = minput.ShapeBuckets(bucket_sizes)

    def sweep(buckets, batch_size, pad_to=None, precompile=False, label=""):
        tele = telemetry.get()
        tail0 = len(getattr(tele, "events", ()))
        loader = spec.input.apply(source, buckets=buckets).jax().loader(
            batch_size=batch_size, shuffle=False,
            group_by_shape=buckets is not None, num_workers=2)
        stats = evaluation.EvalRunStats(name=label)
        fn = evaluation.make_eval_fn(model, None)
        t0 = time.perf_counter()
        if precompile:
            evaluation.warmup_eval_fn(fn, variables, buckets.sizes,
                                      pad_to or batch_size, stats=stats)
        epe_sum = n = 0.0
        for s in evaluation.evaluate(model, variables, loader, eval_fn=fn,
                                     show_progress=False, pad_to=pad_to,
                                     stats=stats):
            err = np.linalg.norm(s.final - s.target, axis=-1)
            epe_sum += float(err[np.asarray(s.valid, bool)].mean())
            n += 1
        wall = time.perf_counter() - t0
        # steady state: the sweep minus compile/warmup cost — what a
        # second epoch over the same buckets would cost
        tail = getattr(tele, "events", [])[tail0:]
        compile_s = sum(e["seconds"] for e in tail
                        if e["kind"] == "compile"
                        and e.get("label") == "eval_step")
        warm = stats.phases.get("warmup", 0.0)
        steady = max(wall - max(warm, compile_s), 1e-9)
        return {
            "samples": int(n),
            "samples_per_sec": round(n / wall, 3),
            "samples_per_sec_steady": round(n / steady, 3),
            "compiled_shapes": stats.compiles,
            "compile_s": round(compile_s, 3),
            "batches": stats.batches,
            "pad_waste_ratio": round(stats.pad_waste_ratio(), 4),
            "mean_epe": round(epe_sum / max(n, 1), 5),
            "wall_s": round(wall, 3),
        }

    result = {
        "metric": "eval-throughput-mixed-shapes",
        "backend": jax.default_backend(),
        "shapes": [f"{h}x{w}" for h, w in shapes],
        "samples": len(source), "batch": batch,
        "buckets": [f"{h}x{w}" for h, w in buckets.sizes],
    }

    # (a) baseline: batch 1, no bucketing — one compile per distinct shape
    evaluation._EVAL_FN_CACHE.clear()
    result["baseline_b1"] = sweep(None, 1, label="baseline-b1")
    _emit(result)

    # (b) bucketed: grouped full batches, remainder padding, warm buckets
    evaluation._EVAL_FN_CACHE.clear()
    result["bucketed"] = sweep(buckets, batch, pad_to=batch,
                               precompile=True, label="bucketed")
    result["speedup_end_to_end"] = round(
        result["bucketed"]["samples_per_sec"]
        / max(result["baseline_b1"]["samples_per_sec"], 1e-9), 2)
    result["speedup_steady"] = round(
        result["bucketed"]["samples_per_sec_steady"]
        / max(result["baseline_b1"]["samples_per_sec_steady"], 1e-9), 2)
    result["epe_rel_diff"] = round(
        abs(result["bucketed"]["mean_epe"] - result["baseline_b1"]["mean_epe"])
        / max(abs(result["baseline_b1"]["mean_epe"]), 1e-9), 6)
    _emit(result)
    return result


def _bench_serve():
    """Serving-path benchmark (``BENCH_SERVE=1``): an open-loop synthetic
    request stream over 8 mixed resolutions through the continuous-batching
    scheduler (serve/). Three phases: (1) a cold replica — the warm pool
    pays at most one compile per bucket up front, then the whole stream
    (partial batches included: they pad-tile onto the full batch's program)
    serves with zero further compiles; (2) a warm-pool prebuild exporting
    AOT artifacts for every (model, bucket, wire) triple into a fresh
    store; (3) a fresh replica against that store — prepared with zero
    compiles (AOT hits only) and serving the full stream the same way.
    Budget permitting, a fourth phase streams fast-class requests
    through a ladder'd replica on the quantized matching tier
    (``BENCH_SERVE_QUANT``, default u8; see ``ops.quant``), and a fifth
    runs the serving-fleet kill/rejoin drill (two video replicas behind
    the router, skewed mix + sticky stream, one replica hard-killed
    mid-stream and rejoining warm from the published AOT store;
    ``BENCH_FLEET_FRAMES`` sizes the stream). Reports p50/p99 latency,
    wall + steady-state pairs/s, and shed/error counts; every phase row
    carries a ``quant`` field. One cumulative JSON line per phase;
    consumers read the last."""
    import shutil
    import tempfile

    import jax

    from raft_meets_dicl_tpu import compile as programs
    from raft_meets_dicl_tpu import evaluation, serve, telemetry
    from raft_meets_dicl_tpu.models import input as minput
    from raft_meets_dicl_tpu.models import wire as mwire
    import raft_meets_dicl_tpu.models as models

    cpu = jax.default_backend() == "cpu"
    if cpu:
        shapes = [(64, 96), (64, 88), (64, 80), (56, 88), (56, 80),
                  (56, 72), (48, 72), (48, 64)]
        bucket_sizes = [(64, 96), (56, 88)]
        batch = int(os.environ.get("BENCH_SERVE_BATCH", "4"))
        requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "24"))
        rate = float(os.environ.get("BENCH_SERVE_RATE", "50"))
        iters = 2
        model_params = {"corr-levels": 2, "corr-radius": 2,
                        "corr-channels": 32, "context-channels": 16,
                        "recurrent-channels": 16}
    else:
        shapes = [(376, 1248), (376, 1232), (368, 1232), (368, 1224),
                  (360, 1224), (352, 1216), (368, 1248), (360, 1232)]
        bucket_sizes = [(376, 1248), (368, 1232)]
        batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
        requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "64"))
        rate = float(os.environ.get("BENCH_SERVE_RATE", "20"))
        iters = 12
        model_params = {}

    model_cfg = {
        "name": "bench-serve", "id": "bench-serve",
        "model": {"type": "raft/baseline", "parameters": model_params,
                  "arguments": {"iterations": iters}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    wire_name = os.environ.get("BENCH_SERVE_WIRE", "u8")
    wire = mwire.WireFormat.from_config(wire_name)

    def run_phase(quant=None, ladder=None, classes=None):
        # a fresh replica each time: new model spec, new session — the
        # only thing phases may share is the AOT store on disk
        tele = telemetry.get()
        spec = models.load(model_cfg)
        session = serve.ServeSession(
            spec, minput.ShapeBuckets(bucket_sizes), wire=wire,
            batch_size=batch, ladder=ladder, quant=quant)
        t0 = time.perf_counter()
        outcomes = session.warm_pool()
        warm_s = time.perf_counter() - t0
        mark = len(getattr(tele, "events", ()))
        sched = serve.Scheduler(session, max_wait_ms=20.0,
                                queue_limit=64).start()
        if not sched.slo:
            # no RMD_SLO_* knobs set: pin a bench-local default target so
            # the attainment/burn columns always render
            from raft_meets_dicl_tpu.telemetry import slo as rmd_slo
            sched.slo = rmd_slo.SLOTracker(
                class_targets={"": float(os.environ.get(
                    "BENCH_SERVE_SLO_MS", "250"))},
                objective=0.99, window_s=300.0)
        report = serve.loadgen.run_open_loop(
            sched, shapes, requests=requests, rate_hz=rate,
            classes=classes)
        slo_snap = sched.slo.snapshot()
        trace_snap = sched.trace_summary.snapshot()
        sched.stop(drain=True)
        tail = getattr(tele, "events", [])[mark:]
        labels = ("eval_step", "rung_step") if ladder else ("eval_step",)
        serve_compiles = [e for e in tail if e["kind"] == "compile"
                          and e.get("label") in labels]
        compile_s = sum(e["seconds"] for e in serve_compiles)
        steady = max(report["wall_s"] - compile_s, 1e-9)
        return {
            "quant": session.quant,
            "completed": report["completed"],
            "rejected": report["rejected"],
            "errors": report["errors"],
            "wall_s": report["wall_s"],
            "pairs_per_sec": report["pairs_per_sec"],
            "pairs_per_sec_steady": round(report["completed"] / steady, 3),
            "p50_ms": report["p50_ms"],
            "p99_ms": report["p99_ms"],
            "spans_ms": report["spans_ms"],
            # per-class SLO attainment over the stream + the slowest-decile
            # critical-path breakdown (queue vs batch-formation vs device)
            "slo": {(k or "default"): {
                "target_ms": s["target_ms"],
                "attainment": s["attainment"],
                "burn_rate": s["burn_rate"],
            } for k, s in slo_snap.items()},
            "classes": {(k or "default"): c
                        for k, c in trace_snap["classes"].items()},
            "tail": trace_snap["tail"],
            # zero expected in every phase: partial batches ride the full
            # batch's compiled program, so serving never compiles
            "serve_compiles": len(serve_compiles),
            "warm_pool": {
                "compiles": sum(o["compiles"] for o in outcomes),
                "aot_hits": sum(o["aot_hits"] for o in outcomes),
                "aot_saves": sum(o["aot_saves"] for o in outcomes),
                "seconds": round(warm_s, 3),
            },
        }

    result = {
        "metric": "serve-throughput-mixed-shapes",
        "backend": jax.default_backend(),
        "shapes": [f"{h}x{w}" for h, w in shapes],
        "buckets": [f"{h}x{w}" for h, w in bucket_sizes],
        "batch": batch, "requests": requests, "rate_hz": rate,
        "wire": wire_name,
    }
    budget_s = float(os.environ.get("BENCH_SERVE_BUDGET_S", "900"))
    t_start = time.monotonic()

    # phase 1: cold replica, no AOT store — at most one compile per bucket
    programs.disable_aot()
    programs.reset()
    evaluation._EVAL_FN_CACHE.clear()
    result["cold"] = run_phase()
    _emit(result)

    # phases 2+3 replay the compile work against a fresh AOT store; skip
    # explicitly when the cold phase already ate the budget rather than
    # letting an external timeout kill the run (BENCH rc=124 discipline)
    elapsed = time.monotonic() - t_start
    if 2.5 * elapsed > budget_s:
        result["prebuild_skipped"] = f"budget ({elapsed:.0f}s elapsed)"
        print(f"SKIPPED prebuild/warm-replica: budget "
              f"({elapsed:.0f}s of {budget_s:.0f}s used)", flush=True)
        _emit(result)
        return result

    tmp = tempfile.mkdtemp(prefix="bench-serve-aot-")
    try:
        # phase 2: prebuild — compile + AOT-export every triple
        programs.enable_aot(tmp)
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        spec = models.load(model_cfg)
        session = serve.ServeSession(
            spec, minput.ShapeBuckets(bucket_sizes), wire=wire,
            batch_size=batch)
        t0 = time.perf_counter()
        outcomes = session.warm_pool()
        result["prebuild"] = {
            "triples": len(outcomes),
            "compiles": sum(o["compiles"] for o in outcomes),
            "aot_saves": sum(o["aot_saves"] for o in outcomes),
            "seconds": round(time.perf_counter() - t0, 3),
        }
        _emit(result)

        # phase 3: fresh replica against the exported store — prepared and
        # serving the full stream with zero compiles
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        result["warm_replica"] = run_phase()
        result["zero_compile_serve"] = (
            result["warm_replica"]["warm_pool"]["compiles"] == 0
            and result["warm_replica"]["serve_compiles"] == 0)
        _emit(result)
    finally:
        programs.disable_aot()
        shutil.rmtree(tmp, ignore_errors=True)

    # phase 4 (budget permitting): the quantized fast class — a fresh
    # ladder'd replica on the quant matching tier (BENCH_SERVE_QUANT,
    # default u8; 'off' skips), streaming fast-class requests — the
    # class the tier exists for. Every phase row carries a ``quant``
    # field; only this one is non-null.
    from raft_meets_dicl_tpu.ops import quant as quant_ops

    qmode = quant_ops.normalize_mode(
        os.environ.get("BENCH_SERVE_QUANT", "u8"))
    elapsed = time.monotonic() - t_start
    if qmode is not None:
        if elapsed * 4 / 3 > budget_s:
            result["quant_fast_skipped"] = (
                f"budget ({elapsed:.0f}s elapsed)")
            print(f"SKIPPED quant-fast phase: budget "
                  f"({elapsed:.0f}s of {budget_s:.0f}s used)", flush=True)
        else:
            programs.reset()
            evaluation._EVAL_FN_CACHE.clear()
            result["quant_fast"] = run_phase(
                quant=qmode,
                ladder=serve.LadderSpec(
                    rungs=(iters, 2 * iters, 3 * iters)),
                classes=["fast"])
        _emit(result)

    # phase 5 (budget permitting): the serving fleet (PR 20) — two video
    # replicas behind the router, a skewed bucket mix plus one sticky
    # stream, and the kill/rejoin chaos drill: a replica is hard-killed
    # mid-stream, every affected request ends in a result or a *typed*
    # shed, the stream pays at most one cold frame, and the rejoining
    # replica boots against the published AOT store with zero compiles.
    elapsed = time.monotonic() - t_start
    if elapsed * 2 > budget_s:
        result["fleet_skipped"] = f"budget ({elapsed:.0f}s elapsed)"
        print(f"SKIPPED fleet phase: budget "
              f"({elapsed:.0f}s of {budget_s:.0f}s used)", flush=True)
        _emit(result)
        return result

    from raft_meets_dicl_tpu import fleet as fleet_mod
    from raft_meets_dicl_tpu.serve.observe import Observer

    store = tempfile.mkdtemp(prefix="bench-serve-fleet-aot-")
    replicas = {}

    def boot_replica(index):
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        spec = models.load(model_cfg)
        session = serve.ServeSession(
            spec, minput.ShapeBuckets(bucket_sizes), wire=wire,
            batch_size=batch, video=True)
        outcomes = session.warm_pool()
        programs.publish(store)
        sched = serve.Scheduler(session, max_wait_ms=20.0,
                                queue_limit=64).start()
        obs = Observer(session, sched)
        server = fleet_mod.serve_replica(session, sched, obs, 0,
                                         index=index)
        return {"session": session, "scheduler": sched, "server": server,
                "compiles": sum(o["compiles"] for o in outcomes),
                "aot_hits": sum(o["aot_hits"] for o in outcomes)}

    try:
        programs.enable_aot(store)
        codec = fleet_mod.EdgeCodec(
            minput.ShapeBuckets(bucket_sizes), wire=wire)
        router = fleet_mod.Router(codec, retries=2)
        boot_compiles = {}
        for i in range(2):
            replicas[i] = boot_replica(i)
            boot_compiles[f"replica-{i}"] = replicas[i]["compiles"]
            router.add_replica(f"replica-{i}", replicas[i]["server"].url)

        def kill(owner):
            index = int(owner.rsplit("-", 1)[1]) if owner else 0
            name = f"replica-{index}"
            replicas[index]["server"].close()
            replicas[index]["scheduler"].stop(drain=False)
            router.mark_down(name, reason="drill kill")

            def rejoin():
                replicas[index] = boot_replica(index)
                router.add_replica(name, replicas[index]["server"].url)

            threading.Thread(target=rejoin, daemon=True).start()
            return name

        frames = int(os.environ.get("BENCH_FLEET_FRAMES", "16"))
        drill_report = fleet_mod.run_drill(
            router, kill, bucket_sizes, frames=frames,
            kill_after=frames // 3, background_per_frame=2,
            rejoin_wait_s=max(60.0, budget_s - (time.monotonic()
                                                - t_start)))
        router.stop()
        result["fleet"] = {
            "replicas": 2,
            "boot_compiles": boot_compiles,
            "drill": drill_report,
            "zero_compile_rejoin":
                drill_report["rejoin_compiles"] == 0,
        }
    finally:
        for rep in replicas.values():
            try:
                rep["server"].close()
                rep["scheduler"].stop(drain=False)
            except Exception:
                pass
        programs.disable_aot()
        shutil.rmtree(store, ignore_errors=True)
    _emit(result)
    return result


def _bench_ladder():
    """Iteration-ladder frontier (``BENCH_LADDER=1``): EPE-vs-latency
    across fixed recurrence budgets plus the adaptive policy, per model
    family.

    Synthetic constant-shift pairs (img2 is img1 rolled by a known
    offset) give an exact ground-truth flow, so EPE is measurable without
    a dataset. For each family: every fixed rung (4/8/12 iterations) is
    one compiled rung program timed over the eval set; the adaptive
    policy starts at the base rung and escalates through continuation
    programs while the batch's flow-delta norm exceeds a threshold.

    The threshold is *calibrated from the measurement itself*: at random
    init the delta signal never shrinks (untrained GRU updates don't
    converge), so a fixed production threshold would escalate every
    batch. Calibrating to an upper quantile (``BENCH_LADDER_PCTL``,
    default 90) of the measured base-rung deltas emulates the converged-
    model operating point — most requests stop at the base rung, the
    stragglers pay for continuation rungs — which is the regime the
    ladder is built for. ``adaptive.vs_full`` reports the latency ratio
    and EPE regression against the monolithic full budget — the
    acceptance frontier. One cumulative JSON line per family; consumers
    read the last.

    ``BENCH_LADDER_QUANT`` (default ``u8,i8``) appends quantized base
    rungs to each family's frontier — the fast class's serving point on
    the u8/i8 matching tier (``ops.quant``) — with the masked-metric EPE
    delta against the full-precision base rung, p50/p99 latency, and the
    correlation-volume bytes per step at each width. Every frontier row
    carries a ``quant`` field (``null`` = full precision)."""
    from raft_meets_dicl_tpu import evaluation, models
    from raft_meets_dicl_tpu.metrics import functional as mfunc
    from raft_meets_dicl_tpu.ops import quant as quant_ops

    cpu = jax.default_backend() == "cpu"
    rungs = tuple(int(r) for r in
                  os.environ.get("BENCH_LADDER_RUNGS", "4,8,12").split(","))
    pctl = float(os.environ.get("BENCH_LADDER_PCTL", "90"))
    if cpu:
        h, w, batch, n_batches = 64, 96, 2, 8
        tiny = {"corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
                "context-channels": 16, "recurrent-channels": 16}
        families = [
            ("raft", {"type": "raft/baseline", "parameters": tiny}),
            ("raft_fs", {"type": "raft/fs", "parameters": tiny}),
            ("raft_dicl_sl", {"type": "raft+dicl/sl", "parameters": {
                "corr-radius": 2, "corr-channels": 16,
                "context-channels": 16, "recurrent-channels": 16}}),
        ]
    else:
        h, w, batch, n_batches = 384, 704, 2, 8
        families = [
            ("raft", {"type": "raft/baseline",
                      "parameters": {"mixed-precision": True}}),
            ("raft_fs", {"type": "raft/fs",
                         "parameters": {"mixed-precision": True}}),
            ("raft_dicl_sl", {"type": "raft+dicl/sl",
                              "parameters": {"mixed-precision": True}}),
        ]

    budget_s = float(os.environ.get("BENCH_LADDER_BUDGET_S", "900"))
    t_start = time.monotonic()
    increments = tuple(b - a for a, b in zip(rungs, rungs[1:]))

    # constant-shift ground truth: a different (dy, dx) per batch so the
    # adaptive policy sees per-batch variation
    shifts = [(2, 3), (1, -2), (-2, 1), (3, 2), (-1, -3), (2, -1),
              (1, 1), (-3, 2)]
    rng = np.random.RandomState(7)
    batches = []
    for i in range(n_batches):
        dy, dx = shifts[i % len(shifts)]
        i1 = rng.rand(batch, h, w, 3).astype(np.float32)
        i2 = np.roll(i1, (dy, dx), axis=(1, 2))
        gt = np.zeros((batch, h, w, 2), np.float32)
        gt[..., 0] = dx
        gt[..., 1] = dy
        batches.append((jnp.asarray(i1), jnp.asarray(i2), gt))

    def epe(flow, gt):
        d = np.asarray(flow, np.float32) - gt
        return float(np.mean(np.sqrt(np.sum(d * d, axis=-1))))

    def volume_bytes(levels, bytes_per_elem):
        # all-pairs pyramid at 1/8 feature resolution: level l is
        # (B, h8, w8, h8/2^l, w8/2^l); for raft_fs this is the upper
        # bound covering the materialized (non-windowed) suffix
        h8, w8 = h // 8, w // 8
        elems = sum(batch * h8 * w8 * (h8 >> l) * (w8 >> l)
                    for l in range(levels))
        return elems * bytes_per_elem

    result = {"metric": "ladder-frontier", "rungs": list(rungs),
              "shape": f"{batch}x{h}x{w}", "families": {}}
    for name, model_cfg in families:
        elapsed = time.monotonic() - t_start
        if result["families"] and elapsed > budget_s * 0.8:
            result["families"][name] = {
                "skipped": f"budget ({elapsed:.0f}s elapsed)"}
            _emit(result)
            continue
        spec = models.load({
            "name": name, "id": f"bench-ladder-{name}",
            "model": model_cfg, "loss": {"type": "raft/sequence"},
            "input": {"padding": {"type": "modulo", "mode": "zeros",
                                  "size": [8, 8]}}})
        model = spec.model
        variables = model.init(jax.random.PRNGKey(0), batches[0][0],
                               batches[0][1], iterations=1)

        progs = {}
        for k in rungs:
            progs[(k, False)] = evaluation.make_rung_fn(
                model, k, model_id=spec.id)
        for inc in sorted(set(increments)):
            progs[(inc, True)] = evaluation.make_rung_fn(
                model, inc, cont=True, model_id=spec.id)

        fam = {"frontier": [], "adaptive": {}}

        # fixed budgets: one program each, warmed then timed
        base_deltas = []
        for k in rungs:
            step = progs[(k, False)]
            flow, st = step(variables, *batches[0][:2])
            jax.block_until_ready(flow)
            times, errs = [], []
            for i1, i2, gt in batches:
                t0 = time.perf_counter()
                flow, st = step(variables, i1, i2)
                jax.block_until_ready(flow)
                times.append(time.perf_counter() - t0)
                errs.append(epe(flow, gt))
                if k == rungs[0]:
                    base_deltas.append(float(np.max(np.asarray(st["delta"]))))
            fam["frontier"].append({
                "iterations": k, "quant": None,
                "epe": round(sum(errs) / len(errs), 4),
                "mean_ms": round(1e3 * sum(times) / len(times), 3)})

        # quantized matching tier: the base rung — the fast class's
        # serving point — re-registered per mode with u8/i8 volumes
        # dequantized in-register by the lookup. EPE via the masked
        # metric (all-valid synthetic mask: the same number the
        # acceptance gate reads); p50/p99 because the tier exists for
        # latency-critical classes. The dicl families have no quant
        # path, so they report full-precision rows only.
        qmodes = [quant_ops.normalize_mode(m) for m in
                  os.environ.get("BENCH_LADDER_QUANT", "u8,i8").split(",")
                  if m.strip()]
        if not model_cfg["type"].startswith("raft/"):
            qmodes = []
        params = model_cfg.get("parameters", {})
        full_itemsize = 2 if params.get("mixed-precision") else 4
        levels = params.get("corr-levels", 4)
        base_epe = fam["frontier"][0]["epe"]
        for mode in [m for m in qmodes if m is not None]:
            qstep = evaluation.make_rung_fn(model, rungs[0],
                                            model_id=spec.id, quant=mode)
            flow, _ = qstep(variables, *batches[0][:2])
            jax.block_until_ready(flow)
            valid = jnp.ones((batch, h, w), bool)
            times, errs = [], []
            for i1, i2, gt in batches:
                t0 = time.perf_counter()
                flow, _ = qstep(variables, i1, i2)
                jax.block_until_ready(flow)
                times.append(time.perf_counter() - t0)
                errs.append(float(np.mean(np.asarray(
                    mfunc.end_point_error(flow, jnp.asarray(gt),
                                          valid)["mean"]))))
            ms = [1e3 * t for t in times]
            q_epe = sum(errs) / len(errs)
            fam["frontier"].append({
                "iterations": rungs[0], "quant": mode,
                "epe": round(q_epe, 4),
                "epe_delta_vs_full_precision": round(q_epe - base_epe, 4),
                "mean_ms": round(sum(ms) / len(ms), 3),
                "p50_ms": round(float(np.percentile(ms, 50)), 3),
                "p99_ms": round(float(np.percentile(ms, 99)), 3),
                "volume_bytes_per_step": volume_bytes(levels, 1),
                "volume_bytes_full_precision": volume_bytes(
                    levels, full_itemsize)})

        # adaptive: threshold at an upper quantile of the base-rung
        # deltas (see docstring — emulates the converged-model regime
        # where only straggler batches escalate)
        threshold = float(np.percentile(base_deltas, pctl))
        step0 = progs[(rungs[0], False)]
        for inc in sorted(set(increments)):
            s = progs[(inc, True)]
            flow, st = step0(variables, *batches[0][:2])
            flow, st = s(variables, *batches[0][:2], st["flow"],
                         st["hidden"])
            jax.block_until_ready(flow)
        times, errs, iters_run = [], [], []
        for i1, i2, gt in batches:
            t0 = time.perf_counter()
            flow, st = step0(variables, i1, i2)
            executed = rungs[0]
            for inc in increments:
                worst = float(np.max(np.asarray(st["delta"])))
                if worst <= threshold:
                    break
                flow, st = progs[(inc, True)](variables, i1, i2,
                                              st["flow"], st["hidden"])
                executed += inc
            jax.block_until_ready(flow)
            times.append(time.perf_counter() - t0)
            errs.append(epe(flow, gt))
            iters_run.append(executed)
        full = fam["frontier"][-1]
        adaptive_ms = 1e3 * sum(times) / len(times)
        adaptive_epe = sum(errs) / len(errs)
        fam["adaptive"] = {
            "quant": None,
            "threshold": round(threshold, 4),
            "epe": round(adaptive_epe, 4),
            "mean_ms": round(adaptive_ms, 3),
            "mean_iterations": round(sum(iters_run) / len(iters_run), 2),
            "vs_full": {
                "latency_ratio": round(adaptive_ms / full["mean_ms"], 4),
                "epe_regression": round(
                    (adaptive_epe - full["epe"]) / max(full["epe"], 1e-9),
                    4)},
        }
        result["families"][name] = fam
        _emit(result)


def _bench_video():
    """Streaming-video warm-start benchmark (``BENCH_VIDEO=1``): frames/s
    and EPE at fixed quality, cold vs warm, on synthetic constant-motion
    sequences.

    Each sequence drifts a random texture by a fixed (dy, dx) per frame
    (np.roll, exact ground truth). The cold arm runs every frame through
    the monolithic full-budget rung — the fixed-quality baseline the
    warm arm must match. The warm arm carries the previous frame's flow
    through the registered warm-start program at the bottom rung and
    escalates by the ladder's delta policy; the acceptance claim is that
    it reaches the cold arm's EPE with fewer mean iterations per frame
    (a frames/s uplift). The escalation threshold is calibrated like
    BENCH_LADDER's (upper ``BENCH_VIDEO_PCTL`` quantile of warm-entry
    deltas — random-init deltas never shrink, see _bench_ladder).

    A fw/bw occlusion-product measurement rides along: the doubled-batch
    dispatch's cost per frame plus the resulting occlusion ratio (~0 on
    constant motion away from frame edges). ``BENCH_VIDEO_DATA`` names a
    Sintel-layout frame directory to run instead of one synthetic
    sequence (no ground truth there, EPE omitted). One cumulative JSON
    line per stage; consumers read the last."""
    from raft_meets_dicl_tpu import models
    from raft_meets_dicl_tpu.serve.ladder import LadderSpec
    from raft_meets_dicl_tpu.video import (SequenceRunner, fw_bw_flows,
                                           fw_bw_products_batch)

    cpu = jax.default_backend() == "cpu"
    rungs = tuple(int(r) for r in
                  os.environ.get("BENCH_VIDEO_RUNGS", "4,8,12").split(","))
    pctl = float(os.environ.get("BENCH_VIDEO_PCTL", "90"))
    n_frames = int(os.environ.get("BENCH_VIDEO_FRAMES", "8"))
    budget_s = float(os.environ.get("BENCH_VIDEO_BUDGET_S", "900"))
    t_start = time.monotonic()
    if cpu:
        h, w, batch = 64, 96, 1
        model_cfg = {"type": "raft/baseline", "parameters": {
            "corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
            "context-channels": 16, "recurrent-channels": 16}}
    else:
        h, w, batch = 384, 704, 1
        model_cfg = {"type": "raft/baseline",
                     "parameters": {"mixed-precision": True}}

    spec = models.load({
        "name": "bench-video", "id": "bench-video",
        "model": model_cfg, "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}}})
    model = spec.model

    # synthetic constant-motion sequences: exact per-pair ground truth
    motions = [(2, 3), (1, -2), (-2, 1)]
    rng = np.random.RandomState(7)
    sequences = []
    for dy, dx in motions:
        base = rng.rand(batch, h, w, 3).astype(np.float32)
        frames = [np.roll(base, (t * dy, t * dx), axis=(1, 2))
                  for t in range(n_frames)]
        gt = np.zeros((batch, h, w, 2), np.float32)
        gt[..., 0] = dx
        gt[..., 1] = dy
        sequences.append((frames, [gt] * (n_frames - 1)))

    # plus one layered-scene sequence from the synthetic scenario
    # generator (PR 19): coherent per-layer affine motion with exact
    # per-pair dense flow — the warm-start signal a roll-drift sequence
    # can't probe (flow varies across the frame and over time)
    from raft_meets_dicl_tpu.data import synth as dsynth

    imgs, flows, _ = dsynth.render_sequence(
        jax.random.PRNGKey(19), (h, w), frames=n_frames, motion=3.0)
    imgs = np.repeat(np.asarray(imgs)[:, None], batch, axis=1)
    flows = np.repeat(np.asarray(flows)[:, None], batch, axis=1)
    sequences.append(([imgs[t] for t in range(n_frames)],
                      [flows[t] for t in range(n_frames - 1)]))

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.asarray(sequences[0][0][0]),
                           jnp.asarray(sequences[0][0][1]), iterations=1)

    # calibration pass: warm frames with escalation disabled, collect the
    # warm-entry delta signal the threshold quantile pins
    cal = SequenceRunner(
        model, variables, model_id=spec.id,
        ladder=LadderSpec(rungs=rungs, threshold=float("inf")))
    cal_run = cal.run(sequences[0][0], keep_flows=False)
    deltas = [float(np.max(np.asarray(f.carry["delta"])))
              for f in cal_run.frames if f.warm]
    threshold = float(np.percentile(deltas, pctl))

    runner = SequenceRunner(
        model, variables, model_id=spec.id,
        ladder=LadderSpec(rungs=rungs, threshold=threshold))

    # untimed warm-up: a tight-threshold pass escalates through every
    # continuation rung, so all programs either arm can touch are
    # compiled before the measured passes (same registry, shared
    # programs) — frames/s then measures serving, not compilation
    warmup = SequenceRunner(
        model, variables, model_id=spec.id,
        ladder=LadderSpec(rungs=rungs, threshold=1e-12))
    warmup.run(sequences[0][0][:3], keep_flows=False)

    result = {"metric": "video-warmstart", "rungs": list(rungs),
              "shape": f"{batch}x{h}x{w}", "frames": n_frames,
              "sequences": len(sequences),
              "threshold": round(threshold, 4), "arms": {}}

    def run_arm(warm):
        epes, its, fps, warm_frames = [], [], [], 0
        for frames, targets in sequences:
            run = runner.run(frames, targets=targets, warm=warm,
                             keep_flows=False)
            epes.append(run.mean_epe())
            its.append(run.mean_iterations())
            fps.append(run.frames_per_sec())
            warm_frames += run.warm_frames()
        return {
            "epe": round(sum(epes) / len(epes), 4),
            "mean_iterations": round(sum(its) / len(its), 2),
            "frames_per_sec": round(sum(fps) / len(fps), 3),
            "warm_frames": warm_frames,
        }

    result["arms"]["cold"] = run_arm(False)
    _emit(result)
    result["arms"]["warm"] = run_arm(True)
    cold, warmed = result["arms"]["cold"], result["arms"]["warm"]
    result["uplift"] = {
        "frames_per_sec_ratio": round(
            warmed["frames_per_sec"] / max(cold["frames_per_sec"], 1e-9),
            4),
        "iterations_ratio": round(
            warmed["mean_iterations"] / max(cold["mean_iterations"], 1e-9),
            4),
        "epe_regression": round(
            (warmed["epe"] - cold["epe"]) / max(cold["epe"], 1e-9), 4),
    }
    _emit(result)

    # fw/bw products: one doubled-batch dispatch on the full rung + the
    # host-side occlusion/confidence products
    if time.monotonic() - t_start < budget_s * 0.9:
        full = runner._full
        i1 = jnp.asarray(sequences[0][0][0])
        i2 = jnp.asarray(sequences[0][0][1])
        fw, bw = fw_bw_flows(full, variables, i1, i2)  # warm the shape
        jax.block_until_ready(fw)
        t0 = time.perf_counter()
        fw, bw = fw_bw_flows(full, variables, i1, i2)
        jax.block_until_ready(fw)
        dispatch_ms = 1e3 * (time.perf_counter() - t0)
        occ, conf = fw_bw_products_batch(np.asarray(fw), np.asarray(bw))
        result["fwbw"] = {
            "doubled_batch_ms": round(dispatch_ms, 3),
            "occlusion_ratio": round(float(occ.mean()), 5),
            "confidence_mean": round(float(conf.mean()), 5),
        }
        _emit(result)

    # optional Sintel-layout sequence (a directory of ordered frames);
    # no ground truth — the warm arm's iteration/fps accounting only
    data_dir = os.environ.get("BENCH_VIDEO_DATA")
    if data_dir:
        import glob

        import cv2

        paths = sorted(
            glob.glob(os.path.join(data_dir, "*.png"))
            + glob.glob(os.path.join(data_dir, "*.jpg")))[:n_frames]
        if len(paths) >= 2:
            imgs = []
            for p in paths:
                img = cv2.imread(p)[:, :, ::-1].astype(np.float32) / 255.0
                hh = img.shape[0] - img.shape[0] % 8
                ww = img.shape[1] - img.shape[1] % 8
                imgs.append(img[None, :hh, :ww])
            run = runner.run(imgs, keep_flows=False)
            result["sintel"] = {
                "frames": len(run.frames),
                "mean_iterations": round(run.mean_iterations(), 2),
                "frames_per_sec": round(run.frames_per_sec(), 3),
                "warm_frames": run.warm_frames(),
            }
        else:
            result["sintel"] = {"skipped": f"no frames in '{data_dir}'"}
        _emit(result)


def _bench_dicl():
    """Matching-phase breakdown (``BENCH_DICL=1``): window-sample ms (XLA
    gather vs fused Pallas sampler) and matching-net ms (per-level loop vs
    level-batched) at the ml hybrid's 1/8-resolution matching shape, plus
    the per-iteration matching-volume bytes each path moves. One JSON line
    per measurement group (cumulative; consumers read the last line)."""
    import jax
    import jax.numpy as jnp

    from raft_meets_dicl_tpu.models.common.corr.common import sample_window
    from raft_meets_dicl_tpu.models.common.grid import coordinate_grid
    from raft_meets_dicl_tpu.models.impls.raft_dicl_ml import (
        MlCorrelationModule,
    )
    from raft_meets_dicl_tpu.ops.pallas import sample_window_fused

    cpu = jax.default_backend() == "cpu"
    if cpu:
        batch, height, width, c, levels, radius, reps = 1, 64, 128, 8, 2, 2, 3
    else:
        batch = int(os.environ.get("BENCH_BATCH", "6"))
        height = int(os.environ.get("BENCH_HEIGHT", "384"))
        width = int(os.environ.get("BENCH_WIDTH", "704"))
        c, levels, radius, reps = 32, 4, 4, 10
    hc, wc = height // 8, width // 8

    rng = np.random.RandomState(0)
    fmap1 = tuple(jnp.asarray(rng.randn(batch, hc, wc, c), jnp.float32)
                  for _ in range(levels))
    fmap2 = tuple(
        jnp.asarray(rng.randn(batch, hc // 2 ** i, wc // 2 ** i, c),
                    jnp.float32)
        for i in range(levels))
    coords = coordinate_grid(batch, hc, wc) + jnp.asarray(
        rng.randn(batch, hc, wc, 2) * 2, jnp.float32)

    def timed(fn, *args):
        f = jax.jit(fn)
        float(f(*args))  # compile + sync (value transfer, see _measure)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        float(out)
        return round((time.perf_counter() - t0) / reps * 1e3, 3)

    result = {
        "metric": "dicl-matching-breakdown",
        "batch": batch, "height": height, "width": width,
        "levels": levels, "radius": radius, "channels": c,
        "backend": jax.default_backend(),
    }

    def sample_all(sampler, f2s):
        return sum(
            jnp.sum(sampler(f2, coords / 2 ** i, radius))
            for i, f2 in enumerate(f2s))

    def sample_all_grad(sampler, f2s):
        return sum(jnp.sum(jnp.abs(g)) for g in jax.grad(
            lambda fs: sample_all(sampler, fs))(f2s))

    result["window_sample_ms"] = {
        "xla": timed(lambda fs: sample_all(sample_window, fs), fmap2),
        "fused": timed(lambda fs: sample_all(sample_window_fused, fs), fmap2),
        "xla_fwd_bwd": timed(
            lambda fs: sample_all_grad(sample_window, fs), fmap2),
        "fused_fwd_bwd": timed(
            lambda fs: sample_all_grad(sample_window_fused, fs), fmap2),
    }
    _emit(result)

    # matching nets: reference per-level loop vs the level-batched call,
    # on identical parameters (bf16 matching like the mixed policy)
    from raft_meets_dicl_tpu import telemetry
    tele = telemetry.get()
    for share in (False, True):
        m = MlCorrelationModule(feature_dim=c, levels=levels, radius=radius,
                                share=share, dtype=jnp.bfloat16)
        v = m.init(jax.random.PRNGKey(0), fmap1, fmap2, coords)

        def fwd(v, fast, m=m):
            return jnp.sum(jnp.abs(m.apply(
                v, fmap1, fmap2, coords, train=True, frozen_bn=True,
                fast=fast)))

        def fwd_bwd(v, fast, m=m):
            return jax.grad(lambda p: fwd({**v, "params": p}, fast))(
                v["params"])["MatchingNet_0"]["Conv_0"]["bias"].sum()

        key = "shared" if share else "per_level_params"
        result[f"matching_net_ms_{key}"] = {
            "loop": timed(lambda vv: fwd(vv, False), v),
            "batched": timed(lambda vv: fwd(vv, True), v),
            "loop_fwd_bwd": timed(lambda vv: fwd_bwd(vv, False), v),
            "batched_fwd_bwd": timed(lambda vv: fwd_bwd(vv, True), v),
        }
        _emit(result)

    # per-iteration matching-volume bytes (bf16 fast path vs f32 stacked
    # reference): window + f1 in matching dtype vs the 2C stacked volume
    win = batch * (2 * radius + 1) ** 2 * hc * wc * c
    f1b = batch * hc * wc * c
    result["matching_volume_bytes"] = {
        "fast_bf16_unstacked": levels * (win + f1b) * 2,
        "reference_f32_stacked": levels * 2 * win * 4,
    }
    if tele.enabled:
        result["telemetry_events"] = tele.counts()
    _emit(result)
    return result


def _bench_spmd():
    """SPMD scale-out benchmark (``BENCH_SPMD=1``): step time and
    per-chip param/opt-state bytes across mesh shapes on the 8-device
    virtual CPU topology — the replicated 1-D baseline ``(8,1)`` against
    partitioned ``(4,2)`` / ``(2,4)`` meshes (params + Adam moments
    sharded over ``model`` per parallel.partition's rules), plus in-step
    gradient accumulation (``accumulate=2``). Re-execs itself onto a
    virtual 8-device CPU backend when the current backend is smaller
    (same trick as ``__graft_entry__.dryrun_multichip``). One cumulative
    JSON line per measurement; consumers read the last."""
    if jax.device_count() < 8:
        import re
        import subprocess
        import sys

        if os.environ.get("_BENCH_SPMD_CHILD"):
            raise RuntimeError(
                f"BENCH_SPMD child still sees {jax.device_count()} devices "
                "— platform forcing failed")

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["_BENCH_SPMD_CHILD"] = "1"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.path.insert(0, {repo!r}); "
            "import bench; bench._bench_spmd()"
        )
        rc = subprocess.run([sys.executable, "-c", code], env=env,
                            cwd=repo).returncode
        if rc != 0:
            raise RuntimeError(f"BENCH_SPMD subprocess failed (rc={rc})")
        return None

    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import parallel

    batch, height, width, iters = 8, 64, 96, 2
    steps = int(os.environ.get("BENCH_STEPS", "3"))
    # elapsed budget: measurements run cheapest-signal-first and later
    # configs are skipped (marked explicitly) rather than letting an
    # external timeout kill the whole run — same discipline as
    # dryrun_multichip's RMD_DRYRUN_BUDGET_S
    budget_s = float(os.environ.get("BENCH_SPMD_BUDGET_S", "420"))
    t_start = time.monotonic()

    spec = models.load({
        "name": "bench-spmd", "id": "bench-spmd",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"}, "input": None,
    })
    model, loss = spec.model, spec.loss

    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, height, width, 3)), jnp.zeros((1, height, width, 3)),
        iterations=1)
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-4))

    def measure(mesh_spec, accumulate=1):
        # fresh fixed-seed data per measurement so the cross-mesh loss
        # comparison is apples to apples
        rng = np.random.RandomState(0)
        mesh = parallel.make_mesh(mesh_spec)
        part = parallel.Partitioner(mesh)
        state = part.shard_state(parallel.TrainState.create(variables, tx))
        step = parallel.make_train_step(
            model, loss, tx, mesh=mesh, model_args={"iterations": iters},
            state_sharding=part.state_shardings(state),
            accumulate=accumulate, donate=False)

        b = batch * accumulate
        img1 = jnp.asarray(rng.rand(b, height, width, 3), jnp.float32)
        img2 = jnp.asarray(rng.rand(b, height, width, 3), jnp.float32)
        flow = jnp.asarray(rng.randn(b, height, width, 2), jnp.float32)
        valid = jnp.ones((b, height, width), bool)
        bt = parallel.shard_batch((img1, img2, flow, valid), mesh)

        t0 = time.perf_counter()
        state, aux = step(state, *bt)
        float(aux["loss"])
        warm = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(steps):
            state, aux = step(state, *bt)
        loss_val = float(aux["loss"])
        dt = (time.perf_counter() - t0) / steps

        rep = part.report(state)
        return {
            "mesh": rep["mesh"],
            "accumulate": accumulate,
            "loss": round(loss_val, 5),
            "step_ms": round(dt * 1e3, 2),
            "pairs_per_sec": round(b / dt, 3),
            "warmup_s": round(warm, 2),
            "params_mib_per_chip": round(
                rep["params_bytes_per_chip"] / 2 ** 20, 3),
            "opt_mib_per_chip": round(
                rep["opt_bytes_per_chip"] / 2 ** 20, 3),
            "params_mib_replicated": round(
                rep["params_bytes_replicated"] / 2 ** 20, 3),
            "opt_mib_replicated": round(
                rep["opt_bytes_replicated"] / 2 ** 20, 3),
            "params_sharded_leaves": rep["params_sharded_leaves"],
        }

    result = {
        "metric": "spmd-mesh-shapes",
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "batch": batch, "height": height, "width": width,
        "iterations": iters, "steps": steps,
    }
    slowest = 0.0
    for key, mesh_spec, acc in (("mesh_8x1", (8, 1), 1),
                                ("mesh_4x2", (4, 2), 1),
                                ("mesh_2x4", (2, 4), 1),
                                ("mesh_4x2_accum2", (4, 2), 2)):
        elapsed = time.monotonic() - t_start
        if result and elapsed + 1.5 * max(slowest, 30.0) > budget_s:
            result[f"{key}_skipped"] = f"budget ({elapsed:.0f}s elapsed)"
            _emit(result)
            continue
        t0 = time.monotonic()
        result[key] = measure(mesh_spec, acc)
        slowest = max(slowest, time.monotonic() - t0)
        _emit(result)

    base = result.get("mesh_8x1")
    for key in ("mesh_4x2", "mesh_2x4"):
        m = result.get(key)
        if base is None or m is None:
            continue
        result[f"{key}_hbm_ratio"] = round(
            (m["params_mib_per_chip"] + m["opt_mib_per_chip"])
            / max(base["params_mib_per_chip"] + base["opt_mib_per_chip"],
                  1e-9), 4)
        result[f"{key}_loss_rel_diff"] = round(
            abs(m["loss"] - base["loss"]) / max(abs(base["loss"]), 1e-9), 6)
    _emit(result)
    return result


def _bench_compile_child():
    """One BENCH_COMPILE scenario, in a fresh process (jit caches are
    process-local, so cold/warm can only be compared across processes).

    ``_BENCH_COMPILE_CHILD`` selects the workload (``train`` | ``eval``);
    the parent controls cache state via RMD_NO_COMPILE_CACHE /
    RMD_COMPILE_CACHE / RMD_AOT / RMD_AOT_DIR. Prints one JSON line:
    ``time_to_first_step_s`` is the step-warmup window — program build,
    tracing, compilation or artifact load, first dispatch, sync — i.e.
    exactly the cost the registry/AOT store addresses; ``setup_s``
    (model load + init + data) and ``total_s`` give the full boot for
    context.
    """
    mode = os.environ["_BENCH_COMPILE_CHILD"]

    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import (
        compile as programs, evaluation, parallel, telemetry,
    )
    from raft_meets_dicl_tpu.utils.compcache import enable_persistent_cache

    enable_persistent_cache()
    programs.enable_aot()
    telemetry.activate(telemetry.create())
    # wall-clock ledger from process start: the emitted line's goodput
    # block is the compile-vs-productive split the scenarios compare
    from raft_meets_dicl_tpu.telemetry import goodput
    goodput.activate()

    cpu = jax.default_backend() == "cpu"
    if cpu:
        batch, height, width, iters = 2, 64, 96, 4
        params = {"corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
                  "context-channels": 16, "recurrent-channels": 16}
    else:
        batch = int(os.environ.get("BENCH_BATCH", "6"))
        height = int(os.environ.get("BENCH_HEIGHT", "400"))
        width = int(os.environ.get("BENCH_WIDTH", "720"))
        iters = int(os.environ.get("BENCH_ITERS", "12"))
        params = {"mixed-precision": True}

    spec = models.load({
        "name": "bench-compile", "id": "bench-compile",
        "model": {"type": "raft/baseline", "parameters": params},
        "loss": {"type": "raft/sequence"}, "input": None,
    })
    model, loss = spec.model, spec.loss

    rng = np.random.RandomState(0)
    t_boot = time.perf_counter()
    if mode == "train":
        img = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
        flow = jnp.asarray(rng.randn(batch, height, width, 2), jnp.float32)
        valid = jnp.ones((batch, height, width), bool)
        variables = model.init(jax.random.PRNGKey(0), img[:1], img[:1],
                               iterations=1)
        tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(4e-4))
        state = parallel.TrainState.create(variables, tx)
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        key = programs.ProgramKey(
            kind="train_step", model="bench-compile",
            flags=programs.flag_items(shape=(batch, height, width),
                                      iterations=iters))
        step = parallel.make_train_step(model, loss, tx,
                                        model_args={"iterations": iters},
                                        key=key)
        state, aux = step(state, img, img, flow, valid)
        float(aux["loss"])
        prog = step
    else:
        # bucketed eval: warmup over two bucket shapes + one real batch
        shapes = [(height, width), (height - 8, width - 16)]
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, height, width, 3)), jnp.zeros((1, height, width, 3)),
            iterations=1)
        img = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
        jax.block_until_ready(jax.tree.leaves(variables)[0])
        t0 = time.perf_counter()
        fn = evaluation.make_eval_fn(model, {"iterations": iters},
                                     model_id="bench-compile")
        evaluation.warmup_eval_fn(fn, variables, shapes, batch)
        out = fn(variables, img, img)
        jax.block_until_ready(out[1])
        prog = fn
    t_end = time.perf_counter()
    tts = t_end - t0

    tele = telemetry.get()
    _emit({
        "mode": mode,
        "time_to_first_step_s": round(tts, 3),
        "setup_s": round(t0 - t_boot, 3),
        "total_s": round(t_end - t_boot, 3),
        "compiles": prog.compiles,
        "compile_s": round(prog.compile_seconds, 3),
        "compile_events": tele.counts().get("compile", 0),
        "cache_hits": sum(1 for e in getattr(tele, "events", ())
                          if e["kind"] == "cache" and e["event"] == "hit"),
        "aot_hits": prog.aot_hits,
        "aot_saves": prog.aot_saves,
        "aot_fallbacks": prog.aot_fallbacks,
    })


def _bench_compile():
    """Cold-start benchmark (``BENCH_COMPILE=1``): time-to-first-step for
    the train step and the bucketed eval path under three boot regimes —
    (a) cold (no caches at all), (b) persistent-compile-cache warm
    (tracing + cache lookup, no backend compile), (c) AOT warm
    (deserialized executables, no tracing, zero compiles). Each regime
    runs in a fresh subprocess against a temp cache/program directory; a
    ``populate`` run in between fills both stores. One cumulative JSON
    line per measurement; consumers read the last."""
    import subprocess
    import sys
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="bench-compile-")
    cache_dir = os.path.join(tmp, "cache")
    aot_dir = os.path.join(tmp, "programs")

    def run_child(mode, scenario):
        env = dict(os.environ)
        env.pop("BENCH_COMPILE", None)
        env["_BENCH_COMPILE_CHILD"] = mode
        env["RMD_COMPILE_CACHE"] = cache_dir
        env["RMD_AOT_DIR"] = aot_dir
        if scenario == "cold":
            env["RMD_NO_COMPILE_CACHE"] = "1"
            env["RMD_AOT"] = "0"
        elif scenario == "populate":
            env["RMD_AOT"] = "1"
        elif scenario == "warm_cache":
            env["RMD_AOT"] = "0"
        elif scenario == "aot":
            env["RMD_AOT"] = "1"
        code = (f"import sys; sys.path.insert(0, {repo!r}); "
                "import bench; bench._bench_compile_child()")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              cwd=repo, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"BENCH_COMPILE child ({mode}/{scenario}) failed:\n"
                f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    result = {"metric": "compile-cold-start",
              "backend": jax.default_backend()}
    for mode in ("train", "eval"):
        m = {}
        m["cold"] = run_child(mode, "cold")
        _emit(result | {mode: m})
        run_child(mode, "populate")  # fills compile cache + AOT store
        m["warm_cache"] = run_child(mode, "warm_cache")
        m["aot"] = run_child(mode, "aot")
        cold = m["cold"]["time_to_first_step_s"]
        m["speedup_warm_cache"] = round(
            cold / max(m["warm_cache"]["time_to_first_step_s"], 1e-9), 2)
        m["speedup_aot"] = round(
            cold / max(m["aot"]["time_to_first_step_s"], 1e-9), 2)
        # compile-vs-productive per scenario, read off the child's
        # goodput ledger (one classifier for every bench, rather than
        # this bench's old ad-hoc compile_s/total_s arithmetic)
        for scen in ("cold", "warm_cache", "aot"):
            gp = m[scen].get("goodput")
            if gp and gp.get("total_s"):
                m[scen]["compile_share"] = round(
                    gp["classes_s"].get("compile", 0.0) / gp["total_s"], 4)
        result[mode] = m
        _emit(result)
    return result


def _bench_fault():
    """Fault-tolerance overhead (``BENCH_FAULT=1``): per-step cost of the
    non-finite recovery machinery. Measures the same synthetic training
    step (a) unguarded (policy ``raise``: one isfinite reduce over the
    final flow, as always) and (b) with the skip guard compiled in
    (policies ``skip``/``rollback``: isfinite over the update tree plus
    the conditional state select). Target: within noise. One JSON line;
    consumers read the last."""
    cpu = jax.default_backend() == "cpu"
    if cpu:
        batch, height, width, iters, steps = 2, 64, 96, 4, 3
    else:
        batch = int(os.environ.get("BENCH_BATCH", "6"))
        height = int(os.environ.get("BENCH_HEIGHT", "400"))
        width = int(os.environ.get("BENCH_WIDTH", "720"))
        iters = int(os.environ.get("BENCH_ITERS", "12"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))

    model_cfg = {"type": "raft/baseline",
                 "parameters": {"mixed-precision": not cpu}}
    loss_cfg = {"type": "raft/sequence"}

    result = {
        "metric": "fault-overhead",
        "backend": jax.default_backend(),
        "batch": batch, "height": height, "width": width,
        "iterations": iters, "steps": steps,
    }
    plain, _, psum = _measure(model_cfg, loss_cfg, batch, height, width,
                              {"iterations": iters}, steps)
    result["plain_pairs_per_sec"] = round(plain, 3)
    if psum is not None:
        result["plain_step_ms"] = psum["step_ms_mean"]
    _emit(result)

    guarded, _, gsum = _measure(model_cfg, loss_cfg, batch, height, width,
                                {"iterations": iters}, steps,
                                nonfinite="skip")
    result["guarded_pairs_per_sec"] = round(guarded, 3)
    if gsum is not None:
        result["guarded_step_ms"] = gsum["step_ms_mean"]
    result["overhead_pct"] = round((plain / guarded - 1.0) * 100, 2) \
        if guarded else None
    _emit(result)
    return result


def main():
    if os.environ.get("_BENCH_COMPILE_CHILD"):
        # one cold-start scenario delegated by the BENCH_COMPILE parent
        _bench_compile_child()
        return

    # every BENCH_* mode runs on a goodput ledger from here: telemetry
    # compile/checkpoint/eval events are classified as they are emitted
    # and _emit attaches the breakdown to each JSON line
    from raft_meets_dicl_tpu.telemetry import goodput
    goodput.activate()

    if os.environ.get("BENCH_COMPILE", "0") != "0":
        # cold vs persistent-cache-warm vs AOT-warm time-to-first-step
        _bench_compile()
        return

    if os.environ.get("BENCH_SPMD", "0") != "0":
        # SPMD mesh-shape benchmark: replicated vs partitioned state,
        # per-chip HBM + step time on the 8-device virtual CPU topology
        from raft_meets_dicl_tpu.utils.compcache import (
            enable_persistent_cache,
        )
        enable_persistent_cache()
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_spmd()
        return

    if os.environ.get("BENCH_FAULT", "0") != "0":
        # non-finite guard overhead: unguarded vs skip-guarded train step
        from raft_meets_dicl_tpu.utils.compcache import (
            enable_persistent_cache,
        )
        enable_persistent_cache()
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_fault()
        return

    if os.environ.get("BENCH_INPUT", "0") != "0":
        # input-pipeline-only mode: host-side decode/collate/wire-volume
        # numbers, no device required
        _bench_input()
        return

    if os.environ.get("BENCH_EVAL", "0") != "0":
        # shape-bucketed evaluation: batch-1 per-shape baseline vs the
        # bucketed recompile-free pipeline on a mixed-resolution set.
        # No persistent compile cache here: cold compiles per distinct
        # shape are exactly the cost being measured.
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_eval()
        return

    if os.environ.get("BENCH_SERVE", "0") != "0":
        # serving path: open-loop mixed-resolution load through the
        # continuous-batching scheduler, cold vs AOT-prebuilt replica.
        # No persistent compile cache: the warm-pool/AOT mechanics are
        # exactly the cost being measured.
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_serve()
        return

    if os.environ.get("BENCH_LADDER", "0") != "0":
        # iteration-ladder frontier: EPE vs latency at fixed recurrence
        # budgets plus the adaptive escalation policy. Persistent cache
        # on: program compiles are not the measurement, the per-rung
        # execution times are.
        from raft_meets_dicl_tpu.utils.compcache import (
            enable_persistent_cache,
        )
        enable_persistent_cache()
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_ladder()
        return

    if os.environ.get("BENCH_VIDEO", "0") != "0":
        # streaming-video warm-start: cold vs warm frames/s + EPE on
        # synthetic constant-motion sequences, plus fw/bw products.
        # Persistent cache on: the warm-start claim is about iterations
        # per frame, not compiles.
        from raft_meets_dicl_tpu.utils.compcache import (
            enable_persistent_cache,
        )
        enable_persistent_cache()
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_video()
        return

    if os.environ.get("BENCH_DICL", "0") != "0":
        # matching-phase microbench for the DICL-hybrid fast path
        from raft_meets_dicl_tpu.utils.compcache import (
            enable_persistent_cache,
        )
        enable_persistent_cache()
        from raft_meets_dicl_tpu import telemetry
        telemetry.activate(telemetry.create())
        _bench_dicl()
        return

    # persistent compile cache: cold zoo compiles total ~40 min and have
    # overrun the harness budget (BENCH_r04 rc=124); with a warmed cache
    # the full run is measurement-dominated (~5 min)
    from raft_meets_dicl_tpu.utils.compcache import enable_persistent_cache
    enable_persistent_cache()

    # memory-only telemetry sink: compile/cache events feed the per-model
    # summaries attached to the JSON lines (RMD_TELEMETRY=0 disables and
    # drops the summaries, restoring the bare measurement path)
    from raft_meets_dicl_tpu import telemetry
    telemetry.activate(telemetry.create())

    batch = int(os.environ.get("BENCH_BATCH", "6"))
    height = int(os.environ.get("BENCH_HEIGHT", "400"))
    width = int(os.environ.get("BENCH_WIDTH", "720"))
    iters = int(os.environ.get("BENCH_ITERS", "12"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))

    # elapsed budget for the scenario loop below: the primary metric always
    # runs, then flagship/zoo scenarios are skipped (marked explicitly in
    # the JSON line, SKIPPED printed) once the projected cost would overrun
    # — same discipline as BENCH_SPMD / dryrun_multichip, and the fix for
    # the external-timeout rc=124 runs that lost everything after the kill
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1200"))
    t_start = time.monotonic()
    slowest = [0.0]

    def budget_allows(tag, factor):
        elapsed = time.monotonic() - t_start
        need = factor * max(slowest[0], 30.0)
        if elapsed + need <= budget_s:
            return True
        result[f"{tag}_skipped"] = (
            f"budget ({elapsed:.0f}s elapsed, est {need:.0f}s)")
        print(f"SKIPPED {tag}: budget ({elapsed:.0f}s of {budget_s:.0f}s "
              f"used, est {need:.0f}s)", flush=True)
        _emit(result)
        return False

    if jax.default_backend() == "cpu":
        # CPU fallback (no TPU attached): tiny shapes, still one JSON line
        batch, height, width, iters, steps = 2, 64, 96, 4, 3

    # mixed-precision bf16 is the TPU-native policy (the reference's
    # autocast equivalent). Profiling history at this config:
    # - scalar-gather corr lookup: ~17 s/step; einsum lookup: 0.67 s
    # - convex Up8 hoisted out of the remat'd scan, compact mask layout,
    #   remat policy saving the corr lookups: 0.43 s
    # - fused Pallas softmax+combine Up8 kernel (ops/pallas.py): 0.39 s
    t0 = time.monotonic()
    pairs_per_sec, _, tsum = _measure(
        {"type": "raft/baseline", "parameters": {"mixed-precision": True}},
        {"type": "raft/sequence"},
        batch, height, width, {"iterations": iters}, steps,
    )
    slowest[0] = max(slowest[0], time.monotonic() - t0)

    result = {
        "metric": "train-throughput-raft-things",
        "value": round(pairs_per_sec, 3),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3),
    }
    if tsum is not None:
        result["telemetry"] = tsum

    # publish the primary metric immediately: the flagship measurement
    # below adds a cold ~10 min compile, and a harness timeout must not
    # lose this line (consumers read the LAST json line printed)
    _emit(result)

    if os.environ.get("BENCH_FLAGSHIP", "1") != "0" \
            and budget_allows("ctf_l3", 3.0):
        # the thesis flagship at a Things-like config (pyramid needs
        # multiples of 64) under the bf16 policy; a flagship failure must
        # not lose the main measurement
        try:
            if jax.default_backend() == "cpu":
                fb, fh, fw, fi, fs = 1, 64, 128, (2, 1, 1), 2
            else:
                fb, fh, fw, fi, fs = 6, 384, 704, (4, 3, 3), 5
            t0 = time.monotonic()
            ctf_pairs, _, ctf_tsum = _measure(
                {"type": "raft+dicl/ctf-l3",
                 "parameters": {"mixed-precision": True}},
                {"type": "raft+dicl/mlseq",
                 "arguments": {"alpha": [0.38, 0.6, 1.0]}},
                fb, fh, fw, {"iterations": fi}, fs,
            )
            slowest[0] = max(slowest[0], time.monotonic() - t0)
            result["ctf_l3_pairs_per_sec"] = round(ctf_pairs, 3)
            if ctf_tsum is not None:
                result["ctf_l3_telemetry"] = ctf_tsum
        except Exception as e:  # noqa: BLE001 - report, don't lose the line
            result["ctf_l3_error"] = f"{type(e).__name__}: {str(e)[:120]}"

        _emit(result)

    if os.environ.get("BENCH_ZOO", "1") != "0":
        # one throughput line per model family at its reference training
        # shape, so a perf regression anywhere in the zoo is visible —
        # not just in the headline models. The enriched JSON line reprints
        # after every measurement: a harness timeout keeps what finished.
        cpu = jax.default_backend() == "cpu"
        zoo = [
            # raft/fs: the windowed (no-volume) lookup strategy, bf16
            ("raft_fs", {"type": "raft/fs",
                         "parameters": {"mixed-precision": True}},
             {"type": "raft/sequence"},
             (1, 64, 96, {"iterations": 2}, 2) if cpu else
             (6, 400, 720, {"iterations": 12}, 3)),
            # raft/sl-ctf-l3: single-lookup coarse-to-fine (thesis ablation)
            ("raft_sl_ctf3", {"type": "raft/sl-ctf-l3", "parameters": {}},
             {"type": "raft+dicl/mlseq",
              "arguments": {"gamma": 0.85, "alpha": [0.38, 0.6, 1.0]}},
             (1, 64, 128, {"iterations": (2, 1, 1)}, 2) if cpu else
             (6, 384, 704, {"iterations": (4, 3, 3)}, 3)),
            # raft+dicl/ml: multi-level DICL lookup, single RAFT loop,
            # at the reference Things shape (b6, 384x704, 12 iters)
            ("raft_dicl_ml", {"type": "raft+dicl/ml", "parameters": {}},
             {"type": "raft/sequence"},
             (1, 64, 128, {"iterations": 2}, 2) if cpu else
             (6, 384, 704, {"iterations": 12}, 3)),
            # dicl/baseline: pure DICL coarse-to-fine (GA-Net encoder)
            ("dicl_baseline",
             {"type": "dicl/baseline",
              "parameters": {"displacement-range": {
                  f"level-{lvl}": [3, 3] for lvl in range(2, 7)}}},
             {"type": "dicl/multiscale",
              "arguments": {"weights": [1.0, 0.8, 0.75, 0.6, 0.5,
                                        0.4, 0.5, 0.4, 0.5, 0.4],
                            "ord": 2}},
             (1, 128, 128, {}, 2) if cpu else (6, 384, 768, {}, 3)),
        ]
        # labeled fallback shapes: if a model fails at its reference shape
        # (e.g. a compiler-service crash) the bench still reports a number,
        # and the JSON says explicitly which config produced it (so reduced
        # measurements are never silently comparable to full ones)
        fallbacks = {
            "raft_dicl_ml": [((2, 256, 448, {"iterations": 6}, 3),
                              "reduced:b2/256x448/6-iters")],
        }
        for name, model_cfg, loss_cfg, shape in zoo:
            if not budget_allows(name, 1.5):
                continue
            candidates = [(shape, None)]
            if not cpu:
                candidates += fallbacks.get(name, [])
            for (zb, zh, zw, zargs, zsteps), label in candidates:
                try:
                    t0 = time.monotonic()
                    pairs, _, zsum = _measure(model_cfg, loss_cfg, zb, zh, zw,
                                              zargs, zsteps)
                    slowest[0] = max(slowest[0], time.monotonic() - t0)
                    result[f"{name}_pairs_per_sec"] = round(pairs, 3)
                    if zsum is not None:
                        result[f"{name}_telemetry"] = zsum
                    if label:
                        result[f"{name}_config"] = label
                    result.pop(f"{name}_error", None)
                    break
                except Exception as e:  # noqa: BLE001
                    result[f"{name}_error"] = (
                        f"{type(e).__name__}: {str(e)[:120]}")
            _emit(result)


if __name__ == "__main__":
    main()
