"""Fleet front-end router: dispatch, backpressure, drain, affinity.

The router is the fleet's admission plane. It exposes the same
``submit(...) -> ticket`` surface as the in-process scheduler (so the
loadgen, bench harness and CLI drive a fleet unchanged) plus an HTTP
front-end for real network clients, and routes every request to one of
N replica processes:

- **least-loaded dispatch per (bucket, class)**: each replica tracks
  in-flight counts per lane; the eligible replica with the fewest
  in-flight requests on the request's lane wins (total in-flight breaks
  ties), so a slow replica backs up only its own lanes and a skewed
  bucket/class mix spreads by *load*, not round-robin luck.
- **bounded retry on safe failures**: transport failures that provably
  returned no response (connection refused/reset, replica died
  mid-exchange) and typed replica sheds (429 queue_full, 503 draining)
  re-dispatch to another replica with jittered backoff, at most
  ``RMD_FLEET_RETRIES`` times within the per-request
  ``RMD_FLEET_TIMEOUT_MS`` deadline. Application errors (400/500) are
  deterministic and complete the ticket typed, never retried.
- **typed fleet shed**: when no eligible replica exists the request
  sheds ``replica_unavailable``; when every try shed ``queue_full`` the
  fleet-wide answer is ``queue_full``. Callers see exactly the
  :class:`~..serve.batcher.ServeRejected` contract the single-replica
  scheduler pins.
- **health/drain from the PR-13 plane**: a poll thread reads every
  replica's /healthz (readiness, liveness age, draining) and /statusz
  (per-class SLO burn). Burn above ``RMD_FLEET_BURN_DRAIN`` or a stale
  liveness heartbeat drains the replica: traffic shifts off, sticky
  sessions hand off, the supervisor recycles it.
- **session affinity + handoff**: sticky video clients pin to one
  replica (their carry lives there). On drain the carry snapshot moves
  to the new owner via /sessionz (at most one *handoff* blip, zero cold
  frames when the import validates); on death it is evicted and the
  stream restarts with exactly one cold frame — never a dropped stream.
"""

import json
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import urlparse

from .. import telemetry
from ..serve.batcher import FlowResult, ServeError, ServeRejected
from ..telemetry import metrics as metrics_mod
from ..telemetry import sidecar
from ..utils import env
from . import wire as fwire
from .client import ReplicaClient, ReplicaDown, ReplicaTimeout

# the router's own HTTP surface (front-end, not sidecar);
# graftlint:sidecar-route checks these against README
ROUTES = ("/v1/flow", "/fleetz", "/healthz")

# consecutive health-poll transport failures before a replica is
# declared dead (distinguishes a lost poll from a lost process)
_HEALTH_FAILURES_DOWN = 3
# jittered retry backoff base; doubles per attempt
_RETRY_BACKOFF_S = 0.025


class FleetTicket:
    """Caller handle for one routed request (scheduler-Ticket shaped:
    ``result(timeout)`` returns the FlowResult or raises the typed
    ServeError/ServeRejected)."""

    def __init__(self, rid, client):
        self.rid = rid
        self.client = client
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _complete(self, result=None, error=None):
        self._result = result
        self._error = error
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.rid} still in flight "
                               f"after {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result


class ReplicaState:
    """Router-side view of one replica: health + per-lane load."""

    def __init__(self, name, url):
        self.name = name
        self.url = url
        self.client = ReplicaClient(url)
        self.up = True
        self.ready = True
        self.live = True
        self.draining = False
        self.generation = 0
        self.health_failures = 0
        self.burn = 0.0
        self.inflight = {}  # (bucket, klass) -> count
        self.total_inflight = 0

    def eligible(self):
        return self.up and self.ready and self.live and not self.draining

    def lane_load(self, lane):
        return self.inflight.get(lane, 0)

    def describe(self):
        return {
            "url": self.url, "up": self.up, "ready": self.ready,
            "live": self.live, "draining": self.draining,
            "generation": self.generation,
            "burn": round(self.burn, 3),
            "inflight": self.total_inflight,
        }


class Router:
    """The fleet dispatch plane over N replica processes."""

    def __init__(self, codec, retries=None, timeout_ms=None,
                 burn_drain=None, health_interval_s=None, workers=16,
                 on_recycle=None):
        self.codec = codec
        self.retries = int(retries if retries is not None
                           else env.get_int("RMD_FLEET_RETRIES"))
        timeout_ms = (timeout_ms if timeout_ms is not None
                      else env.get_float("RMD_FLEET_TIMEOUT_MS"))
        self.timeout_s = float(timeout_ms) / 1e3
        self.burn_drain = float(burn_drain if burn_drain is not None
                                else env.get_float("RMD_FLEET_BURN_DRAIN"))
        self.health_interval_s = float(
            health_interval_s if health_interval_s is not None
            else env.get_float("RMD_FLEET_HEALTH_S"))
        # supervisor hook: called with a replica name after drain-handoff
        # completes, so the process can be recycled
        self.on_recycle = on_recycle

        self._replicas = {}
        self._affinity = {}  # sticky client -> replica name
        self._lock = threading.Lock()
        self._rid = 0
        self._pool = ThreadPoolExecutor(max_workers=int(workers),
                                        thread_name_prefix="fleet-route")
        self._health_thread = None
        self._stopping = threading.Event()
        self.sheds = {}   # reason -> count (fleet-level, typed)
        self.retries_done = 0

        reg = metrics_mod.registry()
        self._m_requests = reg.counter(
            "rmd_fleet_requests_total",
            "requests completed per replica", ("replica",))
        self._m_retries = reg.counter(
            "rmd_fleet_retries_total",
            "safe-failure re-dispatches to another replica")
        self._m_shed = reg.counter(
            "rmd_fleet_shed_total",
            "fleet-level typed request sheds", ("reason",))
        self._m_handoffs = reg.counter(
            "rmd_fleet_handoffs_total",
            "sticky sessions moved or evicted on drain/death",
            ("outcome",))
        self._m_drains = reg.counter(
            "rmd_fleet_drains_total",
            "replicas drained by trigger", ("reason",))
        self._m_ready = reg.gauge(
            "rmd_fleet_replicas_ready",
            "replicas currently eligible for dispatch")
        self._m_inflight = reg.gauge(
            "rmd_fleet_inflight", "requests in flight across the fleet")

    # -- membership (supervisor callbacks) -----------------------------------

    def add_replica(self, name, url):
        """(Re)register a replica — fresh state, traffic eligible.

        Idempotent while the replica is up at the same URL (the
        supervisor's boot announce and an explicit registration loop
        may race); a re-add after death/drain bumps the generation."""
        with self._lock:
            prior = self._replicas.get(name)
            if prior is not None and prior.up and not prior.draining \
                    and prior.url == url:
                return prior
            state = ReplicaState(name, url)
            state.generation = prior.generation + 1 if prior else 0
            self._replicas[name] = state
        telemetry.get().emit("fleet", event="replica_up", replica=name,
                             url=url, generation=state.generation)
        self._refresh_ready_gauge()
        return state

    def mark_down(self, name, reason="died"):
        """A replica process is gone: stop routing, evict its sticky
        sessions (the carry died with it — one cold frame per stream)."""
        with self._lock:
            state = self._replicas.get(name)
            if state is None or not state.up:
                return
            state.up = False
            orphans = [c for c, owner in self._affinity.items()
                       if owner == name]
            for c in orphans:
                del self._affinity[c]
        for c in orphans:
            self._m_handoffs.labels(outcome="evicted").inc()
            telemetry.get().emit("fleet", event="handoff", client=c,
                                 source=name, outcome="evicted",
                                 reason=reason)
        telemetry.get().emit("fleet", event="replica_down", replica=name,
                             reason=reason)
        self._refresh_ready_gauge()

    def replicas(self):
        with self._lock:
            return dict(self._replicas)

    def _refresh_ready_gauge(self):
        with self._lock:
            ready = sum(1 for s in self._replicas.values() if s.eligible())
            inflight = sum(s.total_inflight
                           for s in self._replicas.values())
        self._m_ready.set(ready)
        self._m_inflight.set(inflight)
        return ready

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._health_thread = threading.Thread(
            target=self._health_loop, name="fleet-health", daemon=True)
        self._health_thread.start()
        return self

    def stop(self):
        self._stopping.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        self._pool.shutdown(wait=True)

    # -- admission -----------------------------------------------------------

    def submit(self, img1, img2, client="default", klass=None,
               sequence=False, products=False):
        """Scheduler-shaped admission: encode at the edge, dispatch on
        the pool, return a ticket. Payload errors raise synchronously
        (same typed contract as in-process admission); routing failures
        and replica sheds complete the ticket with the typed error."""
        if products:
            raise ServeError(
                "malformed",
                "fw/bw products are not served over the fleet wire")
        e1, e2, bucket, shape = self.codec.encode_pair(img1, img2)
        meta = {
            "bucket": list(bucket),
            "shape": list(shape),
            "dtype": str(e1.dtype),
            "client": client,
            "sequence": bool(sequence),
        }
        if klass is not None:
            meta["klass"] = klass
        return self.submit_wire(meta, fwire.pack_pair(e1, e2))

    def submit_wire(self, meta, body):
        """Admit one already-encoded request (the HTTP front-end path:
        client bytes go to the device untouched)."""
        with self._lock:
            rid = self._rid
            self._rid += 1
        ticket = FleetTicket(rid, str(meta.get("client", "default")))
        self._pool.submit(self._route, ticket, meta, body)
        return ticket

    # -- dispatch ------------------------------------------------------------

    def _lane(self, meta):
        bucket = tuple(meta.get("bucket", ()))
        return (bucket, meta.get("klass") or "")

    def _pick(self, lane, client, sequence, exclude=()):
        """The target replica, honoring sticky affinity then least
        lane load. Returns (state, sticky) or (None, False)."""
        with self._lock:
            if sequence:
                owner = self._affinity.get(client)
                if owner is not None:
                    state = self._replicas.get(owner)
                    if state is not None and state.eligible() \
                            and owner not in exclude:
                        return state, True
            candidates = [s for s in self._replicas.values()
                          if s.eligible() and s.name not in exclude]
            if not candidates:
                # a retry may have excluded every live replica; better
                # a repeated target than a spurious shed
                candidates = [s for s in self._replicas.values()
                              if s.eligible()]
            if not candidates:
                return None, False
            state = min(candidates,
                        key=lambda s: (s.lane_load(lane),
                                       s.total_inflight, s.name))
            if sequence:
                self._affinity[client] = state.name
            return state, False

    def _track(self, state, lane, delta):
        with self._lock:
            state.inflight[lane] = max(
                0, state.inflight.get(lane, 0) + delta)
            state.total_inflight = max(0, state.total_inflight + delta)

    def _shed(self, ticket, reason, detail=""):
        with self._lock:
            self.sheds[reason] = self.sheds.get(reason, 0) + 1
        self._m_shed.labels(reason=reason).inc()
        telemetry.get().emit("fleet", event="shed", rid=ticket.rid,
                             client=ticket.client, reason=reason)
        ticket._complete(error=ServeRejected(reason, detail))

    def _route(self, ticket, meta, body):
        try:
            self._route_inner(ticket, meta, body)
        except Exception as e:  # noqa: BLE001 - a routing bug must fail the ticket, not the pool thread
            ticket._complete(error=ServeError("internal", str(e)))

    def _route_inner(self, ticket, meta, body):
        lane = self._lane(meta)
        client = ticket.client
        sequence = bool(meta.get("sequence", False))
        deadline = time.monotonic() + self.timeout_s
        tried = []
        last_queue_full = False
        for attempt in range(self.retries + 1):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            state, sticky = self._pick(lane, client, sequence,
                                       exclude=tried)
            if state is None:
                self._shed(ticket, "replica_unavailable",
                           "no eligible replica")
                return
            if attempt > 0:
                self.retries_done += 1
                self._m_retries.inc()
                telemetry.get().emit(
                    "fleet", event="retry", rid=ticket.rid,
                    client=client, attempt=attempt, replica=state.name)
                backoff = (_RETRY_BACKOFF_S * (2 ** (attempt - 1))
                           * random.uniform(0.5, 1.5))
                time.sleep(min(backoff, max(0.0, remaining)))
            self._track(state, lane, +1)
            try:
                status, out_meta, out_body = state.client.flow(
                    meta, body, timeout=remaining)
            except ReplicaTimeout:
                # the per-request deadline is spent waiting on this
                # replica; answering late AND re-executing elsewhere
                # would blow the deadline anyway — fail typed
                self._shed(ticket, "replica_unavailable",
                           f"replica {state.name} deadline "
                           f"({self.timeout_s} s)")
                return
            except ReplicaDown as e:
                # no response ever arrived: safe to retry elsewhere
                tried.append(state.name)
                self.mark_down(state.name, reason=str(e)[:120])
                continue
            finally:
                self._track(state, lane, -1)

            if status == 200:
                self._finish(ticket, state, out_meta, out_body)
                return
            reason = (out_meta or {}).get("error", "internal")
            if status in fwire.SAFE_RETRY_STATUS:
                # typed replica shed (queue_full/draining/shutdown):
                # another replica may have room
                tried.append(state.name)
                last_queue_full = (status == 429)
                continue
            # deterministic application error: complete typed, no retry
            kind = reason if reason in fwire.STATUS_BY_ERROR else "internal"
            ticket._complete(error=ServeError(
                kind, (out_meta or {}).get("detail", "")))
            return
        self._shed(ticket,
                   "queue_full" if last_queue_full
                   else "replica_unavailable",
                   f"retries exhausted after {len(tried)} replicas")

    def _finish(self, ticket, state, out_meta, out_body):
        try:
            flow, out_meta = fwire.unpack_result(out_meta or {}, out_body)
        except ServeError as e:
            ticket._complete(error=e)
            return
        shape = tuple(out_meta["shape"])
        spans = {k: float(v)
                 for k, v in (out_meta.get("spans") or {}).items()}
        self._m_requests.labels(replica=state.name).inc()
        telemetry.get().emit(
            "fleet", event="route", rid=ticket.rid, client=ticket.client,
            replica=state.name, klass=out_meta.get("klass", ""),
            warm=bool(out_meta.get("warm", False)))
        ticket._complete(result=FlowResult(
            rid=ticket.rid, client=ticket.client,
            bucket=shape, shape=shape, flow=flow, spans=spans,
            klass=out_meta.get("klass", ""),
            iterations=int(out_meta.get("iterations", 0)),
            warm=bool(out_meta.get("warm", False))))

    # -- health / drain ------------------------------------------------------

    def _health_loop(self):
        while not self._stopping.wait(self.health_interval_s):
            self.poll_health()

    def poll_health(self):
        """One pass over every replica's /healthz + /statusz (also
        callable directly by tests/drills for determinism)."""
        for state in list(self.replicas().values()):
            if not state.up:
                continue
            try:
                payload, _status = state.client.health(
                    timeout=self.health_interval_s * 4)
                state.health_failures = 0
            except (ReplicaDown, ReplicaTimeout):
                state.health_failures += 1
                if state.health_failures >= _HEALTH_FAILURES_DOWN:
                    self.mark_down(state.name, reason="unreachable")
                continue
            state.ready = bool(payload.get("ready", False))
            state.live = bool(payload.get("live", False))
            replica_draining = bool(payload.get("draining", False))
            if replica_draining and not state.draining:
                # the replica began draining on its own (operator poke
                # at /drainz): honor it — shift traffic + hand off
                self.drain_replica(state.name, reason="replica")
                continue
            if not state.live and not state.draining:
                self.drain_replica(state.name, reason="liveness")
                continue
            try:
                status = state.client.status(
                    timeout=self.health_interval_s * 4)
            except (ReplicaDown, ReplicaTimeout):
                continue
            burns = [s.get("burn_rate", 0.0)
                     for s in (status.get("slo") or {}).values()]
            state.burn = max(burns) if burns else 0.0
            if self.burn_drain > 0 and state.burn > self.burn_drain \
                    and not state.draining:
                self.drain_replica(state.name, reason="slo_burn")
        self._refresh_ready_gauge()

    def drain_replica(self, name, reason="manual"):
        """Shift traffic off a replica and hand off its sticky sessions.

        The replica keeps serving its queue (drain is graceful); new
        requests stop routing to it immediately. Each sticky client's
        carry snapshot moves to a newly-pinned replica — a failed
        export/import degrades that one stream to a single cold frame
        (evicted), never a dropped stream."""
        with self._lock:
            state = self._replicas.get(name)
            if state is None or state.draining:
                return
            state.draining = True
        self._m_drains.labels(reason=reason).inc()
        telemetry.get().emit("fleet", event="drain", replica=name,
                            reason=reason, source="router")
        try:
            state.client.drain()
        except (ReplicaDown, ReplicaTimeout):
            self.mark_down(name, reason="died during drain")
            return
        self._handoff_sessions(state)
        if self.on_recycle is not None:
            self.on_recycle(name)

    def _handoff_sessions(self, source):
        with self._lock:
            stuck = [c for c, owner in self._affinity.items()
                     if owner == source.name]
        for c in stuck:
            target, _ = self._pick(((0, 0), ""), c, False,
                                   exclude=[source.name])
            outcome = "evicted"
            if target is not None:
                try:
                    snapshot = source.client.export_session(c)
                    if snapshot is not None and \
                            target.client.import_session(snapshot):
                        outcome = "moved"
                except (ReplicaDown, ReplicaTimeout):
                    outcome = "evicted"
            with self._lock:
                if outcome == "moved":
                    self._affinity[c] = target.name
                else:
                    self._affinity.pop(c, None)
            self._m_handoffs.labels(outcome=outcome).inc()
            telemetry.get().emit(
                "fleet", event="handoff", client=c, source=source.name,
                target=target.name if outcome == "moved" else None,
                outcome=outcome)

    # -- introspection -------------------------------------------------------

    def describe(self):
        with self._lock:
            replicas = {n: s.describe()
                        for n, s in self._replicas.items()}
            affinity = len(self._affinity)
            sheds = dict(self.sheds)
        return {
            "replicas": replicas,
            "sticky_sessions": affinity,
            "sheds": sheds,
            "retries": self.retries_done,
        }


class _FrontendObserver:
    """Adapter giving the router a sidecar-shaped health surface."""

    def __init__(self, router):
        self.router = router

    def health(self):
        ready = sum(1 for s in self.router.replicas().values()
                    if s.eligible())
        return ({"ready": ready > 0, "replicas_ready": ready},
                200 if ready > 0 else 503)


class FrontendHandler(sidecar.Handler):
    """HTTP front-end: the network boundary real clients speak to."""

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        router = self.observer.router
        try:
            if url.path == "/fleetz":
                self._send_json(200, router.describe())
            elif url.path == "/healthz":
                payload, code = self.observer.health()
                self._send_json(code, payload)
            else:
                self._send_json(404, {"error": f"no route {url.path}"})
        except Exception as e:  # noqa: BLE001 - a scrape must not kill the router
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        router = self.observer.router
        try:
            if url.path != "/v1/flow":
                self._send_json(404, {"error": f"no route {url.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            try:
                meta = fwire.loads_meta(self.headers.get(fwire.META_HEADER))
            except ServeError as e:
                self._send_json(400, {"error": e.kind, "type": "error",
                                      "detail": str(e)})
                return
            ticket = router.submit_wire(meta, body)
            try:
                result = ticket.result(timeout=router.timeout_s + 1.0)
            except ServeRejected as e:
                self._send_json(
                    fwire.STATUS_BY_REJECT.get(e.reason, 503),
                    {"error": e.reason, "type": "rejected",
                     "detail": str(e)})
                return
            except (ServeError, TimeoutError) as e:
                kind = getattr(e, "kind", "timeout")
                self._send_json(
                    fwire.STATUS_BY_ERROR.get(kind, 500),
                    {"error": kind, "type": "error", "detail": str(e)})
                return
            wire = router.codec.wire
            flow_dtype = ("float16" if wire is not None
                          and wire.flow == "f16" else "float32")
            out_meta, out_body = fwire.pack_result(result, flow_dtype)
            data = out_body
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header(fwire.META_HEADER, fwire.dumps_meta(out_meta))
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except Exception as e:  # noqa: BLE001 - a request must not kill the router
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass  # client went away mid-reply


class FrontendServer(sidecar.SidecarServer):
    """The router's bound HTTP server (daemon thread)."""

    def __init__(self, router, port, host="127.0.0.1"):
        obs = _FrontendObserver(router)
        super().__init__(obs, port, host=host,
                         thread_name="fleet-frontend",
                         handler_cls=FrontendHandler)


def serve_frontend(router, port):
    """Bind and start the fleet HTTP front-end; returns the
    :class:`FrontendServer` (``.port`` resolves port 0)."""
    return FrontendServer(router, port).start()
