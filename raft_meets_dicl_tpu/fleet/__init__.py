"""Fault-tolerant serving fleet (PR 20).

One router process fronting N serve replica processes:

- :mod:`.supervisor` — spawn/watch/restart replicas (capped exponential
  backoff + jitter, port-file rendezvous, /healthz boot gate);
- :mod:`.router` — least-loaded per-(bucket, class) dispatch, bounded
  retry on safe failures, typed ``queue_full``/``replica_unavailable``
  sheds, SLO-burn/liveness drain, sticky-session affinity + carry
  handoff, HTTP front-end;
- :mod:`.replica` — the replica-side API (/v1/flow /sessionz /drainz on
  the shared observability sidecar);
- :mod:`.wire` — edge encode/decode for the PR-2 wire presets plus the
  meta-header framing both hops speak;
- :mod:`.client` — stdlib HTTP client with the typed transport failure
  taxonomy (:class:`~.client.ReplicaDown` is safe to retry,
  :class:`~.client.ReplicaTimeout` is not);
- :mod:`.drill` — the kill/rejoin chaos drill the bench/dryrun
  acceptance gates run.
"""

from .client import ReplicaClient, ReplicaDown, ReplicaTimeout
from .drill import run_drill
from .router import FleetTicket, Router, FrontendServer, serve_frontend
from .supervisor import Supervisor
from .replica import ReplicaAPI, ReplicaServer, serve_replica
from .wire import EdgeCodec

__all__ = [
    "EdgeCodec",
    "FleetTicket",
    "FrontendServer",
    "ReplicaAPI",
    "ReplicaClient",
    "ReplicaDown",
    "ReplicaServer",
    "ReplicaTimeout",
    "Router",
    "Supervisor",
    "run_drill",
    "serve_frontend",
    "serve_replica",
]
