"""Thin HTTP client for one serve replica (router/supervisor side).

One connection per call (``http.client``, stdlib only): the fleet's
request volume is batched device work, not connection churn, and a
fresh connection is what makes "the replica died mid-request" a clean,
*typed* failure instead of a wedged keep-alive socket.

Failure taxonomy the router dispatches on:

- :class:`ReplicaDown` — the TCP/HTTP exchange failed before a complete
  response arrived (refused, reset, remote disconnected): the request
  may safely be retried on another replica (the device never confirmed
  executing it — and flow inference on identical inputs is idempotent
  anyway, so even a duplicated execution cannot corrupt a stream);
- :class:`ReplicaTimeout` — the per-attempt socket deadline passed: the
  replica is up but not answering (hung handler, wedged dispatch loop);
- an ordinary ``(status, meta, body)`` return for everything else,
  including typed shed/error statuses — interpreting those is routing
  policy, not transport.
"""

import http.client
import json
import socket
from urllib.parse import urlsplit

from . import wire as fwire


class ReplicaDown(ConnectionError):
    """Transport to the replica failed before a full response."""


class ReplicaTimeout(TimeoutError):
    """The replica did not answer within the per-attempt deadline."""


class ReplicaClient:
    def __init__(self, url, timeout_s=5.0):
        parts = urlsplit(url)
        self.host = parts.hostname or "127.0.0.1"
        self.port = int(parts.port or 80)
        self.url = f"http://{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)

    def _request(self, method, path, body=None, meta=None, timeout=None):
        """One exchange → ``(status, meta dict, body bytes)``."""
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=(self.timeout_s if timeout is None else float(timeout)))
        headers = {}
        if meta is not None:
            headers[fwire.META_HEADER] = fwire.dumps_meta(meta)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            raw = resp.getheader(fwire.META_HEADER)
            out_meta = json.loads(raw) if raw else None
            if out_meta is None and data \
                    and (resp.getheader("Content-Type") or "").startswith(
                        "application/json"):
                try:
                    out_meta = json.loads(data)
                except ValueError:
                    out_meta = None
            return resp.status, out_meta, data
        except socket.timeout as e:
            raise ReplicaTimeout(
                f"{self.url}{path}: no response within "
                f"{timeout or self.timeout_s} s") from e
        except (ConnectionError, http.client.HTTPException, OSError) as e:
            raise ReplicaDown(f"{self.url}{path}: {e}") from e
        finally:
            conn.close()

    # -- observability plane -------------------------------------------------

    def health(self, timeout=None):
        """``(payload, status)`` from /healthz (503 is a *valid* answer:
        not-ready or draining, as opposed to unreachable)."""
        status, meta, _ = self._request("GET", "/healthz", timeout=timeout)
        return meta or {}, status

    def status(self, timeout=None):
        status, meta, _ = self._request("GET", "/statusz", timeout=timeout)
        if status != 200:
            raise ReplicaDown(f"{self.url}/statusz: HTTP {status}")
        return meta or {}

    # -- serving API ---------------------------------------------------------

    def flow(self, meta, body, timeout=None):
        """One inference exchange → ``(status, meta, body)``."""
        return self._request("POST", "/v1/flow", body=body, meta=meta,
                             timeout=timeout)

    def drain(self, timeout=None):
        status, meta, _ = self._request("POST", "/drainz", timeout=timeout)
        return meta or {}, status

    def export_session(self, client, timeout=None):
        """The replica's carry snapshot for ``client``, or None."""
        status, meta, _ = self._request(
            "GET", f"/sessionz?client={client}", timeout=timeout)
        if status != 200 or not isinstance(meta, dict) \
                or "data" not in meta:
            return None
        return meta

    def import_session(self, snapshot, timeout=None):
        payload = json.dumps(snapshot).encode()
        status, meta, _ = self._request("POST", "/sessionz", body=payload,
                                        timeout=timeout)
        return status == 200
