"""Replica-side fleet API: one serve process behind the router.

Extends the shared observability sidecar (PR 13: /metrics /healthz
/statusz /profilez) with the serving surface one replica exposes to the
fleet — one HTTP server, one port, one route table:

- ``POST /v1/flow`` — inference: wire-encoded request bytes admitted
  straight through ``Scheduler.submit_encoded`` (no re-encode), the
  response flow in the session's wire flow dtype. Typed sheds/errors map
  to status codes (fleet/wire.py) so the router can account and retry
  without parsing prose.
- ``GET /sessionz?client=X`` / ``GET /sessionz`` — export one sticky
  video session's carry snapshot (handoff source) / list live sessions.
- ``POST /sessionz`` — install a handed-off carry snapshot (handoff
  target); validation failures answer 400 and the stream restarts cold.
- ``POST /drainz`` — begin drain: /healthz flips to 503 with a
  ``draining`` body, new /v1/flow requests shed typed ``draining``,
  queued/in-flight work still completes.

The chaos triggers (testing.faults) live here, keyed by the replica
index: ``slow_replica`` sleeps before handling, ``hang_replica`` wedges
request handling (the process stays up — the router's per-request
deadline is what must save the client), ``kill_replica`` hard-exits the
process after N completed requests (``os._exit``: no drain, no goodbye
— the supervisor and router must cope).
"""

import json
import logging
import os
import threading
import time
from urllib.parse import parse_qs, urlparse

from .. import telemetry
from ..serve.batcher import ServeError, ServeRejected
from ..telemetry import sidecar
from ..testing import faults
from ..utils import env
from ..video.cache import CarryMismatch
from . import wire as fwire

# every route this replica serves beyond the inherited observability
# sidecar table; graftlint:sidecar-route checks these against README
ROUTES = ("/v1/flow", "/sessionz", "/drainz")

# hard exit code for the kill_replica chaos trigger: distinguishable
# from a python crash (1) and a clean drain (0) in supervisor logs
KILL_EXIT_CODE = 17


class ReplicaAPI:
    """Request handling + fault hooks for one replica process."""

    def __init__(self, session, scheduler, observer, index=0,
                 timeout_s=None):
        self.session = session
        self.scheduler = scheduler
        self.observer = observer
        self.index = int(index)
        if timeout_s is None:
            timeout_s = env.get_float("RMD_FLEET_TIMEOUT_MS") / 1e3
        self.timeout_s = float(timeout_s)
        self._served = 0
        self._hang_until = 0.0
        self._lock = threading.Lock()

    # -- chaos hooks ---------------------------------------------------------

    def _fault_hooks(self):
        """Fire any armed fleet triggers at this replica's coordinates.

        ``after=N`` pins the trigger to fire once N requests have
        *completed* on this replica (so a kill lands mid-stream, not at
        boot); omitted, it fires on the first request. The counter check
        runs on every request until the directive's budget is consumed.
        """
        with self._lock:
            served = self._served
        if faults.fire("kill_replica", replica=self.index, after=served) \
                is not None:
            logging.warning(
                f"fault kill_replica: replica {self.index} hard-exiting "
                f"after {served} served requests")
            os._exit(KILL_EXIT_CODE)
        p = faults.fire("hang_replica", replica=self.index, after=served)
        if p is not None:
            self._hang_until = time.monotonic() + float(
                p.get("seconds", 3600))
        p = faults.fire("slow_replica", replica=self.index)
        if p is not None:
            time.sleep(float(p.get("ms", 250)) / 1e3)
        hang = self._hang_until - time.monotonic()
        if hang > 0:
            time.sleep(hang)

    # -- /v1/flow ------------------------------------------------------------

    def handle_flow(self, meta, body):
        """One inference request → ``(status, meta, body | None)``."""
        self._fault_hooks()
        if self.observer.draining():
            return 503, {"error": "draining", "type": "rejected"}, None
        try:
            e1, e2, shape = fwire.unpack_pair(
                meta, body, expect_dtype=self.session.image_dtype())
            ticket = self.scheduler.submit_encoded(
                e1, e2, shape,
                client=str(meta.get("client", "default")),
                klass=meta.get("klass"),
                sequence=bool(meta.get("sequence", False)))
        except ServeRejected as e:
            return (fwire.STATUS_BY_REJECT.get(e.reason, 503),
                    {"error": e.reason, "type": "rejected",
                     "detail": str(e)}, None)
        except ServeError as e:
            return (fwire.STATUS_BY_ERROR.get(e.kind, 500),
                    {"error": e.kind, "type": "error",
                     "detail": str(e)}, None)
        try:
            result = ticket.result(timeout=self.timeout_s)
        except TimeoutError:
            return (504, {"error": "timeout", "type": "error",
                          "detail": f"no result in {self.timeout_s} s"},
                    None)
        except ServeError as e:
            return (fwire.STATUS_BY_ERROR.get(e.kind, 500),
                    {"error": e.kind, "type": "error",
                     "detail": str(e)}, None)
        with self._lock:
            self._served += 1
        wire = getattr(self.session, "wire", None)
        flow_dtype = ("float16" if wire is not None and wire.flow == "f16"
                      else "float32")
        out_meta, out_body = fwire.pack_result(result, flow_dtype)
        out_meta["replica"] = self.index
        return 200, out_meta, out_body

    # -- /sessionz -----------------------------------------------------------

    def _sessions(self):
        return getattr(self.scheduler, "sessions", None)

    def export_session(self, client):
        sessions = self._sessions()
        if sessions is None:
            return 400, {"error": "no_video",
                         "detail": "replica serves no video sessions"}
        snapshot = sessions.export_carry(client)
        if snapshot is None:
            return 404, {"error": "no_session", "client": client}
        snapshot["replica"] = self.index
        return 200, snapshot

    def list_sessions(self):
        sessions = self._sessions()
        clients = sessions.clients() if sessions is not None else []
        return 200, {"clients": clients, "replica": self.index}

    def import_session(self, snapshot):
        sessions = self._sessions()
        if sessions is None:
            return 400, {"error": "no_video",
                         "detail": "replica serves no video sessions"}
        expected = self.scheduler.carry_shapes() \
            if hasattr(self.scheduler, "carry_shapes") else None
        try:
            if expected is not None and \
                    tuple(int(d) for d in snapshot.get("shape", ())) \
                    not in expected:
                raise CarryMismatch(
                    f"carry shape {snapshot.get('shape')} matches no "
                    f"bucket's coarse grid {sorted(expected)}")
            sessions.import_carry(snapshot)
        except CarryMismatch as e:
            return 400, {"error": "carry_mismatch", "detail": str(e)}
        return 200, {"imported": snapshot.get("client"),
                     "replica": self.index}

    # -- /drainz -------------------------------------------------------------

    def drain(self):
        first = self.observer.begin_drain()
        if first:
            telemetry.get().emit("fleet", event="drain",
                                 replica=self.index, source="replica")
        return 200, {"draining": True, "first": first,
                     "pending": self.scheduler.pending(),
                     "replica": self.index}


class Handler(sidecar.Handler):
    """The sidecar handler plus the fleet serving routes.

    ``observer`` (bound by SidecarServer) must be a serve Observer whose
    ``api`` attribute is the :class:`ReplicaAPI`.
    """

    def _api(self):
        return getattr(self.observer, "api", None)

    def _send_meta(self, status, meta, body):
        """Reply with an X-RMD-Meta header + raw body (the flow path),
        or a plain JSON body when there is no payload."""
        if body is None:
            self._send_json(status, meta)
            return
        data = body if isinstance(body, bytes) else bytes(body)
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header(fwire.META_HEADER, fwire.dumps_meta(meta))
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        api = self._api()
        if url.path != "/sessionz" or api is None:
            super().do_GET()
            return
        try:
            qs = parse_qs(url.query)
            client = qs.get("client", [None])[0]
            if client:
                status, payload = api.export_session(client)
            else:
                status, payload = api.list_sessions()
            self._send_json(status, payload)
        except Exception as e:  # noqa: BLE001 - a handler must not kill the replica
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        api = self._api()
        try:
            if api is None:
                self._send_json(404, {"error": f"no route {url.path}"})
            elif url.path == "/v1/flow":
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                try:
                    meta = fwire.loads_meta(
                        self.headers.get(fwire.META_HEADER))
                except ServeError as e:
                    self._send_json(400, {"error": e.kind, "type": "error",
                                          "detail": str(e)})
                    return
                status, out_meta, out_body = api.handle_flow(meta, body)
                self._send_meta(status, out_meta, out_body)
            elif url.path == "/sessionz":
                length = int(self.headers.get("Content-Length", 0))
                try:
                    snapshot = json.loads(self.rfile.read(length))
                except ValueError as e:
                    self._send_json(400, {"error": "carry_mismatch",
                                          "detail": f"bad json: {e}"})
                    return
                status, payload = api.import_session(snapshot)
                self._send_json(status, payload)
            elif url.path == "/drainz":
                status, payload = api.drain()
                self._send_json(status, payload)
            else:
                self._send_json(404, {"error": f"no route {url.path}"})
        except Exception as e:  # noqa: BLE001 - a handler must not kill the replica
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass  # client went away mid-reply


class ReplicaServer(sidecar.SidecarServer):
    """One replica's single HTTP server: observability + serving API."""

    def __init__(self, observer, port, host="127.0.0.1"):
        super().__init__(observer, port, host=host,
                         thread_name="fleet-replica", handler_cls=Handler)


def serve_replica(session, scheduler, observer, port, index=0,
                  timeout_s=None):
    """Bind the fleet API onto a booted replica; returns the started
    :class:`ReplicaServer` (``.port`` resolves port 0)."""
    api = ReplicaAPI(session, scheduler, observer, index=index,
                     timeout_s=timeout_s)
    observer.api = api
    return ReplicaServer(observer, port).start()
