"""Fleet wire protocol: how a flow request crosses the network boundary.

The PR-2 wire formats already define the *device* contract (u8/bf16
images decoded inside the jitted program); this module defines the
*HTTP* contract so client-encoded bytes land on device untouched:

- request: ``POST /v1/flow`` with an ``X-RMD-Meta`` JSON header (bucket,
  original shape, wire dtype, client, class, sequence flag) and a raw
  body of the two bucket-padded, wire-encoded images concatenated —
  no base64, no re-encode at any hop;
- response: ``X-RMD-Meta`` (shape, flow dtype, class, iterations, warm
  flag, latency spans) plus the raw flow bytes, in the session's wire
  flow dtype (f16 under the bf16/u8 presets);
- errors: JSON bodies with the *typed* reason — HTTP status carries the
  shed/error class (429 ``queue_full``, 503 ``replica_unavailable`` /
  ``draining`` / ``shutdown``, 400 payload errors, 504 deadline, 500
  internal) so every hop can account sheds without parsing prose.

:class:`EdgeCodec` is the client-side edge: it owns the bucket
quantization + wire encode that ``serve.Scheduler.submit`` would do
in-process, so the router (and any thin client) produces exactly the
bytes a replica's ``submit_encoded`` admits.

Numpy-only; no jax anywhere on the wire path.
"""

import json

import numpy as np

from ..serve.batcher import ServeError

META_HEADER = "X-RMD-Meta"

# HTTP status per typed shed/error: the fleet-wide backpressure contract
STATUS_BY_REJECT = {"queue_full": 429, "shutdown": 503,
                    "replica_unavailable": 503, "draining": 503}
STATUS_BY_ERROR = {"malformed": 400, "oversized": 400,
                   "unknown_class": 400, "no_video": 400,
                   "decode": 500, "internal": 500, "timeout": 504}
# replies on these paths never executed the request on the device, so a
# router may safely re-dispatch them to another replica
SAFE_RETRY_STATUS = (429, 503)


def dumps_meta(meta):
    return json.dumps(meta, separators=(",", ":"))


def loads_meta(raw):
    if not raw:
        raise ServeError("malformed", f"missing {META_HEADER} header")
    try:
        meta = json.loads(raw)
    except ValueError as e:
        raise ServeError("malformed", f"bad {META_HEADER}: {e}") from e
    if not isinstance(meta, dict):
        raise ServeError("malformed", f"{META_HEADER} is not an object")
    return meta


class EdgeCodec:
    """Bucket quantization + wire encoding at the client edge.

    Mirrors the serve admission path exactly (`ShapeBuckets.assign` +
    ``pad_image`` + ``WireFormat.encode_image``): the replica admits the
    resulting arrays through ``submit_encoded`` without touching a
    pixel. ``wire=None`` means raw f32 (no wire format configured).
    """

    def __init__(self, buckets, wire=None):
        self.buckets = buckets
        self.wire = wire

    def image_dtype(self):
        if self.wire is not None:
            return self.wire.image_dtype()
        return np.dtype(np.float32)

    def flow_dtype(self):
        if self.wire is not None and self.wire.flow == "f16":
            return np.dtype(np.float16)
        return np.dtype(np.float32)

    def encode_image(self, img):
        if self.wire is not None:
            return self.wire.encode_image(img)
        return np.ascontiguousarray(img, np.float32)

    def encode_pair(self, img1, img2):
        """Raw HWC pair → (e1, e2, bucket, shape); raises the same typed
        ``oversized``/``malformed`` errors as in-process admission."""
        for img in (img1, img2):
            if not isinstance(img, np.ndarray) or img.ndim != 3 \
                    or img.shape[-1] != 3:
                raise ServeError(
                    "malformed",
                    f"expected HWC RGB arrays, got "
                    f"{getattr(img, 'shape', type(img).__name__)}")
        if img1.shape != img2.shape:
            raise ServeError(
                "malformed",
                f"pair shapes differ: {img1.shape} vs {img2.shape}")
        h, w = int(img1.shape[0]), int(img1.shape[1])
        bucket = self.buckets.assign(h, w)
        if bucket is None:
            raise ServeError(
                "oversized",
                f"{h}x{w} fits no bucket ({self.buckets.describe()})")
        e1 = self.encode_image(self.buckets.pad_image(img1, bucket))
        e2 = self.encode_image(self.buckets.pad_image(img2, bucket))
        return e1, e2, bucket, (h, w)

    def request(self, img1, img2, client="default", klass=None,
                sequence=False):
        """Raw pair → ``(meta, body)`` ready for ``POST /v1/flow``."""
        e1, e2, bucket, shape = self.encode_pair(img1, img2)
        meta = {
            "bucket": list(bucket),
            "shape": list(shape),
            "dtype": str(e1.dtype),
            "client": client,
            "sequence": bool(sequence),
        }
        if klass is not None:
            meta["klass"] = klass
        return meta, pack_pair(e1, e2)


def pack_pair(e1, e2):
    """Two equally-shaped wire arrays → one raw body (img1 then img2)."""
    return np.ascontiguousarray(e1).tobytes() \
        + np.ascontiguousarray(e2).tobytes()


def unpack_pair(meta, body, expect_dtype=None):
    """Request body → the two bucket-shaped wire arrays.

    Validates the meta against the body length and (when given) the
    serving session's wire dtype; every failure is a typed ``malformed``
    so the replica answers 400, never 500.
    """
    try:
        bucket = tuple(int(d) for d in meta["bucket"])
        shape = tuple(int(d) for d in meta["shape"])
        dtype = np.dtype(str(meta["dtype"]))
    except Exception as e:  # noqa: BLE001 - anything missing/unparseable is a client error
        raise ServeError("malformed", f"bad request meta: {e}") from e
    if len(bucket) != 2 or len(shape) != 2:
        raise ServeError("malformed",
                         f"bucket/shape must be (H, W): {meta}")
    if expect_dtype is not None and dtype != expect_dtype:
        raise ServeError(
            "malformed",
            f"wire dtype {dtype} does not match the replica's "
            f"{expect_dtype}")
    nbytes = bucket[0] * bucket[1] * 3 * dtype.itemsize
    if len(body) != 2 * nbytes:
        raise ServeError(
            "malformed",
            f"body is {len(body)} bytes, two {bucket[0]}x{bucket[1]}x3 "
            f"{dtype} images need {2 * nbytes}")
    full = (bucket[0], bucket[1], 3)
    e1 = np.frombuffer(body[:nbytes], dtype=dtype).reshape(full)
    e2 = np.frombuffer(body[nbytes:], dtype=dtype).reshape(full)
    return e1, e2, shape


def pack_result(result, flow_dtype):
    """A scheduler :class:`~..serve.batcher.FlowResult` → (meta, body)."""
    flow = np.ascontiguousarray(result.flow, dtype=flow_dtype)
    meta = {
        "rid": result.rid,
        "client": result.client,
        "shape": list(result.shape),
        "dtype": str(flow.dtype),
        "klass": result.klass,
        "iterations": result.iterations,
        "warm": bool(result.warm),
        "spans": {k: round(v, 6) for k, v in result.spans.items()},
    }
    return meta, flow.tobytes()


def unpack_result(meta, body):
    """Response (meta, body) → ``(flow f32, meta)``; typed ``decode``
    error when the payload does not match its declaration."""
    try:
        shape = tuple(int(d) for d in meta["shape"])
        dtype = np.dtype(str(meta["dtype"]))
    except Exception as e:  # noqa: BLE001 - a malformed reply is a decode failure
        raise ServeError("decode", f"bad response meta: {e}") from e
    nbytes = shape[0] * shape[1] * 2 * dtype.itemsize
    if len(body) != nbytes:
        raise ServeError(
            "decode",
            f"flow body is {len(body)} bytes, {shape[0]}x{shape[1]}x2 "
            f"{dtype} needs {nbytes}")
    flow = np.frombuffer(body, dtype=dtype).reshape(shape[0], shape[1], 2)
    return np.asarray(flow, np.float32), meta
