"""Kill/rejoin chaos drill: the fleet's acceptance scenario.

One harness, three consumers (BENCH_SERVE fleet phase, the
``fleet-smoke`` dryrun entry, and ad-hoc CLI drills): drive a skewed
request mix plus one sticky video stream through the router, hard-kill
a replica mid-stream, and account for what the fleet *promised*:

- zero dropped accepted requests — every submitted request ends in a
  result or a *typed* shed (``queue_full`` / ``replica_unavailable``),
  never an untyped error;
- the sticky stream survives with at most one cold frame (its carry is
  evicted with the dead replica; the next frame re-primes it);
- the rejoining replica serves warm: with the AOT store published, its
  boot compiles are zero (every program fetched, not rebuilt).

The drill only *drives and measures* — process lifecycle belongs to the
supervisor, routing policy to the router.
"""

import threading
import time

import numpy as np

from ..serve.batcher import ServeError, ServeRejected
from .client import ReplicaClient, ReplicaDown, ReplicaTimeout


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _pair(rng, h, w):
    return (rng.random((h, w, 3), dtype=np.float32),
            rng.random((h, w, 3), dtype=np.float32))


def run_drill(router, kill, shapes, classes=(None,), frames=24,
              kill_after=8, rejoin_wait_s=120.0, seed=0,
              background_per_frame=2, ticket_timeout_s=None):
    """Run the kill/rejoin scenario; returns the report dict.

    ``router`` is a started :class:`~.router.Router`; ``kill()`` is a
    callback that hard-kills one (non-sticky-owner if possible) replica
    and eventually brings it back — typically wrapping
    ``supervisor.kill`` or an in-process server shutdown. It receives
    the sticky session's current owner name (or None) and must return
    the killed replica's name. ``shapes`` is the (H, W) list for the
    skewed background mix (first = the sticky stream's shape).
    """
    rng = np.random.default_rng(seed)
    if ticket_timeout_s is None:
        ticket_timeout_s = router.timeout_s + 5.0
    sticky = "drill-stream"
    report = {
        "frames": frames,
        "submitted": 0, "completed": 0, "dropped": 0,
        "sheds": {}, "cold_frames": 0, "warm_frames": 0,
        "errors": [],
        "killed": None, "rejoined": False, "rejoin_compiles": None,
        "latencies_ms": {},
    }
    latencies = {}  # (shape, klass) -> [seconds]
    lock = threading.Lock()

    def account(ticket, key, t0, frame=None):
        report["submitted"] += 1
        try:
            result = ticket.result(timeout=ticket_timeout_s)
        except ServeRejected as e:
            with lock:
                report["sheds"][e.reason] = \
                    report["sheds"].get(e.reason, 0) + 1
            return None
        except (ServeError, TimeoutError) as e:
            with lock:
                report["dropped"] += 1
                if len(report["errors"]) < 8:
                    report["errors"].append(
                        f"{key}[{frame}]: {type(e).__name__}: {e}")
            return None
        with lock:
            report["completed"] += 1
            latencies.setdefault(key, []).append(time.monotonic() - t0)
        return result

    h0, w0 = shapes[0]
    killed_at_frame = None
    for frame in range(frames):
        # the sticky stream frame (sequence: carries flow between frames)
        img1, img2 = _pair(rng, h0, w0)
        t0 = time.monotonic()
        ticket = router.submit(img1, img2, client=sticky, klass=classes[0],
                               sequence=True)
        result = account(ticket, ("stream", f"{h0}x{w0}"), t0, frame=frame)
        if result is not None and frame > 0:
            with lock:
                if result.warm:
                    report["warm_frames"] += 1
                else:
                    report["cold_frames"] += 1
        # skewed background singles (shape 0 is hot, the rest cold)
        for j in range(background_per_frame):
            h, w = shapes[0] if (frame + j) % 3 else \
                shapes[min(1 + j % max(1, len(shapes) - 1),
                           len(shapes) - 1)]
            klass = classes[(frame + j) % len(classes)]
            b1, b2 = _pair(rng, h, w)
            t0 = time.monotonic()
            t = router.submit(b1, b2, klass=klass)
            account(t, ("single", f"{h}x{w}", klass or ""), t0)
        if frame == kill_after:
            with router._lock:
                owner = router._affinity.get(sticky)
            report["killed"] = kill(owner)
            killed_at_frame = frame

    # wait for the killed replica to rejoin and prove it serves warm
    if report["killed"] is not None:
        deadline = time.monotonic() + rejoin_wait_s
        while time.monotonic() < deadline:
            state = router.replicas().get(report["killed"])
            if state is not None and state.eligible() \
                    and state.generation > 0:
                report["rejoined"] = True
                try:
                    status = state.client.status(timeout=5.0)
                    report["rejoin_compiles"] = status.get("compiles")
                except (ReplicaDown, ReplicaTimeout):
                    pass
                break
            time.sleep(0.2)
        if report["rejoined"]:
            # a few post-rejoin frames: the stream must already be warm
            # again and the rejoined replica must take traffic
            for frame in range(4):
                img1, img2 = _pair(rng, h0, w0)
                t0 = time.monotonic()
                ticket = router.submit(img1, img2, client=sticky,
                                       klass=classes[0], sequence=True)
                account(ticket, ("stream", f"{h0}x{w0}"), t0,
                        frame=frames + frame)

    every = sorted(v for vals in latencies.values() for v in vals)
    if every:
        report["latencies_ms"]["aggregate"] = {
            "n": len(every),
            "p50": round(_percentile(every, 0.50) * 1e3, 2),
            "p99": round(_percentile(every, 0.99) * 1e3, 2),
        }
    for key, vals in latencies.items():
        vals.sort()
        report["latencies_ms"]["/".join(str(k) for k in key)] = {
            "n": len(vals),
            "p50": round(_percentile(vals, 0.50) * 1e3, 2),
            "p99": round(_percentile(vals, 0.99) * 1e3, 2),
        }
    report["killed_at_frame"] = killed_at_frame
    report["ok"] = (
        report["dropped"] == 0
        and report["cold_frames"] <= 1
        and (report["killed"] is None or report["rejoined"])
        and (report["rejoin_compiles"] is None
             or report["rejoin_compiles"] == 0))
    return report
