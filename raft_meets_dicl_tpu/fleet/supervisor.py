"""Replica process supervisor: spawn, watch, restart with backoff.

The supervisor owns the fleet's process tree. Each replica slot runs
one serve process (``serve --listen-port 0 --port-file ...``); the
monitor thread reaps exits and respawns crashed slots with capped
exponential backoff (base ``RMD_FLEET_BACKOFF_MS``, doubling per
consecutive crash, capped at 30 s, ±25 % jitter so a correlated crash
doesn't produce a correlated thundering-herd restart). A slot that
comes back *stays backed off* until it proves healthy: the port-file
rendezvous plus an HTTP /healthz gate runs before the ``on_up``
callback announces the replica to the router, so traffic never routes
to a half-booted process.

The supervisor is deliberately policy-free: it knows processes, ports
and exit codes, not requests. Routing policy (drain, affinity, retry)
lives in :class:`~.router.Router`; the two meet only through the
``on_up``/``on_down`` callbacks and :meth:`recycle`.
"""

import logging
import os
import pathlib
import random
import signal
import subprocess
import threading
import time

from .. import telemetry
from ..telemetry import metrics as metrics_mod
from ..utils import env
from .client import ReplicaClient, ReplicaDown, ReplicaTimeout

# restart backoff ceiling; crashes faster than this stop accelerating
_BACKOFF_CAP_S = 30.0
# a replica alive this long resets its consecutive-crash counter
_HEALTHY_RESET_S = 10.0
# port-file + healthz rendezvous budget per boot
_BOOT_DEADLINE_S = 120.0


class ReplicaProc:
    """One supervised slot: process handle + restart bookkeeping."""

    def __init__(self, index):
        self.index = int(index)
        self.name = f"replica-{index}"
        self.proc = None
        self.url = None
        self.port_file = None
        self.crashes = 0          # consecutive, reset after healthy uptime
        self.restarts = 0         # lifetime
        self.started_at = 0.0
        self.restart_after = 0.0  # monotonic gate for the next spawn
        self.wanted = True        # False once stop()/kill(permanent) hit
        self.reaped = True        # this death already counted/announced

    def alive(self):
        return self.proc is not None and self.proc.poll() is None


class Supervisor:
    """Spawn and keep N replica processes alive.

    ``spawn(index, port_file)`` must return a started
    :class:`subprocess.Popen` for slot ``index`` whose process writes
    its bound HTTP port (decimal, one line) to ``port_file`` once
    serving. ``on_up(index, url)`` / ``on_down(index)`` are the router
    hookup; both run on the monitor thread.
    """

    def __init__(self, spawn, n, on_up=None, on_down=None,
                 backoff_ms=None, poll_s=None, workdir=None):
        self.spawn = spawn
        self.n = int(n)
        self.on_up = on_up
        self.on_down = on_down
        self.backoff_s = float(
            backoff_ms if backoff_ms is not None
            else env.get_float("RMD_FLEET_BACKOFF_MS")) / 1e3
        self.poll_s = float(poll_s if poll_s is not None
                            else env.get_float("RMD_FLEET_HEALTH_S"))
        self.workdir = pathlib.Path(
            workdir if workdir is not None
            else os.environ.get("TMPDIR", "/tmp")) / f"rmd-fleet-{os.getpid()}"
        self.slots = [ReplicaProc(i) for i in range(self.n)]
        self._thread = None
        self._stopping = threading.Event()
        self._lock = threading.Lock()
        self._m_restarts = metrics_mod.registry().counter(
            "rmd_fleet_restarts_total",
            "supervisor replica respawns after crash", ("replica",))

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_ready=True):
        """Boot every slot; optionally block until all pass the health
        gate (initial boot is sequential on purpose — N replicas racing
        a cold compile cache would duplicate every warm-up compile)."""
        self.workdir.mkdir(parents=True, exist_ok=True)
        for slot in self.slots:
            self._spawn_slot(slot)
            if wait_ready:
                self._await_boot(slot)
        self._thread = threading.Thread(
            target=self._monitor, name="fleet-supervisor", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s=10.0):
        """SIGTERM every child (graceful drain path), then SIGKILL."""
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        for slot in self.slots:
            slot.wanted = False
            if slot.alive():
                slot.proc.terminate()
        deadline = time.monotonic() + timeout_s
        for slot in self.slots:
            if slot.proc is None:
                continue
            try:
                slot.proc.wait(timeout=max(0.1,
                                           deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                slot.proc.kill()
                slot.proc.wait(timeout=5.0)

    # -- chaos / recycling ---------------------------------------------------

    def kill(self, index, permanent=False):
        """Hard-kill one slot (drill hook). With ``permanent`` the slot
        stays down; otherwise the monitor respawns it with backoff."""
        slot = self.slots[index]
        if permanent:
            slot.wanted = False
        if slot.alive():
            slot.proc.send_signal(signal.SIGKILL)
            slot.proc.wait(timeout=10.0)

    def recycle(self, index):
        """Gracefully replace one slot's process (the router calls this
        after drain + handoff): SIGTERM, then the monitor respawns."""
        slot = self.slots[index]
        slot.crashes = 0  # a commanded recycle is not a crash
        if slot.alive():
            slot.proc.terminate()

    def restore(self, index):
        """Re-arm a slot disabled by ``kill(permanent=True)``."""
        self.slots[index].wanted = True
        self.slots[index].restart_after = 0.0

    # -- internals -----------------------------------------------------------

    def _spawn_slot(self, slot):
        slot.port_file = self.workdir / f"{slot.name}.port"
        try:
            slot.port_file.unlink()
        except FileNotFoundError:
            pass
        slot.proc = self.spawn(slot.index, str(slot.port_file))
        slot.started_at = time.monotonic()
        slot.url = None
        slot.reaped = False
        logging.info(f"fleet: spawned {slot.name} pid {slot.proc.pid}")

    def _await_boot(self, slot, deadline_s=_BOOT_DEADLINE_S):
        """Port-file rendezvous then /healthz gate; returns the URL or
        None (the slot crashed or never came up — backoff applies)."""
        deadline = time.monotonic() + deadline_s
        port = None
        while time.monotonic() < deadline and slot.alive():
            try:
                text = slot.port_file.read_text().strip()
                if text:
                    port = int(text)
                    break
            except (FileNotFoundError, ValueError):
                pass
            time.sleep(0.05)
        if port is None:
            return None
        url = f"http://127.0.0.1:{port}"
        client = ReplicaClient(url, timeout_s=2.0)
        while time.monotonic() < deadline and slot.alive():
            try:
                payload, status = client.health()
                # draining 503 at boot means a stale process; any
                # /healthz answer proves the server thread is up, and
                # ready=True proves the warm pool is built
                if status == 200 and payload.get("ready"):
                    slot.url = url
                    return url
            except (ReplicaDown, ReplicaTimeout):
                pass
            time.sleep(0.1)
        return None

    def _announce_up(self, slot, deadline_s=_BOOT_DEADLINE_S):
        url = self._await_boot(slot, deadline_s=deadline_s)
        if url is None:
            return False
        if self.on_up is not None:
            self.on_up(slot.index, url)
        return True

    def _monitor(self):
        # announce the initially-booted slots
        for slot in self.slots:
            if slot.alive() and slot.url and self.on_up is not None:
                self.on_up(slot.index, slot.url)
        while not self._stopping.wait(self.poll_s):
            now = time.monotonic()
            for slot in self.slots:
                if slot.alive():
                    if slot.crashes and \
                            now - slot.started_at > _HEALTHY_RESET_S:
                        slot.crashes = 0
                    if slot.url is None:
                        # spawned without the blocking boot gate
                        # (wait_ready=False): keep trying the rendezvous
                        self._announce_up(slot,
                                          deadline_s=self.poll_s * 2)
                    continue
                if slot.proc is not None and not slot.reaped:
                    # fresh death: tell the router before anything else
                    code = slot.proc.returncode
                    slot.reaped = True
                    announced = slot.url is not None
                    slot.url = None
                    logging.warning(
                        f"fleet: {slot.name} exited with code {code}")
                    if announced and self.on_down is not None:
                        self.on_down(slot.index)
                    slot.crashes += 1
                    backoff = min(
                        _BACKOFF_CAP_S,
                        self.backoff_s * (2 ** (slot.crashes - 1)))
                    backoff *= random.uniform(0.75, 1.25)
                    slot.restart_after = now + backoff
                    telemetry.get().emit(
                        "fleet", event="restart", replica=slot.index,
                        exit_code=code, crashes=slot.crashes,
                        backoff_ms=round(backoff * 1e3, 1))
                if not slot.wanted or now < slot.restart_after:
                    continue
                slot.restarts += 1
                self._m_restarts.labels(replica=slot.name).inc()
                self._spawn_slot(slot)
                self._announce_up(slot)

    def describe(self):
        return {s.name: {"alive": s.alive(), "url": s.url,
                         "crashes": s.crashes, "restarts": s.restarts}
                for s in self.slots}
