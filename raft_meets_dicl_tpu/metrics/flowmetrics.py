"""Flow-quality metrics: EPE, Fl-all, AAE, flow magnitude.

Config surface and key naming match the reference registry entries
(src/metrics/epe.py, fl_all.py, aae.py, flow.py); the math lives in
``functional`` so jitted validation steps can share it.
"""

from collections import OrderedDict
from typing import List

from . import functional as F
from .common import Metric


class EndPointError(Metric):
    type = "epe"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        key = cfg.get("key", "EndPointError/")
        dist = list(cfg.get("distances", [1, 3, 5]))
        return cls(dist, key)

    def __init__(self, distances: List[float] = (1, 3, 5), key: str = "EndPointError/"):
        self.distances = list(distances)
        self.key = key

    def get_config(self):
        return {"type": self.type, "key": self.key, "distances": self.distances}

    def compute(self, ctx, estimate, target, valid, loss):
        # one batched device->host fetch for mean + every distance bucket
        vals = F.fetch_scalars(
            F.end_point_error(estimate, target, valid, self.distances))

        result = OrderedDict()
        result[f"{self.key}mean"] = vals["mean"]
        for d in self.distances:
            result[f"{self.key}{d}px"] = vals[f"{d}px"]
        return result


class FlAll(Metric):
    type = "fl-all"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "Fl-all"))

    def __init__(self, key: str = "Fl-all"):
        self.key = key

    def get_config(self):
        return {"type": self.type, "key": self.key}

    def compute(self, ctx, estimate, target, valid, loss):
        return {self.key: float(F.fl_all(estimate, target, valid))}


class AverageAngularError(Metric):
    """``masked: true`` restricts the mean to valid pixels — mandatory
    under shape-bucketed (padded) evaluation; the default ``false`` keeps
    the reference's unmasked semantics."""

    type = "aae"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "AverageAngularError"),
                   bool(cfg.get("masked", False)))

    def __init__(self, key: str = "AverageAngularError", masked: bool = False):
        self.key = key
        self.masked = masked

    def get_config(self):
        return {"type": self.type, "key": self.key, "masked": self.masked}

    def compute(self, ctx, estimate, target, valid, loss):
        v = valid if self.masked else None
        return {self.key: float(F.average_angular_error(estimate, target, v))}


class FlowMagnitude(Metric):
    """``masked: true`` restricts the mean to valid pixels (see
    AverageAngularError)."""

    type = "flow-magnitude"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("ord", 2), cfg.get("key", "FlowMagnitude"),
                   bool(cfg.get("masked", False)))

    def __init__(self, ord: float = 2, key: str = "FlowMagnitude",
                 masked: bool = False):
        self.ord = ord
        self.key = key
        self.masked = masked

    def get_config(self):
        return {"type": self.type, "key": self.key, "ord": self.ord,
                "masked": self.masked}

    def compute(self, ctx, estimate, target, valid, loss):
        v = valid if self.masked else None
        return {self.key: float(F.flow_magnitude(estimate, self.ord, v))}
