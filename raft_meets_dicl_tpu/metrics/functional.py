"""Pure jnp metric math — usable eagerly on host arrays and under jit.

The metric *classes* (flowmetrics/trainmetrics) wrap these functions behind
the reference's config-constructible registry (src/metrics/common.py:5-41).
Keeping the math here as pure functions lets the jitted validation/eval
steps compute metrics on-device (scalars only cross the host boundary, the
TPU-first design) while the eval command reuses the exact same definitions
eagerly.

Layout note: all flow tensors are NHWC — ``estimate``/``target`` are
(..., H, W, 2) with channels last, ``valid`` is (..., H, W). The reference
computes the same quantities on NCHW with ``dim=-3``
(src/metrics/epe.py:39, fl_all.py:34-35).
"""

import jax
import jax.numpy as jnp
import numpy as np


def masked_mean(x, valid):
    """Mean of ``x`` over pixels where ``valid``; 0 if no pixel is valid."""
    v = valid.astype(x.dtype)
    return jnp.sum(x * v) / jnp.maximum(jnp.sum(v), 1.0)


def end_point_error(estimate, target, valid, distances=(1, 3, 5)):
    """EPE mean + accuracy-at-distance fractions over valid pixels.

    Matches src/metrics/epe.py:36-52: the ``{d}px`` entries are the fraction
    of valid pixels with EPE ≤ d (inverted bad-pixel rate).
    """
    epe = jnp.linalg.norm(estimate - target, ord=2, axis=-1)

    out = {"mean": masked_mean(epe, valid)}
    for d in distances:
        out[f"{d}px"] = masked_mean((epe <= d).astype(jnp.float32), valid)
    return out


def fl_all(estimate, target, valid):
    """KITTI Fl-all outlier fraction: EPE > 3px and EPE > 5% of target
    magnitude, over valid pixels (src/metrics/fl_all.py:31-44)."""
    epe = jnp.linalg.norm(estimate - target, ord=2, axis=-1)
    mag = jnp.linalg.norm(target, ord=2, axis=-1)

    bad = jnp.logical_and(epe > 3.0, epe > 0.05 * mag)
    return masked_mean(bad.astype(jnp.float32), valid)


def average_angular_error(estimate, target, valid=None):
    """Mean angular error (degrees) between spatio-temporal vectors (u,v,1).

    Published definition (Barron et al.): the denominator is
    ``sqrt(|est|²+1)·sqrt(|tgt|²+1)``. The reference's AAE deviates twice
    (src/metrics/aae.py:32-41: NCHW channel indexing addresses the width
    axis, and the denominator drops the per-vector +1 terms under the
    roots); this implementation follows the published formula.

    ``valid`` restricts the mean to valid pixels — required under
    shape-bucketed evaluation, where padded pixels must never contribute
    (the reference applies no mask; pass ``valid=None`` for its exact
    semantics).
    """
    u_est, v_est = estimate[..., 0], estimate[..., 1]
    u_tgt, v_tgt = target[..., 0], target[..., 1]

    n_est = jnp.sqrt(jnp.square(u_est) + jnp.square(v_est) + 1.0)
    n_tgt = jnp.sqrt(jnp.square(u_tgt) + jnp.square(v_tgt) + 1.0)

    cos = (u_est * u_tgt + v_est * v_tgt + 1.0) / (n_est * n_tgt)
    cos = jnp.clip(cos, -1.0, 1.0)

    angles = jnp.arccos(cos)
    if valid is None:
        return jnp.rad2deg(jnp.mean(angles))
    return jnp.rad2deg(masked_mean(angles, valid))


def flow_magnitude(estimate, ord=2, valid=None):
    """Mean per-pixel flow-vector norm (src/metrics/flow.py:34-36);
    ``valid`` restricts the mean to valid pixels (padded-batch safe)."""
    mag = jnp.linalg.norm(estimate, ord=ord, axis=-1)
    if valid is None:
        return jnp.mean(mag)
    return masked_mean(mag, valid)


# -- pytree (gradient / parameter) statistics --------------------------------
#
# The reference walks module.named_parameters() (src/metrics/grad.py:11-47);
# the pytree analog flattens the params/grads tree with path-joined names.

def tree_named_leaves(tree):
    """Flatten a pytree into [(dotted-path-name, leaf)] pairs."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)

    def name(path):
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        return ".".join(parts)

    return [(name(path), leaf) for path, leaf in flat]


def fetch_scalars(scalars):
    """One device→host transfer for a whole dict of on-device scalars —
    per-leaf ``float()`` fetches would serialize the device pipeline."""
    host = jax.device_get(scalars)  # graftlint: disable=host-sync -- the sanctioned batched fetch point for metric scalars
    return {k: float(v) for k, v in host.items()}  # graftlint: disable=host-sync -- values already on host (device_get above)


def tree_norm(tree, ord=2):
    """Per-leaf norms + 'total' (norm of the vector of norms)."""
    named = tree_named_leaves(tree)
    norms = {
        name: jnp.linalg.norm(jnp.ravel(leaf), ord=ord) for name, leaf in named
    }
    norms = fetch_scalars(norms)
    # total on host: the per-leaf norms were just fetched, so a jnp
    # round-trip here would pay a second device sync for a tiny vector
    norms["total"] = float(np.linalg.norm(list(norms.values()), ord=ord))
    return norms


def tree_mean(tree):
    """Per-leaf (size, mean) + size-weighted 'total'."""
    named = tree_named_leaves(tree)
    means = fetch_scalars({name: jnp.mean(leaf) for name, leaf in named})
    mean = {name: (int(leaf.size), means[name]) for name, leaf in named}
    total_size = sum(n for n, _ in mean.values()) or 1
    mean["total"] = (
        total_size,
        sum((n / total_size) * m for n, m in mean.values()),
    )
    return mean


def tree_minmax(tree):
    """Per-leaf (min, max) + overall 'total'."""
    named = tree_named_leaves(tree)
    lo = fetch_scalars({name: jnp.min(leaf) for name, leaf in named})
    hi = fetch_scalars({name: jnp.max(leaf) for name, leaf in named})
    mm = {name: (lo[name], hi[name]) for name, _ in named}
    mm["total"] = (
        min(l for l, _ in mm.values()),
        max(h for _, h in mm.values()),
    )
    return mm
