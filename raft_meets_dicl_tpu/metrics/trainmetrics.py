"""Training-state metrics: loss, learning rate, gradient/parameter stats.

The reference versions hook a live torch module/optimizer
(src/metrics/loss.py, lr.py, grad.py, param.py); here the equivalent state
arrives as pytrees + a float lr in the ``MetricContext``. Parameter
selection semantics ('total' | 'all' | [names] | {group: [prefixes]})
match the reference exactly.
"""

from typing import List, Union

import numpy as np

from . import functional as F
from .common import Metric


class Loss(Metric):
    type = "loss"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "Loss"))

    def __init__(self, key: str = "Loss"):
        self.key = key

    def get_config(self):
        return {"type": self.type, "key": self.key}

    def compute(self, ctx, estimate, target, valid, loss):
        return {self.key: float(loss)}


class LearningRate(Metric):
    type = "learning-rate"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "LearningRate"))

    def __init__(self, key: str = "LearningRate"):
        self.key = key

    def get_config(self):
        return {"type": self.type, "key": self.key}

    def compute(self, ctx, estimate, target, valid, loss):
        return {self.key: float(ctx.lr) if ctx.lr is not None else float("nan")}

    def reduce(self, values):
        return {k: vs[-1] for k, vs in values.items()}


def _normalize_params(params):
    if not isinstance(params, (list, dict)) and params != "all":
        return [params]
    return params


class _TreeMetric(Metric):
    """Shared parameter-selection logic over a named-stat dict."""

    def __init__(self, key, params):
        self.key = key
        self.params = _normalize_params(params)

    def get_config(self):
        return {"type": self.type, "key": self.key, "parameters": self.params}

    def _tree(self, ctx):
        raise NotImplementedError

    def _select(self, stats, collect):
        """stats: {name: stat}; collect(list-of-stats) aggregates a group."""
        if self.params == "all":
            return dict(stats)
        if isinstance(self.params, dict):
            out = {}
            for group, prefixes in self.params.items():
                # a group of exactly ['total'] passes the synthetic whole-
                # tree aggregate through (the reference configs' convention,
                # cfg/inspect/detailed-ctf3.yaml)
                if list(prefixes) == ["total"]:
                    out[group] = stats["total"]
                    continue
                # each leaf counts once even if several prefixes match, and
                # the synthetic 'total' aggregate never joins a group
                sel = [v for k, v in stats.items()
                       if k != "total" and any(k.startswith(p) for p in prefixes)]
                if not sel:
                    raise ValueError(
                        f"metric '{self.type}': parameter group '{group}' "
                        f"(prefixes {prefixes}) matches no parameter; "
                        f"available: {sorted(stats)[:10]}..."
                    )
                out[group] = collect(sel)
            return out
        return {name: stats[name] for name in self.params}


class GradientNorm(_TreeMetric):
    type = "grad-norm"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "GradientNorm/"), float(cfg.get("ord", 2)),
                   cfg.get("parameters", "total"))

    def __init__(self, key: str = "GradientNorm/", ord: float = 2,
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, params)
        self.ord = ord

    def get_config(self):
        return super().get_config() | {"ord": self.ord}

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.grads is None:
            return {}
        norms = F.tree_norm(ctx.grads, self.ord)
        sel = self._select(
            norms,
            lambda ns: float(np.linalg.norm(np.asarray(ns), ord=self.ord)),
        )
        return {f"{self.key}{k}": v for k, v in sel.items()}

    def reduce(self, values):
        return {k: vs[-1] for k, vs in values.items()}


class GradientMean(_TreeMetric):
    type = "grad-mean"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "GradientMean/"), cfg.get("parameters", "total"))

    def __init__(self, key: str = "GradientMean/",
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, params)

    @staticmethod
    def _collect(stats):
        total = sum(n for n, _ in stats) or 1
        return (total, sum((n / total) * m for n, m in stats))

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.grads is None:
            return {}
        mean = F.tree_mean(ctx.grads)
        sel = self._select(mean, self._collect)
        return {f"{self.key}{k}": m for k, (_, m) in sel.items()}

    def reduce(self, values):
        return {k: vs[-1] for k, vs in values.items()}


class GradientMinMax(_TreeMetric):
    type = "grad-minmax"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "GradientMinMax/"), cfg.get("parameters", "total"))

    def __init__(self, key: str = "GradientMinMax/",
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, params)

    @staticmethod
    def _collect(stats):
        return (min(lo for lo, _ in stats), max(hi for _, hi in stats))

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.grads is None:
            return {}
        mm = self._select(F.tree_minmax(ctx.grads), self._collect)
        out = {f"{self.key}{k}/min": lo for k, (lo, _) in mm.items()}
        out |= {f"{self.key}{k}/max": hi for k, (_, hi) in mm.items()}
        return out

    def reduce(self, values):
        out = {}
        for k, vs in values.items():
            out[k] = min(vs) if k.endswith("/min") else max(vs)
        return out


class ParameterNorm(GradientNorm):
    type = "param-norm"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "ParameterNorm/"), float(cfg.get("ord", 2)),
                   cfg.get("parameters", "total"))

    def __init__(self, key: str = "ParameterNorm/", ord: float = 2,
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, ord, params)

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.params is None:
            return {}
        norms = F.tree_norm(ctx.params, self.ord)
        sel = self._select(
            norms,
            lambda ns: float(np.linalg.norm(np.asarray(ns), ord=self.ord)),
        )
        return {f"{self.key}{k}": v for k, v in sel.items()}


class ParameterMean(GradientMean):
    type = "param-mean"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "ParameterMean/"), cfg.get("parameters", "total"))

    def __init__(self, key: str = "ParameterMean/",
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, params)

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.params is None:
            return {}
        mean = F.tree_mean(ctx.params)
        sel = self._select(mean, self._collect)
        return {f"{self.key}{k}": m for k, (_, m) in sel.items()}


class ParameterMinMax(GradientMinMax):
    type = "param-minmax"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("key", "ParameterMinMax/"), cfg.get("parameters", "total"))

    def __init__(self, key: str = "ParameterMinMax/",
                 params: Union[str, List[str]] = "total"):
        super().__init__(key, params)

    def compute(self, ctx, estimate, target, valid, loss):
        if ctx.params is None:
            return {}
        mm = self._select(F.tree_minmax(ctx.params), self._collect)
        out = {f"{self.key}{k}/min": lo for k, (lo, _) in mm.items()}
        out |= {f"{self.key}{k}/max": hi for k, (_, hi) in mm.items()}
        return out
