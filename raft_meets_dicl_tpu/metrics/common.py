"""Metric registry base + collection/collector machinery.

Mirrors the reference's config-constructible metric protocol
(src/metrics/common.py:5-41) and the eval-side Collector pipeline
(src/cmd/eval.py:22-109), reshaped for the pure-function world: instead of
a live torch module + optimizer, ``compute`` receives a ``MetricContext``
carrying the current params/grads pytrees and learning rate.
"""

from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, List, Optional

import numpy as np


@dataclass
class MetricContext:
    """What train-time metrics may look at besides estimate/target.

    ``params``/``grads`` are pytrees (host or device); ``lr`` is the current
    learning rate. Eval-time metrics receive an empty context.
    """

    lr: Optional[float] = None
    params: Any = None
    grads: Any = None


class Metric:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid metric type '{cfg['type']}', expected '{cls.type}'"
            )

    @classmethod
    def from_config(cls, cfg):
        from . import flowmetrics, trainmetrics

        types = [
            flowmetrics.EndPointError,
            flowmetrics.FlAll,
            flowmetrics.AverageAngularError,
            flowmetrics.FlowMagnitude,
            trainmetrics.Loss,
            trainmetrics.LearningRate,
            trainmetrics.GradientNorm,
            trainmetrics.GradientMean,
            trainmetrics.GradientMinMax,
            trainmetrics.ParameterNorm,
            trainmetrics.ParameterMean,
            trainmetrics.ParameterMinMax,
        ]
        types = {t.type: t for t in types}

        return types[cfg["type"]].from_config(cfg)

    def get_config(self):
        raise NotImplementedError

    def compute(self, ctx, estimate, target, valid, loss):
        """Compute {key: float}. ``estimate``/``target`` are NHWC flow
        arrays (batched or single), ``valid`` the matching mask."""
        raise NotImplementedError

    def __call__(self, ctx, estimate, target, valid, loss):
        return self.compute(ctx, estimate, target, valid, loss)

    def reduce(self, values):
        """Reduce accumulated per-step value lists {key: [floats]}."""
        return {k: float(np.mean(vs)) for k, vs in values.items()}


class Metrics:
    """Ordered list of metrics evaluated together (src/cmd/eval.py:93-109)."""

    @classmethod
    def from_config(cls, cfg):
        return cls([Metric.from_config(c) for c in cfg])

    def __init__(self, metrics: List[Metric]):
        self.metrics = list(metrics)

    def get_config(self):
        return [m.get_config() for m in self.metrics]

    def __call__(self, ctx, estimate, target, valid, loss):
        result = OrderedDict()
        for metric in self.metrics:
            result.update(metric(ctx, estimate, target, valid, loss))
        return result


class Collector:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid collector type '{cfg['type']}', expected '{cls.type}'"
            )

    @classmethod
    def from_config(cls, cfg):
        types = {MeanCollector.type: MeanCollector}
        return types[cfg["type"]].from_config(cfg)

    def collect(self, metrics):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    def __call__(self, metrics):
        self.collect(metrics)


class MeanCollector(Collector):
    """Running per-key mean over collected metric dicts, NaN-skipping
    (src/cmd/eval.py:46-74)."""

    type = "mean"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls()

    def __init__(self):
        self.results = OrderedDict()

    def collect(self, metrics):
        for k, v in metrics.items():
            if np.isnan(v):
                continue
            self.results.setdefault(k, []).append(v)

    def result(self):
        return OrderedDict((k, float(np.mean(vs))) for k, vs in self.results.items())


class Collectors:
    @classmethod
    def from_config(cls, cfg):
        return cls([Collector.from_config(c) for c in cfg])

    def __init__(self, collectors: List[Collector]):
        self.collectors = list(collectors)

    def collect(self, metrics):
        for collector in self.collectors:
            collector.collect(metrics)

    def results(self):
        return {c.type: c.result() for c in self.collectors}
