"""Metric registry (reference src/metrics/): EPE, Fl-all, AAE, flow
magnitude, loss, learning rate, gradient/parameter statistics."""

from . import functional
from .common import (
    Collector,
    Collectors,
    MeanCollector,
    Metric,
    MetricContext,
    Metrics,
)
from .flowmetrics import AverageAngularError, EndPointError, FlAll, FlowMagnitude
from .trainmetrics import (
    GradientMean,
    GradientMinMax,
    GradientNorm,
    LearningRate,
    Loss,
    ParameterMean,
    ParameterMinMax,
    ParameterNorm,
)

__all__ = [
    "functional",
    "Collector",
    "Collectors",
    "MeanCollector",
    "Metric",
    "MetricContext",
    "Metrics",
    "AverageAngularError",
    "EndPointError",
    "FlAll",
    "FlowMagnitude",
    "GradientMean",
    "GradientMinMax",
    "GradientNorm",
    "LearningRate",
    "Loss",
    "ParameterMean",
    "ParameterMinMax",
    "ParameterNorm",
]
