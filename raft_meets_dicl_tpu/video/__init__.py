"""Streaming-video engine: temporal warm-start over frame sequences.

The single-pair estimator becomes a video engine by composition:

- ``warmstart`` — forward flow projection across frames (the host twin
  of the projection baked into ``evaluation.make_warm_fn``'s registered
  warm-start programs);
- ``sequence`` — the sequence runner: full-budget cold frame 0, then
  warm frames entering at the bottom ladder rung with the previous
  frame's carry, escalating by the serve ladder's delta policy; plus
  the doubled-batch fw/bw dispatch helper;
- ``products`` — forwards-backwards consistency products (occlusion
  masks + confidence) from fetched flow pairs, host-side numpy;
- ``cache`` — the bounded, TTL-evicted per-client session store the
  serve scheduler keys warm-start state on.
"""

from .cache import CarryMismatch, SessionCache
from .products import fw_bw_products, fw_bw_products_batch, warp_flow
from .sequence import (FrameResult, SequenceResult, SequenceRunner,
                       fw_bw_flows)
from .warmstart import project_flow

__all__ = [
    "CarryMismatch",
    "SessionCache",
    "fw_bw_products",
    "fw_bw_products_batch",
    "warp_flow",
    "FrameResult",
    "SequenceResult",
    "SequenceRunner",
    "fw_bw_flows",
    "project_flow",
]
