"""Forwards-backwards consistency products: occlusion masks + confidence.

Running the estimator both ways over a frame pair — forward
``flow_fw = F(img1, img2)`` and backward ``flow_bw = F(img2, img1)`` —
buys a per-pixel consistency signal for free: where both directions see
the same surface, ``flow_fw(p) + flow_bw(p + flow_fw(p)) ≈ 0``; where a
pixel is occluded in the second frame (or the estimate is just wrong),
the round trip does not return home. The classic criterion (Sundaram,
Brox & Keutzer, ECCV 2010) thresholds the squared round-trip error
against a motion-magnitude-relative bound:

    |fw + bw∘fw|²  >  alpha * (|fw|² + |bw∘fw|²) + beta

Everything here is host-side numpy on fetched flows: the serve path
computes fw and bw by running the *same compiled program* on the
swapped pair (no new shapes, no new programs), and the consistency
products are cheap O(HW) host math per request — putting them on device
would add program variants for a bandwidth-trivial computation.
"""

import numpy as np

DEFAULT_ALPHA = 0.01
DEFAULT_BETA = 0.5


def warp_flow(flow_b, flow_a):
    """Backward-warp ``flow_b`` along ``flow_a``: ``out(p) =
    flow_b(p + flow_a(p))`` bilinearly, plus an in-bounds mask.

    flow_a, flow_b: (H, W, 2) float arrays, channel 0 = x. Returns
    ``(warped (H, W, 2), inside (H, W) bool)``; samples falling outside
    the image are zero-filled and flagged outside.
    """
    flow_a = np.asarray(flow_a, np.float32)
    flow_b = np.asarray(flow_b, np.float32)
    h, w = flow_a.shape[:2]
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float32),
                         np.arange(w, dtype=np.float32), indexing="ij")
    x = xs + flow_a[..., 0]
    y = ys + flow_a[..., 1]
    inside = (x >= 0) & (x <= w - 1) & (y >= 0) & (y <= h - 1)

    x0 = np.clip(np.floor(x), 0, w - 2).astype(np.int64)
    y0 = np.clip(np.floor(y), 0, h - 2).astype(np.int64)
    fx = np.clip(x - x0, 0.0, 1.0)[..., None]
    fy = np.clip(y - y0, 0.0, 1.0)[..., None]

    v00 = flow_b[y0, x0]
    v01 = flow_b[y0, x0 + 1]
    v10 = flow_b[y0 + 1, x0]
    v11 = flow_b[y0 + 1, x0 + 1]
    warped = ((1 - fy) * ((1 - fx) * v00 + fx * v01)
              + fy * ((1 - fx) * v10 + fx * v11))
    return np.where(inside[..., None], warped, 0.0), inside


def fw_bw_products(flow_fw, flow_bw, alpha=DEFAULT_ALPHA,
                   beta=DEFAULT_BETA):
    """Occlusion mask + confidence from a forward/backward flow pair.

    flow_fw, flow_bw: (H, W, 2). Returns ``(occlusion (H, W) bool,
    confidence (H, W) float32 in (0, 1])`` in the *first* frame's
    coordinates. Pixels whose forward flow leaves the image are
    occluded by definition (nothing to check against); confidence is
    ``1 / (1 + round_trip_err²)`` so consistent pixels sit near 1 and
    the scale degrades smoothly rather than cliffing at the mask
    threshold.
    """
    flow_fw = np.asarray(flow_fw, np.float32)
    flow_bw = np.asarray(flow_bw, np.float32)
    if flow_fw.shape != flow_bw.shape or flow_fw.shape[-1] != 2:
        raise ValueError(
            f"flow pair must share an (H, W, 2) shape, got "
            f"{flow_fw.shape} vs {flow_bw.shape}")
    bw_at_fw, inside = warp_flow(flow_bw, flow_fw)
    diff = flow_fw + bw_at_fw
    err2 = np.sum(diff * diff, axis=-1)
    mag2 = (np.sum(flow_fw * flow_fw, axis=-1)
            + np.sum(bw_at_fw * bw_at_fw, axis=-1))
    occluded = (err2 > alpha * mag2 + beta) | ~inside
    confidence = (1.0 / (1.0 + err2)).astype(np.float32)
    confidence[~inside] = 0.0
    return occluded, confidence


def fw_bw_products_batch(flow_fw, flow_bw, alpha=DEFAULT_ALPHA,
                         beta=DEFAULT_BETA):
    """Batched :func:`fw_bw_products`: (B, H, W, 2) pairs -> stacked
    (B, H, W) masks/confidences."""
    occ, conf = zip(*(fw_bw_products(f, b, alpha=alpha, beta=beta)
                      for f, b in zip(flow_fw, flow_bw)))
    return np.stack(occ), np.stack(conf)
