"""Sequence runner: temporal warm-start over a frame sequence.

Walks consecutive frame pairs of one video carrying the previous
frame's coarse flow (and optionally the GRU hidden state) into the next
frame's recurrence:

- **frame 0** runs the monolithic full-budget rung program — there is
  no prior, it pays the full iteration count;
- **warm frames** enter through the registered warm-start program
  (:func:`evaluation.make_warm_fn`: bottom ladder rung, previous flow
  forward-projected inside the program) and escalate through the
  existing ``cont=True`` continuation rungs only while the batch's
  flow-delta norm still exceeds the ladder threshold — exactly the
  serve path's balanced-class policy, so a well-predicted frame stops
  at the bottom rung and a cut/occlusion-heavy frame pays more.

Every program involved is a registered ``rung_step`` variant over the
same bucket set: the whole sequence is recompile-free by construction
after the first frame of each mode, and ``warm_pool()``/``--prebuild``
cover the variants for serving.

The runner measures what the warm-start claim needs measuring:
per-frame iterations actually spent, wall seconds, and EPE when ground
truth is supplied — the EPE-vs-iterations evidence that warm frames
reach full-budget quality from the bottom rung. One ``video`` telemetry
event per frame plus a sequence summary event.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import evaluation, telemetry
from ..serve.ladder import LadderSpec
from .warmstart import project_flow


def fw_bw_flows(step, variables, img1, img2):
    """Forward and backward flow in one doubled-batch program call.

    Concatenates ``[img1; img2]`` against ``[img2; img1]`` on the batch
    axis and runs the *existing* step once — the fw/bw product costs one
    dispatch at 2x batch instead of two, and no new program kind. Use
    offline (eval CLI, bench) where the doubled batch shape is free to
    compile once; the serve path instead issues two same-shape calls to
    stay inside its prebuilt bucket programs.

    ``step`` is any ``(variables, a, b) -> (flow, ...)`` program (eval or
    rung). Returns ``(flow_fw, flow_bw)`` with the input batch size.
    """
    b = img1.shape[0]
    a = jnp.concatenate([img1, img2], axis=0)
    c = jnp.concatenate([img2, img1], axis=0)
    out = step(variables, a, c)
    flow = out[0] if isinstance(out, tuple) else out
    return flow[:b], flow[b:]


@dataclass
class FrameResult:
    """One estimated frame pair of a sequence run."""
    frame: int
    flow: np.ndarray          # full-resolution (B, H, W, 2)
    warm: bool
    iterations: int
    rungs: int
    seconds: float
    epe: Optional[float] = None
    carry: Any = None         # device-side {"flow", "hidden", "delta"}


@dataclass
class SequenceResult:
    """A full sequence run: per-frame results + aggregate accounting."""
    frames: List[FrameResult] = field(default_factory=list)
    seconds: float = 0.0

    @property
    def flows(self):
        return [f.flow for f in self.frames]

    def mean_iterations(self):
        if not self.frames:
            return 0.0
        return sum(f.iterations for f in self.frames) / len(self.frames)

    def mean_epe(self):
        vals = [f.epe for f in self.frames if f.epe is not None]
        return sum(vals) / len(vals) if vals else None

    def frames_per_sec(self):
        return len(self.frames) / self.seconds if self.seconds > 0 else 0.0

    def warm_frames(self):
        return sum(1 for f in self.frames if f.warm)


class SequenceRunner:
    """Builds the rung/warm program set once, then runs sequences.

    ``ladder`` defaults to the configured :class:`LadderSpec` (RMD_LADDER
    / RMD_LADDER_THRESHOLD): warm frames start at ``rungs[0]`` and may
    escalate through the continuation increments up to ``rungs[-1]``;
    cold frames run the monolithic ``rungs[-1]`` program.

    ``carry_hidden=True`` additionally threads the GRU hidden state
    across frames: warm frames then enter through a ``cont=True`` rung
    program fed ``(project_flow(prev_flow), prev_hidden)`` instead of
    the flow-only warm program. This trades the zero-init bit-parity
    guarantee (a carried hidden has no cold equivalent) for a better
    prior; the default keeps parity.
    """

    def __init__(self, model, variables, ladder=None, model_id=None,
                 model_args=None, mesh=None, wire=None,
                 carry_hidden=False):
        self.model = model
        self.variables = variables
        self.ladder = ladder if ladder is not None else LadderSpec.from_config()
        self.carry_hidden = bool(carry_hidden)
        kw = dict(model_id=model_id, model_args=model_args, mesh=mesh,
                  wire=wire)
        lad = self.ladder
        self._full = evaluation.make_rung_fn(model, lad.rungs[-1], **kw)
        self._warm = evaluation.make_warm_fn(model, lad.rungs[0], **kw)
        self._conts = {
            inc: evaluation.make_rung_fn(model, inc, cont=True, **kw)
            for inc in sorted(set(lad.increments()))}
        if self.carry_hidden:
            # warm entry via a base-rung-sized continuation program
            self._warm_cont = evaluation.make_rung_fn(
                model, lad.rungs[0], cont=True, **kw)

    def programs(self):
        """Every program the runner can execute (compile accounting)."""
        progs = [self._full, self._warm, *self._conts.values()]
        if self.carry_hidden:
            progs.append(self._warm_cont)
        return progs

    def compiles(self):
        return sum(getattr(p, "compiles", 0) for p in self.programs())

    def _epe(self, flow, target, valid=None):
        d = np.asarray(flow, np.float32) - np.asarray(target, np.float32)  # graftlint: disable=host-sync -- EPE accounting is host math on an already-measured frame
        err = np.sqrt(np.sum(d * d, axis=-1))
        if valid is not None:
            v = np.asarray(valid, bool)  # graftlint: disable=host-sync -- valid masks are host numpy inputs
            return float(err[v].mean()) if v.any() else float("nan")
        return float(err.mean())

    def _run_frame(self, i1, i2, carry):
        """One frame pair: (flow, state, warm, iterations, rungs)."""
        lad = self.ladder
        if carry is None:
            flow, state = self._full(self.variables, i1, i2)
            return flow, state, False, lad.rungs[-1], 1
        if self.carry_hidden:
            init = project_flow(carry["flow"])
            flow, state = self._warm_cont(self.variables, i1, i2, init,
                                          carry["hidden"])
        else:
            flow, state = self._warm(self.variables, i1, i2, carry["flow"])
        executed, rungs = lad.rungs[0], 1
        for inc in lad.increments():
            worst = float(np.max(np.asarray(state["delta"])))  # graftlint: disable=host-sync -- the escalation decision needs the delta norm on host (same policy as serve's balanced class)
            if worst <= lad.threshold:
                break
            flow, state = self._conts[inc](self.variables, i1, i2,
                                           state["flow"], state["hidden"])
            executed += inc
            rungs += 1
        return flow, state, True, executed, rungs

    def run(self, frames, targets=None, valids=None, warm=True,
            keep_flows=True):
        """Walk ``frames`` (list of (B, H, W, 3) arrays) pairwise.

        ``targets``/``valids`` optionally supply per-pair ground truth
        (len(frames) - 1 entries) for EPE accounting. ``warm=False``
        runs every pair cold through the full program — the baseline arm
        of the cold-vs-warm comparison. Returns a
        :class:`SequenceResult`.
        """
        if len(frames) < 2:
            raise ValueError("a sequence needs at least two frames")
        tele = telemetry.get()
        result = SequenceResult()
        t_seq = time.perf_counter()
        carry = None
        for t in range(len(frames) - 1):
            i1 = jnp.asarray(frames[t])
            i2 = jnp.asarray(frames[t + 1])
            t0 = time.perf_counter()
            flow, state, was_warm, its, rungs = self._run_frame(
                i1, i2, carry if warm else None)
            jax.block_until_ready(flow)  # graftlint: disable=host-sync -- per-frame wall seconds are the measurement this runner exists for
            dt = time.perf_counter() - t0
            epe = None
            if targets is not None:
                epe = self._epe(flow, targets[t],
                                None if valids is None else valids[t])
            fr = FrameResult(
                frame=t, flow=np.asarray(flow) if keep_flows else None,  # graftlint: disable=host-sync -- keep_flows opts into fetching results
                warm=was_warm, iterations=its, rungs=rungs,
                seconds=dt, epe=epe, carry=state)
            result.frames.append(fr)
            if tele.enabled:
                tele.emit("video", event="frame", frame=t, warm=was_warm,
                          iterations=its, rungs=rungs,
                          seconds=round(dt, 6),
                          **({} if epe is None else {"epe": round(epe, 4)}))
            carry = state
        result.seconds = time.perf_counter() - t_seq
        if tele.enabled:
            mean_epe = result.mean_epe()
            tele.emit(
                "video", event="sequence", frames=len(result.frames),
                warm_frames=result.warm_frames(),
                mean_iterations=round(result.mean_iterations(), 2),
                frames_per_sec=round(result.frames_per_sec(), 3),
                seconds=round(result.seconds, 4),
                **({} if mean_epe is None
                   else {"mean_epe": round(mean_epe, 4)}))
        return result
