"""Bounded, TTL-evicted per-client video session state.

A video stream served through the scheduler is a *sticky session*: the
client id that already orders dispatches (PR 10 stickiness) also keys
the warm-start state — the previous frame's coarse flow carry, left as
the serve path fetched it. The cache is deliberately conservative:

- **bounded** (``RMD_VIDEO_SESSIONS``, LRU past capacity) so a scrape of
  short-lived clients cannot grow host memory without limit;
- **TTL-evicted** (``RMD_VIDEO_SESSION_TTL_S``) so a stream that stalls
  longer than the TTL restarts cold — stale motion is worse than no
  prior;
- **shape-checked** on lookup, so a client that switches resolution
  mid-stream degrades to the cold path instead of feeding a mis-shaped
  carry into a warm program.

A miss of any kind returns None and the caller starts from zero flow —
bit-exact with the plain program, so warm-start is purely an
optimization, never a correctness hazard. Hits/misses/evictions are
counted as ``rmd_serve_session_*`` metrics and ``session`` telemetry
events.
"""

import base64
import threading
import time
import zlib

import numpy as np

from .. import telemetry
from ..telemetry import metrics as metrics_mod
from ..utils import env


class CarryMismatch(ValueError):
    """An imported carry snapshot failed validation (shape/dtype/CRC):
    the receiving replica must start the session cold rather than feed a
    damaged or mis-shaped carry into a warm program."""


class SessionCache:
    """Client-id-keyed warm-start store: ``put(client, flow)`` after a
    frame completes, ``get(client, shape)`` before the next dispatch.

    ``flow`` is whatever coarse-grid carry the serve path fetched
    (host numpy); ``shape`` is the expected carry shape — a mismatch is
    a miss. Thread-safe: the scheduler's dispatch loop and completion
    callbacks touch it from different threads.
    """

    def __init__(self, capacity=None, ttl_s=None, clock=time.monotonic):
        self.capacity = int(capacity if capacity is not None
                            else env.get_int("RMD_VIDEO_SESSIONS"))
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else env.get_float("RMD_VIDEO_SESSION_TTL_S"))
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if self.ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {self.ttl_s}")
        self._clock = clock
        self._lock = threading.Lock()
        self._entries = {}  # client -> (flow, t_touch); dict order = LRU
        reg = metrics_mod.registry()
        self._m_hits = reg.counter(
            "rmd_serve_session_warm_hits_total",
            "video session lookups that served warm-start state")
        self._m_misses = reg.counter(
            "rmd_serve_session_misses_total",
            "video session lookups that fell back to a cold start")
        self._m_evictions = reg.counter(
            "rmd_serve_session_evictions_total",
            "video sessions dropped by TTL expiry or capacity LRU")
        self._m_active = reg.gauge(
            "rmd_serve_session_active", "live video sessions in the cache")

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def _emit(self, event, client, **fields):
        tele = telemetry.get()
        if tele.enabled:
            tele.emit("session", event=event, client=client, **fields)

    def _expire_locked(self, now):
        dead = [c for c, (_, t) in self._entries.items()
                if now - t > self.ttl_s]
        for c in dead:
            del self._entries[c]
        return dead

    def get(self, client, shape=None):
        """The client's cached carry flow, or None (cold start).

        Expired entries are dropped on the way; a shape mismatch drops
        the entry too (the old resolution's carry is useless now).
        """
        now = self._clock()
        with self._lock:
            expired = self._expire_locked(now)
            entry = self._entries.pop(client, None)
            if entry is not None and shape is not None \
                    and tuple(entry[0].shape) != tuple(shape):
                entry = None  # resolution switch: restart cold
            if entry is not None:
                # touch: re-insert at the MRU end
                self._entries[client] = (entry[0], now)
            active = len(self._entries)
        for c in expired:
            self._m_evictions.inc()
            self._emit("evict", c, reason="ttl")
        self._m_active.set(active)
        if entry is None:
            self._m_misses.inc()
            self._emit("miss", client)
            return None
        self._m_hits.inc()
        self._emit("hit", client)
        return entry[0]

    def put(self, client, flow):
        """Store the just-completed frame's carry for the client."""
        now = self._clock()
        evicted = []
        with self._lock:
            expired = self._expire_locked(now)
            self._entries.pop(client, None)
            while len(self._entries) >= self.capacity:
                lru = next(iter(self._entries))
                del self._entries[lru]
                evicted.append(lru)
            self._entries[client] = (flow, now)
            active = len(self._entries)
        for c in expired:
            self._m_evictions.inc()
            self._emit("evict", c, reason="ttl")
        for c in evicted:
            self._m_evictions.inc()
            self._emit("evict", c, reason="capacity")
        self._m_active.set(active)

    def drop(self, client):
        """Explicitly end a session (stream closed)."""
        with self._lock:
            had = self._entries.pop(client, None) is not None
            active = len(self._entries)
        self._m_active.set(active)
        return had

    def clients(self):
        """Live (unexpired) client ids, LRU to MRU — what a draining
        replica must hand off."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            return list(self._entries)

    # -- handoff snapshots ----------------------------------------------------

    def export_carry(self, client):
        """Serializable snapshot of the client's carry, or None.

        The snapshot is a plain JSON-safe dict — shape, dtype, CRC32 and
        base64 payload — so it can cross a process boundary on the fleet
        handoff path (``/sessionz``). Validation happens on import; the
        exporting side never mutates the session (the source replica
        keeps serving until the router flips affinity).
        """
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            entry = self._entries.get(client)
            if entry is None:
                return None
            flow = entry[0]
        flow = np.ascontiguousarray(flow)
        raw = flow.tobytes()
        return {
            "client": client,
            "shape": list(flow.shape),
            "dtype": str(flow.dtype),
            "crc": zlib.crc32(raw),
            "data": base64.b64encode(raw).decode("ascii"),
        }

    def import_carry(self, snapshot, client=None, shape=None):
        """Install an exported snapshot as ``client``'s carry.

        Validates structure, dtype, byte length against the declared
        shape, the CRC, and (when the receiving scheduler knows its
        coarse-grid geometry) the expected carry ``shape`` — raising
        :class:`CarryMismatch` on any failure so the caller degrades the
        stream to one cold frame instead of corrupting it. Returns the
        installed carry array.
        """
        if not isinstance(snapshot, dict):
            raise CarryMismatch(f"snapshot is not an object: "
                                f"{type(snapshot).__name__}")
        missing = {"shape", "dtype", "crc", "data"} - snapshot.keys()
        if missing:
            raise CarryMismatch(f"snapshot missing {sorted(missing)}")
        client = client or snapshot.get("client")
        if not client:
            raise CarryMismatch("snapshot names no client")
        try:
            dtype = np.dtype(snapshot["dtype"])
        except TypeError as e:
            raise CarryMismatch(f"bad dtype {snapshot['dtype']!r}: {e}") \
                from e
        try:
            raw = base64.b64decode(snapshot["data"], validate=True)
        except Exception as e:  # noqa: BLE001 - any decode failure is a mismatch
            raise CarryMismatch(f"payload decode failed: {e}") from e
        declared = tuple(int(d) for d in snapshot["shape"])
        if shape is not None and declared != tuple(shape):
            raise CarryMismatch(
                f"carry shape {declared} does not match the receiving "
                f"replica's expected {tuple(shape)}")
        expect_bytes = int(np.prod(declared)) * dtype.itemsize if declared \
            else dtype.itemsize
        if len(raw) != expect_bytes:
            raise CarryMismatch(
                f"payload is {len(raw)} bytes, shape {declared} "
                f"{dtype} needs {expect_bytes}")
        if zlib.crc32(raw) != int(snapshot["crc"]):
            raise CarryMismatch("payload CRC mismatch")
        flow = np.frombuffer(raw, dtype=dtype).reshape(declared).copy()
        self.put(client, flow)
        self._emit("import", client)
        return flow
