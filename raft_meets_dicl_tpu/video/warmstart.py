"""Temporal warm-start helpers: forward flow projection across frames.

A flow field estimated for frame pair (t-1, t) is a prior for pair
(t, t+1) — but in the *previous* frame's coordinates. Following the
RAFT lineage's warm-start mode (Teed & Deng 2020), the prior must move
with the motion it describes before it can seed the next frame's
recurrence. The exact forward splat scatters; on TPU we use the cheap
backward-sampled approximation

    out(p) = flow(p - flow(p))

via the existing ``ops/warp`` machinery (``warp_backwards(flow, -flow)``
— first-order equivalent for smooth motion), with out-of-frame samples
masked to zero flow so disoccluded regions restart cold.

Two call forms exist deliberately:

- :func:`evaluation.make_warm_fn` bakes this projection *inside* the
  registered warm-start program, so the serve path hands a raw cached
  carry straight to the program (and ``flow=0`` stays bit-exact vs the
  plain rung);
- :func:`project_flow` here is the host-callable twin for flows already
  living outside a program — the sequence runner's hidden-carry mode
  feeds existing ``cont=True`` rung programs, which expect an
  already-projected ``flow_init``.

Zero flow is a fixed point of the projection (``flow(p - 0) = 0``), so
both forms degrade to the cold zero-init path identically.
"""

import jax
import jax.numpy as jnp

from ..ops import warp


@jax.jit
def project_flow(flow):
    """Forward-project a coarse flow field to the frame it points into.

    flow: (B, H, W, 2) coarse-grid flow in coarse-pixel units. Returns
    the projected field, zero where the backward sample leaves the
    image (disocclusion: no prior is better than a stale one).
    """
    flow = flow.astype(jnp.float32)
    projected, _ = warp.warp_backwards(flow, -flow)
    return projected
