"""Test-support utilities: fault injection for recovery-path testing."""

from . import faults

__all__ = ["faults"]
