"""Fault injection for exercising the recovery paths end to end.

Armed via ``RMD_FAULT``, a comma-separated list of directives::

    RMD_FAULT="nan_update@step=3,sigterm@step=5"
    RMD_FAULT="corrupt_checkpoint@nth=1;flips=16"
    RMD_FAULT="kill_worker@index=2,decode_error@index=3;times=2"

Each directive is ``name@key=value;key=value...``. Supported names and
the call sites that consult them:

``nan_update@step=N``
    strategy.training.run_instance poisons the dispatched learning rate
    with NaN at optimizer step N — the update tree goes NaN exactly like
    a NaN-gradient batch would, tripping the non-finite guard.
``sigterm@step=N``
    strategy.training.run_instance delivers SIGTERM to the own process
    at step N (mid-epoch preemption; the graceful-stop handler must
    finish the step, write an emergency checkpoint, and exit cleanly).
``corrupt_checkpoint@nth=K[;flips=B]``
    strategy.checkpoint flips ``B`` bits (default 8) in the payload of
    the K-th checkpoint written after arming (1-based) — the CRC verify
    on load must catch it and quarantine the file.
``kill_worker@index=I``
    models.mpdecode worker processes hard-exit (``os._exit``) when asked
    to decode sample index I — the pool must respawn the worker and
    recover the lost in-flight work.
``decode_error@index=I[;times=T]``
    the sample pipeline raises on sample index I, T times (default 1) —
    the loader's bounded retry / substitute path must absorb it.
``serve_malformed@index=I``
    serve.scheduler rejects request id I at admission as a malformed
    payload — the submit call must raise the typed ServeError without
    the request ever entering a queue.
``serve_oversized@index=I``
    serve.scheduler treats request id I as fitting no configured bucket
    (shape outside every bucket) — typed oversized ServeError at
    admission.
``serve_decode_error@index=I[;times=T]``
    serve.scheduler fails request id I during batch preparation — the
    request's ticket must complete with a typed decode ServeError while
    the rest of its batch still dispatches (no poisoning, no dispatch-
    loop stall).
``kill_replica@replica=R[;after=N]``
    fleet.replica hard-exits (``os._exit``) serve replica R — after it
    has *completed* N requests (default 1), so the kill lands mid-stream
    under load. The supervisor must restart it (backoff), the router
    must re-dispatch safe failures and hand off / evict its sticky
    sessions, and the rejoined replica must serve warm with zero
    compiles. Pair with ``RMD_FAULT_STATE`` so the respawned replica
    does not re-fire.
``hang_replica@replica=R[;after=N;seconds=S]``
    fleet.replica wedges replica R's request handling for S seconds
    (default 3600 — effectively forever) after N completed requests: the
    process stays up and /healthz keeps answering, but requests stall.
    Exercises the router's per-request deadline path.
``slow_replica@replica=R[;ms=M;times=T]``
    fleet.replica sleeps M ms (default 250) before handling a request on
    replica R, T times — degraded-but-alive: latency (and SLO burn)
    climbs without the process failing, which is what the burn-triggered
    drain watches for.

Firing is once per directive by default (``times`` raises the budget).
Counters are per-process; when a fault must fire exactly once *across*
processes (e.g. ``kill_worker`` in a decode pool, where the respawned
worker re-decodes the same index), set ``RMD_FAULT_STATE`` to a shared
directory — fired directives leave marker files there and every process
honors them.

Everything here is inert unless ``RMD_FAULT`` is set; the production
call sites are single dict lookups on the parsed spec.
"""

import threading
from pathlib import Path

from ..utils import env

_lock = threading.Lock()
# parsed spec cache: {spec string: [ (name, params dict), ... ]}
_parsed = {}
# per-process fire counts: {(name, param key): count}
_fired = {}


def _parse(spec):
    directives = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition("@")
        params = {}
        for kv in rest.split(";"):
            if not kv:
                continue
            k, _, v = kv.partition("=")
            try:
                params[k.strip()] = int(v)
            except ValueError:
                params[k.strip()] = v.strip()
        directives.append((name.strip(), params))
    return directives


def _directives():
    spec = env.get_str("RMD_FAULT")
    if not spec:
        return ()
    with _lock:
        if spec not in _parsed:
            _parsed[spec] = _parse(spec)
        return _parsed[spec]


def active():
    """Whether any fault directive is armed (cheap env check)."""
    return bool(env.get_str("RMD_FAULT"))


def reset():
    """Forget per-process fire counts (test isolation)."""
    with _lock:
        _fired.clear()
        _parsed.clear()


def _marker(name, params):
    state = env.raw("RMD_FAULT_STATE")
    if not state:
        return None
    key = "-".join(f"{k}{v}" for k, v in sorted(params.items()))
    return Path(state) / f"fired-{name}-{key}"


def fire(name, **match):
    """Consume one firing of directive ``name`` if its parameters match.

    ``match`` gives the call site's current coordinates (``step=``,
    ``index=``, ``nth=``); a directive fires when every parameter it
    pins (other than ``times``) equals the given value. Returns the
    directive's params dict when it fires, else None.
    """
    if not active():
        return None
    for dname, params in _directives():
        if dname != name:
            continue
        if any(params.get(k) != v for k, v in match.items() if k in params):
            continue
        times = params.get("times", 1)
        key = (name, tuple(sorted(params.items())))
        marker = _marker(name, params)
        with _lock:
            if marker is not None:
                # cross-process once-only: the marker directory is the
                # shared consumed-state (a respawned decode worker must
                # not re-fire on the resubmitted sample)
                try:
                    marker.touch(exist_ok=False)
                except FileExistsError:
                    continue
                except OSError:
                    continue
            else:
                if _fired.get(key, 0) >= times:
                    continue
                _fired[key] = _fired.get(key, 0) + 1
        return params
    return None


def corrupt_file(path, flips=8, offset=64):
    """Flip ``flips`` bits spread across the file's payload region.

    Deterministic (position-derived) so tests are reproducible; starts
    at ``offset`` to land in the serialized payload rather than the
    header magic, and clusters near the start so truncated/partial
    reads also see the damage.
    """
    path = Path(path)
    raw = bytearray(path.read_bytes())
    if len(raw) <= offset:
        offset = 0
    span = max(1, len(raw) - offset)
    for i in range(flips):
        pos = offset + (i * 97) % span
        raw[pos] ^= 1 << (i % 8)
    path.write_bytes(bytes(raw))
    return path
