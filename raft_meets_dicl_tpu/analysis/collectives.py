"""Sharding-contract auditor: expected vs actual collective schedule.

The PR-6 ZeRO execution model is a *contract*: params stored sharded are
**all-gathered** once per step for the pure data-parallel
forward/backward, then gradients are **reduced** back onto the param
shards for the shard-local optimizer update. ``analysis.hlo`` already
checks the gather/reduce pair *exists*; this module derives the full
expected schedule — which phases, in what order, moving how many bytes —
from the :class:`parallel.partition.Partitioner` rules + the actual
parameter tree, and diffs it against the collective sequence GSPMD
really emitted into the compiled HLO.

What the diff catches, each with a prior in this repo's history:

- **collective-missing** — a partition rule stops matching (module
  rename, regex typo) and the param gather silently disappears: params
  replicate again and the per-chip HBM win evaporates with no error.
  Detected by *volume collapse*, not mere absence: even a fully
  replicated program carries a few incidental small all-gathers (GSPMD
  boundary handling on the batch-sharded spatial ops — measured on the
  flagship), so the check is "actual gather volume fell below half the
  sharded-parameter mass". Symmetrically, a vanished grad reduce means
  shards silently diverge.
- **collective-doubled** — PR 6 paid for a GSPMD miscompile that
  reduced gradients *twice* (double-counted all-reduce); actual reduce
  bytes ≫ the parameter mass is exactly that signature.
- **collective-order** — a gather scheduled after the reduces it feeds
  means the program is no longer the gather-compute form at all.

Two drift classes deliberately live in the *pinned budget*
(``analysis.cost.Budget``), not here: byte growth within the contract,
and resharding-op growth (``all-to-all``/``collective-permute``). The
healthy flagship programs legitimately contain a handful of permutes
(GSPMD halo/boundary movement on batch-sharded spatial ops), so "any
permute is a bug" would be red on day one; "more permutes than the
pinned count" is the actionable signal.
"""

import re
from dataclasses import dataclass, field

from .lint import Finding

# one compiled-HLO collective op line, e.g.
#   %all-gather.3 = f32[16,64]{0,1} all-gather(f32[2,64]{0,1} %p), ...
# async "-start" forms return a tuple whose last element is the output;
# "-done" lines just unwrap it and are skipped to avoid double counting.
_COLL_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute)(-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
    "c64": 8, "c128": 16,
    # sub-f32 widths (compiled-HLO spellings): quantized-tier volumes
    # and f8 recipes must not fall through to the 4-byte unknown default
    "f8e4m3": 1, "f8e3m4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3b11fnuz": 1, "f8e8m0fnu": 1,
}

# sub-byte element widths in bits; byte counts round up per shape
_DTYPE_BITS = {"s4": 4, "u4": 4, "s2": 2, "u2": 2}

REDUCE_OPS = ("all-reduce", "reduce-scatter")
RESHARD_OPS = ("all-to-all", "collective-permute")

# doubled-reduction threshold: actual reduce volume this many times the
# expected gradient mass flags the PR-6 double-reduce signature. The
# slack absorbs the legitimate small extras (global-norm scalars, loss
# metrics, counter syncs) riding the same schedule — measured 1.27x on
# the healthy (4, 2)-mesh flagship train step.
DOUBLED_FACTOR = 1.8

# gather-collapse threshold: the param all-gather phase counts as
# *missing* when its actual volume falls below this fraction of the
# sharded-parameter mass (incidental boundary gathers survive even in a
# fully replicated program, so absence alone is not the signal; the
# healthy sharded step runs at ~1.1x expected)
GATHER_COLLAPSE = 0.5


def _shape_bytes(dtype, dims):
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)  # graftlint: disable=host-sync -- parses an HLO shape string, not a device value
    if dtype in _DTYPE_BITS:
        return (n * _DTYPE_BITS[dtype] + 7) // 8
    return n * _DTYPE_BYTES.get(dtype, 4)


@dataclass
class CollectiveOp:
    """One collective instruction in compiled-HLO schedule order."""
    op: str
    index: int   # position in the schedule (line order)
    bytes: int   # result buffer volume (output element of async tuples)

    def to_dict(self):
        return {"op": self.op, "index": self.index, "bytes": self.bytes}


def parse_schedule(text):
    """Collective ops of a compiled (post-GSPMD) HLO module, in schedule
    order, each with its result-buffer byte volume.

    The result type precedes the op name on an HLO instruction line; for
    async ``-start`` tuples the *last* shaped buffer is the op's output
    (the leading elements alias the operands), and ``-done`` lines are
    skipped — they unwrap a start op already counted.
    """
    ops = []
    for line in text.splitlines():
        if " = " not in line:
            continue
        lhs, rhs = line.split(" = ", 1)
        m = _COLL_OP_RE.search(rhs)
        if not m or m.group(2) == "-done":
            continue
        result = rhs[:m.start()]
        shapes = _SHAPE_RE.findall(result)
        nbytes = _shape_bytes(*shapes[-1]) if shapes else 0
        ops.append(CollectiveOp(op=m.group(1), index=len(ops),
                                bytes=nbytes))
    return ops


def summarize_schedule(schedule):
    counts, volumes = {}, {}
    for op in schedule:
        counts[op.op] = counts.get(op.op, 0) + 1
        volumes[op.op] = volumes.get(op.op, 0) + op.bytes
    return {
        "counts": counts,
        "bytes": volumes,
        "total_bytes": sum(volumes.values()),
        "order": [op.op for op in schedule],
    }


@dataclass
class Expectation:
    """The collective schedule the sharding contract implies."""
    kind: str
    n_devices: int
    phases: tuple = ()       # ordered phase names: "all-gather", "reduce"
    gather_bytes: int = 0    # full bytes of rule-sharded params
    reduce_bytes: int = 0    # gradient mass (total param bytes)
    sharded_leaves: int = 0
    notes: list = field(default_factory=list)

    def to_dict(self):
        return {"kind": self.kind, "n_devices": self.n_devices,
                "phases": list(self.phases),
                "gather_bytes": self.gather_bytes,
                "reduce_bytes": self.reduce_bytes,
                "sharded_leaves": self.sharded_leaves}


def expected_schedule(kind, n_devices, partitioner=None, params=None):
    """Derive the expected schedule from the partitioner rules + the
    actual parameter tree.

    - a rule-sharded param tree ⇒ one **all-gather** phase whose volume
      is the *full* bytes of every sharded leaf (the gathered output —
      the transient params-sized buffer the execution model budgets);
    - any multi-device ``train_step`` ⇒ one **reduce** phase (all-reduce
      or reduce-scatter) whose volume is the gradient mass ≈ total param
      bytes;
    - eval / single-device programs ⇒ no collectives at all.
    """
    import jax

    exp = Expectation(kind=kind, n_devices=n_devices)
    if n_devices <= 1:
        return exp

    phases = []
    if partitioner is not None and params is not None:
        shardings = partitioner.param_shardings(params)
        for leaf, sh in zip(jax.tree.leaves(params),
                            jax.tree.leaves(shardings)):
            if tuple(sh.spec):
                exp.sharded_leaves += 1
                exp.gather_bytes += int(leaf.nbytes)
        if exp.sharded_leaves:
            phases.append("all-gather")
    if kind == "train_step":
        phases.append("reduce")
        if params is not None:
            exp.reduce_bytes = sum(int(x.nbytes)
                                   for x in jax.tree.leaves(params))
    exp.phases = tuple(phases)
    return exp


def diff(expectation, summary, key=""):
    """Structural findings: the contract's phases vs what GSPMD emitted.

    Operates on a :func:`summarize_schedule` dict (not the raw op list)
    so reports pinned in ``hlo-budget.json`` — which store exactly that
    summary — can be re-diffed against a fresh expectation without
    recompiling the program.
    """
    path = "analysis/collectives"
    findings = []
    counts, volumes = summary["counts"], summary["bytes"]
    order = summary.get("order", [])

    if "all-gather" in expectation.phases:
        actual = volumes.get("all-gather", 0)
        if actual < GATHER_COLLAPSE * expectation.gather_bytes:
            findings.append(Finding(
                rule="collective-missing", path=path, line=1,
                message=f"{key}: partitioner shards "
                        f"{expectation.sharded_leaves} param leaves "
                        f"({expectation.gather_bytes / 2**20:.1f} MiB) "
                        f"but the compiled schedule gathers only "
                        f"{actual / 2**20:.1f} MiB — the ZeRO param "
                        f"all-gather vanished (dead partition rule? "
                        f"dropped sharding constraint?); params are "
                        f"silently replicated again"))

    n_reduce = sum(counts.get(op, 0) for op in REDUCE_OPS)
    if "reduce" in expectation.phases and not n_reduce:
        findings.append(Finding(
            rule="collective-missing", path=path, line=1,
            message=f"{key}: multi-device train step with no gradient "
                    f"all-reduce/reduce-scatter — shards will diverge"))

    if expectation.reduce_bytes:
        actual = sum(volumes.get(op, 0) for op in REDUCE_OPS)
        if actual > DOUBLED_FACTOR * expectation.reduce_bytes:
            findings.append(Finding(
                rule="collective-doubled", path=path, line=1,
                message=f"{key}: reduce volume {actual / 2**20:.1f} MiB "
                        f"vs ~{expectation.reduce_bytes / 2**20:.1f} MiB "
                        f"gradient mass — the PR-6 doubled-reduction "
                        f"signature (a gradient is being reduced more "
                        f"than once)"))

    gathers = [i for i, op in enumerate(order) if op == "all-gather"]
    reduces = [i for i, op in enumerate(order) if op in REDUCE_OPS]
    if gathers and reduces and "all-gather" in expectation.phases \
            and min(gathers) > max(reduces):
        findings.append(Finding(
            rule="collective-order", path=path, line=1,
            message=f"{key}: first param all-gather is scheduled after "
                    f"the last gradient reduce — the program is no "
                    f"longer the gather-compute form"))

    return findings
