"""Rule ``telemetry-unregistered-kind``: the telemetry schema contract.

The event schema (``telemetry.core.SCHEMA``) and the metric namespace
(``telemetry.metrics.NAME_RE``) are the two registries the live
observability plane stands on — the offline report, the Prometheus
scrape, and the fleet router all consume them by name. Two static
checks keep producers honest:

- every ``emit("<kind>", ...)`` call site (positional or ``kind=``
  keyword string literal) must name a kind declared in SCHEMA —
  ``validate_event`` would reject the record at runtime, but only on
  the code path that actually fires, which for rare kinds (faults,
  preemption) is exactly the path tests miss;
- every metric registered through ``.counter(...)`` / ``.gauge(...)`` /
  ``.histogram(...)`` with a string-literal name must match the
  ``rmd_<subsystem>_<name>`` convention (counters additionally end in
  ``_total``), so the scrape namespace stays collision-free and
  greppable.

Only string-literal names are checked (a computed kind is the schema's
validate-at-runtime problem); non-telemetry ``.emit``/``.histogram``
receivers with non-literal args never match. Baseline-able like every
rule.
"""

import ast

from . import astutil
from .lint import Finding, Rule

RULE = "telemetry-unregistered-kind"

METRIC_METHODS = ("counter", "gauge", "histogram")


def _schema():
    from ..telemetry import core
    return core.SCHEMA


def _metric_name_re():
    from ..telemetry import metrics
    return metrics.NAME_RE


def _literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _emit_kind(node):
    """The string-literal kind of an ``emit(...)`` call, else None."""
    dotted = astutil.dotted_name(node.func) or ""
    if dotted.rpartition(".")[2] != "emit":
        return None
    if node.args:
        return _literal(node.args[0])
    for kw in node.keywords:
        if kw.arg == "kind":
            return _literal(kw.value)
    return None


def _metric_registration(node):
    """(method, string-literal metric name) for registry registrations,
    else None. Attribute calls only: a bare ``histogram(...)`` is
    someone's numpy import, not the registry."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in METRIC_METHODS:
        return None
    if not node.args:
        return None
    name = _literal(node.args[0])
    if name is None:
        return None
    return fn.attr, name


def check(module):
    schema = _schema()
    name_re = _metric_name_re()
    findings = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _emit_kind(node)
        if kind is not None and kind not in schema:
            findings.append(Finding(
                rule=RULE, path=module.rel, line=node.lineno,
                message=f"emit of unregistered event kind {kind!r}: "
                        f"declare it in telemetry.core.SCHEMA (with its "
                        f"required fields) or fix the typo"))
        reg = _metric_registration(node)
        if reg is not None:
            method, name = reg
            if not name_re.match(name):
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"metric name {name!r} breaks the "
                            f"rmd_<subsystem>_<name> convention "
                            f"(lower-snake, rmd_ prefix, >= 3 segments)"))
            elif method == "counter" and not name.endswith("_total"):
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"counter {name!r} must end in _total "
                            f"(Prometheus counter convention)"))
    return findings


RULES = [
    Rule(name=RULE,
         doc="emit() kinds must be declared in telemetry.core.SCHEMA; "
             "metric names must match rmd_<subsystem>_<name> (counters "
             "ending _total)",
         check=check),
]
