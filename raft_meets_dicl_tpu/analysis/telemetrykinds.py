"""Rule ``telemetry-unregistered-kind``: the telemetry schema contract.

The event schema (``telemetry.core.SCHEMA``) and the metric namespace
(``telemetry.metrics.NAME_RE``) are the two registries the live
observability plane stands on — the offline report, the Prometheus
scrape, and the fleet router all consume them by name. Two static
checks keep producers honest:

- every ``emit("<kind>", ...)`` call site (positional or ``kind=``
  keyword string literal) must name a kind declared in SCHEMA —
  ``validate_event`` would reject the record at runtime, but only on
  the code path that actually fires, which for rare kinds (faults,
  preemption) is exactly the path tests miss;
- every metric registered through ``.counter(...)`` / ``.gauge(...)`` /
  ``.histogram(...)`` with a string-literal name must match the
  ``rmd_<subsystem>_<name>`` convention (counters additionally end in
  ``_total``), so the scrape namespace stays collision-free and
  greppable.

Only string-literal names are checked (a computed kind is the schema's
validate-at-runtime problem); non-telemetry ``.emit``/``.histogram``
receivers with non-literal args never match. Baseline-able like every
rule.

A third check, **sidecar-route (project)**, holds the HTTP surface to
the same documentation contract as the knob registry: every route in
``telemetry.sidecar.ROUTES`` (the one tuple both the serve and train
sidecars dispatch on) must appear in the README's observability table —
an endpoint nobody can discover is dead weight on a debug port.
"""

import ast

from . import astutil
from .lint import Finding, Rule

RULE = "telemetry-unregistered-kind"

METRIC_METHODS = ("counter", "gauge", "histogram")


def _schema():
    from ..telemetry import core
    return core.SCHEMA


def _metric_name_re():
    from ..telemetry import metrics
    return metrics.NAME_RE


def _literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _emit_kind(node):
    """The string-literal kind of an ``emit(...)`` call, else None."""
    dotted = astutil.dotted_name(node.func) or ""
    if dotted.rpartition(".")[2] != "emit":
        return None
    if node.args:
        return _literal(node.args[0])
    for kw in node.keywords:
        if kw.arg == "kind":
            return _literal(kw.value)
    return None


def _metric_registration(node):
    """(method, string-literal metric name) for registry registrations,
    else None. Attribute calls only: a bare ``histogram(...)`` is
    someone's numpy import, not the registry."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in METRIC_METHODS:
        return None
    if not node.args:
        return None
    name = _literal(node.args[0])
    if name is None:
        return None
    return fn.attr, name


def check(module):
    schema = _schema()
    name_re = _metric_name_re()
    findings = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _emit_kind(node)
        if kind is not None and kind not in schema:
            findings.append(Finding(
                rule=RULE, path=module.rel, line=node.lineno,
                message=f"emit of unregistered event kind {kind!r}: "
                        f"declare it in telemetry.core.SCHEMA (with its "
                        f"required fields) or fix the typo"))
        reg = _metric_registration(node)
        if reg is not None:
            method, name = reg
            if not name_re.match(name):
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"metric name {name!r} breaks the "
                            f"rmd_<subsystem>_<name> convention "
                            f"(lower-snake, rmd_ prefix, >= 3 segments)"))
            elif method == "counter" and not name.endswith("_total"):
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"counter {name!r} must end in _total "
                            f"(Prometheus counter convention)"))
    return findings


SIDECAR_RULE = "sidecar-route"
SIDECAR_MODULE = "raft_meets_dicl_tpu/telemetry/sidecar.py"
# every module whose module-level ROUTES tuple is a served HTTP surface:
# the observability sidecar plus the fleet's replica API and router
# front-end. The sidecar module is required (missing ROUTES there is a
# finding); the others are checked when present.
ROUTE_MODULES = (
    SIDECAR_MODULE,
    "raft_meets_dicl_tpu/fleet/replica.py",
    "raft_meets_dicl_tpu/fleet/router.py",
)


def _sidecar_routes(module):
    """(lineno, [route literals]) from the module-level ``ROUTES = (...)``
    assignment, else None."""
    for node in module.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "ROUTES"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            routes = [_literal(el) for el in node.value.elts]
            return node.lineno, [r for r in routes if r]
    return None


def check_sidecar_routes(ctx):
    """Every route a ROUTES-declaring HTTP module serves must appear in
    README.md (the endpoint tables document the served surface)."""
    readme = ctx.root / "README.md"
    text = readme.read_text() if readme.exists() else None
    findings = []
    for rel in ROUTE_MODULES:
        module = next((m for m in ctx.modules if m.rel == rel), None)
        if module is None:
            # partial --root runs (or a build without the fleet) don't
            # cover this module; nothing to hold
            continue
        parsed = _sidecar_routes(module)
        if parsed is None:
            if rel == SIDECAR_MODULE:
                findings.append(Finding(
                    rule=SIDECAR_RULE, path=rel, line=1,
                    message="telemetry/sidecar.py has no module-level "
                            "ROUTES tuple of string literals; the "
                            "sidecar-route rule anchors the documented "
                            "endpoint surface on it"))
            continue
        lineno, routes = parsed
        if text is None:
            return [Finding(rule=SIDECAR_RULE, path="README.md", line=1,
                            message="README.md missing")]
        findings.extend(
            Finding(
                rule=SIDECAR_RULE, path=rel, line=lineno,
                message=f"served route {route!r} is not documented in "
                        f"README.md; add it to the endpoint table "
                        f"(or drop the route)")
            for route in routes if route not in text)
    return findings


RULES = [
    Rule(name=RULE,
         doc="emit() kinds must be declared in telemetry.core.SCHEMA; "
             "metric names must match rmd_<subsystem>_<name> (counters "
             "ending _total)",
         check=check),
    Rule(name=SIDECAR_RULE,
         doc="every route in a module-level ROUTES tuple (telemetry "
             "sidecar, fleet replica API, fleet router front-end) must "
             "appear in the README endpoint tables",
         project=check_sidecar_routes),
]
