"""Rule ``tracer-branch``: Python control flow on traced values.

A Python ``if``/``while`` inside jit evaluates its condition eagerly at
trace time; when the condition depends on a traced array the trace
either raises ``TracerBoolConversionError`` or — worse, with
``bool()``-coercible shapes — silently bakes one branch into the
compiled program and *retraces on every boundary crossing*, defeating
the PR-7 program registry. The fix is ``lax.cond``/``lax.while_loop``
or ``jnp.where``.

Taint model (per jit-reachable function, single forward pass):

- the function's own parameters are traced;
- names assigned from jnp/jax.lax/jax.nn calls, from tainted names, or
  from expressions containing either, become traced;
- ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size`` and
  ``len(x)`` / ``isinstance(x, ...)`` / ``x is None`` are trace-time
  constants and launder the taint (static-shape dispatch like
  ``if dim % block:`` stays legal — that's how the Pallas kernels and
  the fs volume-split choose code paths).

Closure variables from an enclosing builder (``accumulate``, ``wire``)
are intentionally NOT tainted: step builders branch on static config at
trace time by design.
"""

import ast

from . import astutil
from .lint import Finding, Rule

RULE = "tracer-branch"

TRACE_ROOTS = {"jnp", "lax", "jax"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}
SHIELD_FUNCS = {"isinstance", "len", "hasattr", "getattr", "callable",
                "type", "repr", "str"}


def _is_trace_call(node):
    """Call whose result is (likely) a traced array: rooted at jnp/lax/
    jax.* numeric namespaces."""
    if not isinstance(node, ast.Call):
        return False
    dotted = astutil.dotted_name(node.func)
    return bool(dotted) and dotted.split(".")[0] in TRACE_ROOTS


def _expr_tainted(node, taint):
    """Whether an expression's value carries taint."""
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, taint)
    if _is_trace_call(node):
        return True
    if isinstance(node, ast.Call):
        fname = astutil.dotted_name(node.func)
        if fname and fname.rsplit(".", 1)[-1] in SHIELD_FUNCS:
            return False
        return any(_expr_tainted(a, taint) for a in node.args)
    for child in ast.iter_child_nodes(node):
        if _expr_tainted(child, taint):
            return True
    return False


def _hot_names(node, taint):
    """Tainted names used *as values* in a condition — occurrences under
    a static attribute (``x.shape[0]``), a shield call (``len(x)``), or
    an identity comparison (``x is None``) do not count."""
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return set()
    if isinstance(node, ast.Call):
        fname = astutil.dotted_name(node.func)
        if fname and fname.rsplit(".", 1)[-1] in SHIELD_FUNCS:
            return set()
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return set()
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
        return {node.id} if node.id in taint else set()
    out = set()
    for child in ast.iter_child_nodes(node):
        out |= _hot_names(child, taint)
    return out


def _taint_set(info, table):
    """One-pass taint propagation over a function's own body."""
    taint = set(info.params)
    for node in astutil.body_nodes(info, table):
        targets = ()
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = (node.target,), node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = (node.target,), node.value
        else:
            continue
        if _expr_tainted(value, taint):
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        taint.add(n.id)
        else:
            # reassignment from a clean value clears simple names
            for t in targets:
                if isinstance(t, ast.Name):
                    taint.discard(t.id)
    return taint


def check(module):
    table = astutil.function_table(module.tree)
    hot = astutil.jit_reachable(module.tree, table)

    findings = []
    for qual in sorted(hot):
        info = table.get(qual)
        if info is None:
            continue
        taint = _taint_set(info, table)
        for node in astutil.body_nodes(info, table):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            names = _hot_names(node.test, taint)
            if not names:
                continue
            kw = "while" if isinstance(node, ast.While) else "if"
            findings.append(Finding(
                rule=RULE, path=module.rel, line=node.lineno,
                severity="error",
                message=f"Python '{kw}' on traced value(s) "
                        f"{sorted(names)} in jit-reachable '{qual}': "
                        f"use lax.cond/lax.while_loop/jnp.where"))
    return findings


RULES = [Rule(
    name=RULE,
    doc="data-dependent Python if/while on traced values in "
        "jit-reachable code",
    check=check,
)]
