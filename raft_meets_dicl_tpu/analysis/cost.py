"""graftcost: static per-op cost model over lowered StableHLO.

``analysis.hlo`` audits *hazard presence* (fingerprints, collectives,
f32 convs); this module puts *numbers* on a program — per-op-class
FLOPs, HBM bytes, arithmetic intensity — and classifies every dot/conv
against the measured TPU cost structure of PERF.md:

- **MXU tile waste** — the MXU consumes (8, 128)-shaped register tiles;
  a dot whose matrix dims don't fill them pays for the padding. The
  flagship's windowed-lookup einsums are the canonical case: a
  (9, H2)×(H2, W2) contraction uses ~15% of the tiles it occupies
  ("a 9-row operand uses 9/128 of the systolic array", PERF.md), which
  is why the lookup is *shape*-bound, not FLOP-bound. Ops below
  ``TILE_OK`` utilization get verdict ``shape-bound``; well-tiled
  dots/convs get ``mxu-bound``; everything else is ``memory-bound``.
- **f32 upcast surfaces** — a bf16-policy program whose dots/convs
  produce f32 results lost its policy between Flax and XLA: 2× the
  matching-volume HBM and half the MXU rate, silently.
- **gather scalarization** — XLA:TPU scalarizes *strip-sliced* gathers
  (slice extent between 1 and the full dim): the measured 23×
  ``lax.gather`` cliff vs ``take_along_axis`` rows (PERF.md). Row
  gathers (all-1 slices) and whole-dim slices are fine.

The walker is deterministic over the canonical StableHLO text (the
fingerprint-stability audit pins exactly that), so its FLOP/byte totals
can be *pinned* per ProgramKey in ``hlo-budget.json`` and enforced on
CPU in tier-1 with zero TPU time: a refactor that silently doubles a
program's reduction bytes, regrows an f32 surface, or adds a strip
gather turns the gate red before any TPU run pays for it.

Where the backend provides ``Compiled.cost_analysis()`` /
``memory_analysis()`` their totals ride along in the report
(informational — backend estimates vary across XLA versions; the
*pinned* numbers are the walker's).
"""

import json
import re
from dataclasses import dataclass, field
from pathlib import Path

from .hlo import _DTYPE_BITS, _DTYPE_BYTES
from .lint import Finding

# the MXU register tile: operands stream as (sublane=8, lane=128) tiles
TILE_SUBLANE = 8
TILE_LANE = 128
# minimum tile utilization for a dot/conv to count as well-shaped
TILE_OK = 0.5
# hazard noise floor: a shape-bound op only counts as tile *waste* when
# it carries a visible share of the program's FLOPs
TILE_WASTE_FLOP_SHARE = 0.01

BUDGET_NAME = "hlo-budget.json"

_TENSOR_RE = re.compile(r"tensor<(?:([0-9][0-9x]*)x)?([a-z][a-z0-9]*)>")
_OP_RE = re.compile(r"=\s*\"?stablehlo\.([a-z0-9_]+)\"?")
_DIMS_PAIR_RE = re.compile(
    r"{}\s*=\s*\[([0-9,\s]*)\]\s*x\s*\[([0-9,\s]*)\]")
_SLICE_SIZES_RE = re.compile(r"slice_sizes\s*=\s*array<i64:\s*([0-9,\s]*)>")
_KERNEL_SPEC_RE = re.compile(r"x\[([^\]]*)\]->")

_CLASS = {
    "dot_general": "dot",
    "dot": "dot",
    "convolution": "conv",
    "gather": "gather",
    "scatter": "gather",
    "dynamic_slice": "gather",
    "dynamic_update_slice": "gather",
    "reduce": "reduce",
    "reduce_window": "reduce",
}

# structural ops that move no tensor data worth accounting
_SKIP = {"return", "func", "constant", "iota", "tuple", "get_tuple_element",
         "optimization_barrier", "custom_call", "partition_id",
         "replica_id", "after_all"}


def _prod(dims):
    n = 1
    for d in dims:
        n *= d
    return n


def _parse_tensor(m):
    dims = tuple(int(d) for d in m.group(1).split("x")) if m.group(1) else ()  # graftlint: disable=host-sync -- parses a StableHLO tensor type, not a device value
    return dims, m.group(2)


def _tensor_nbytes(dims, dtype):
    """Bytes of one ``tensor<dims x dtype>``.

    Sub-f32 element widths count at their true size — the u8/i8 volumes
    of the quantized matching tier, f8 formats, packed sub-byte ints
    (rounded up per tensor) — never at the 4-byte fallback, which is
    reserved for genuinely unknown dtypes. Charging a quantized operand
    4 B would erase exactly the HBM-traffic saving the quant tier is
    pinned to demonstrate.
    """
    if dtype in _DTYPE_BITS:
        return (_prod(dims) * _DTYPE_BITS[dtype] + 7) // 8
    return _prod(dims) * _DTYPE_BYTES.get(dtype, 4)


def _pad(n, to):
    return ((n + to - 1) // to) * to or to


def tile_utilization(m, k, n):
    """Fraction of the streamed (8, 128) MXU register tiles an
    (M, K) × (K, N) contraction actually fills — the smaller of the two
    operand utilizations (the worse operand stalls the array)."""
    u_lhs = (m * k) / (_pad(m, TILE_SUBLANE) * _pad(k, TILE_LANE))
    u_rhs = (k * n) / (_pad(k, TILE_SUBLANE) * _pad(n, TILE_LANE))
    return min(u_lhs, u_rhs)


def _int_list(text):
    return [int(p) for p in text.replace(" ", "").split(",") if p]  # graftlint: disable=host-sync -- parses attribute text, not a device value


@dataclass
class OpCost:
    """Cost estimate for one StableHLO op instance."""
    op: str
    klass: str       # dot | conv | gather | reduce | elementwise
    line: int        # 1-based line in the module text
    flops: int
    bytes: int
    result_dtype: str
    mkn: tuple = None        # (M, K, N) for dot/conv
    tile_util: float = None  # dot/conv only
    verdict: str = "memory-bound"
    hazards: tuple = ()      # hazard tags this op instance triggers

    def to_dict(self):
        d = {"op": self.op, "class": self.klass, "line": self.line,
             "flops": self.flops, "bytes": self.bytes,
             "dtype": self.result_dtype, "verdict": self.verdict}
        if self.mkn is not None:
            d["mkn"] = list(self.mkn)
        if self.tile_util is not None:
            d["tile_util"] = round(self.tile_util, 4)
        if self.hazards:
            d["hazards"] = list(self.hazards)
        return d


def _line_types(line):
    """(operand_types, result_types) for one op line, each a list of
    (dims, dtype). Handles both ``: (a, b) -> r`` and the elementwise
    ``: tensor<...>`` form (operands and result share the type)."""
    _, sep, sig = line.rpartition(" : ")
    if not sep:
        return [], []
    if "->" in sig:
        opnds, _, res = sig.rpartition("->")
        return ([_parse_tensor(m) for m in _TENSOR_RE.finditer(opnds)],
                [_parse_tensor(m) for m in _TENSOR_RE.finditer(res)])
    types = [_parse_tensor(m) for m in _TENSOR_RE.finditer(sig)]
    # elementwise form: every operand and the result share one type;
    # approximate operands as two reads of it (add/mul arity)
    return types * 2, types


def _dot_cost(line, operands, results):
    lhs = operands[0][0] if operands else ()
    rhs = operands[1][0] if len(operands) > 1 else ()
    m_c = _DIMS_PAIR_RE.pattern  # noqa: F841 - doc anchor
    c = re.search(r"contracting_dims\s*=\s*\[([0-9,\s]*)\]\s*x", line)
    b = re.search(r"batching_dims\s*=\s*\[([0-9,\s]*)\]\s*x", line)
    contract = _int_list(c.group(1)) if c else []
    batching = _int_list(b.group(1)) if b else []
    k = _prod(lhs[d] for d in contract) if lhs else 1
    bsz = _prod(lhs[d] for d in batching) if lhs else 1
    m = _prod(lhs) // max(1, bsz * k)
    n = _prod(rhs) // max(1, bsz * k) if rhs else 1
    return 2 * bsz * m * k * n, (m, k, n)


def _conv_cost(line, operands, results):
    kernel = operands[1][0] if len(operands) > 1 else ()
    out = results[0][0] if results else ()
    co = 1
    spec = _KERNEL_SPEC_RE.search(line)
    if spec and kernel:
        parts = [p.strip() for p in spec.group(1).split(",")]
        if "o" in parts and parts.index("o") < len(kernel):
            co = kernel[parts.index("o")]
    k = _prod(kernel) // max(1, co)
    m = _prod(out) // max(1, co)
    return 2 * m * k * co, (m, k, co)


def _gather_hazard(line, operands):
    """Strip-sliced gather: any slice extent strictly between 1 and the
    full operand dim — the scalarization cliff."""
    m = _SLICE_SIZES_RE.search(line)
    if not m or not operands:
        return False
    sizes = _int_list(m.group(1))
    dims = operands[0][0]
    for s, d in zip(sizes, dims):
        if 1 < s < d:
            return True
    return False


def op_costs(text, expect_bf16=False):
    """Walk a lowered StableHLO module's text into per-op cost records.

    Purely textual (no jax import): deterministic over the
    location-stripped canonical text the fingerprint audit pins.
    """
    ops = []
    for lineno, line in enumerate(text.splitlines(), 1):
        m = _OP_RE.search(line)
        if not m:
            continue
        name = m.group(1)
        if name in _SKIP:
            continue
        operands, results = _line_types(line)
        if not results:
            continue
        rbytes = sum(_tensor_nbytes(d, t) for d, t in results)
        obytes = sum(_tensor_nbytes(d, t) for d, t in operands)
        rdtype = results[0][1]
        klass = _CLASS.get(name, "elementwise")

        flops = 0
        mkn = None
        util = None
        hazards = []
        if klass == "dot":
            flops, mkn = _dot_cost(line, operands, results)
        elif klass == "conv":
            flops, mkn = _conv_cost(line, operands, results)
        elif klass == "reduce":
            flops = _prod(operands[0][0]) if operands else 0
        elif klass == "elementwise":
            flops = _prod(results[0][0])

        if mkn is not None:
            util = tile_utilization(*mkn)
            verdict = "mxu-bound" if util >= TILE_OK else "shape-bound"
            if expect_bf16 and rdtype == "f32":
                hazards.append("f32-upcast")
        else:
            verdict = "memory-bound"
        if name == "gather" and _gather_hazard(line, operands):
            hazards.append("gather-scalarization")

        ops.append(OpCost(op=name, klass=klass, line=lineno, flops=flops,
                          bytes=obytes + rbytes, result_dtype=rdtype,
                          mkn=mkn, tile_util=util, verdict=verdict,
                          hazards=tuple(hazards)))
    return ops


def summarize(ops):
    """Per-class aggregates + hazard counts over one program's ops.

    The ``mxu-tile-waste`` hazard is resolved here (not per-op): a
    shape-bound dot/conv only counts as *waste* when it carries at least
    ``TILE_WASTE_FLOP_SHARE`` of the program's FLOPs — a handful of tiny
    setup contractions isn't the hazard; the lookup running 4×12 times a
    step is.
    """
    total_flops = sum(o.flops for o in ops)
    total_bytes = sum(o.bytes for o in ops)
    classes = {}
    verdicts = {}
    hazards = {"mxu-tile-waste": 0, "f32-upcast": 0,
               "gather-scalarization": 0}
    for o in ops:
        c = classes.setdefault(o.klass, {"ops": 0, "flops": 0, "bytes": 0})
        c["ops"] += 1
        c["flops"] += o.flops
        c["bytes"] += o.bytes
        verdicts[o.verdict] = verdicts.get(o.verdict, 0) + 1
        for h in o.hazards:
            hazards[h] = hazards.get(h, 0) + 1
        if o.verdict == "shape-bound" and total_flops and \
                o.flops >= TILE_WASTE_FLOP_SHARE * total_flops:
            hazards["mxu-tile-waste"] += 1
    for c in classes.values():
        c["intensity"] = round(c["flops"] / c["bytes"], 3) if c["bytes"] \
            else 0.0
    return {
        "flops": total_flops,
        "bytes": total_bytes,
        "intensity": round(total_flops / total_bytes, 3) if total_bytes
        else 0.0,
        "classes": classes,
        "verdicts": verdicts,
        "hazards": {k: v for k, v in hazards.items() if v},
    }


def backend_analysis(compiled):
    """Totals from the backend's own cost/memory analyses, where it
    provides them (informational; never pinned — XLA's estimates move
    across versions, the walker's don't)."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["backend_flops"] = int(ca.get("flops", 0))
            out["backend_bytes"] = int(ca.get("bytes accessed", 0))
    except Exception:  # noqa: BLE001 - optional backend surface
        pass
    try:
        ma = compiled.memory_analysis()
        out["peak_temp_bytes"] = int(ma.temp_size_in_bytes)
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
    except Exception:  # noqa: BLE001 - optional backend surface
        pass
    return out


def program_cost(program, args, expect_bf16=False, n_devices=1,
                 partitioner=None, params=None, kind=None,
                 do_compile=True, **hlo_context):
    """Full static cost report for one registered program.

    Returns ``(report, findings)`` — findings here are the *contract*
    violations (collective schedule vs the partitioner-derived
    expectation, via ``analysis.collectives``); budget drift is judged
    separately by :class:`Budget` so one audit pass can serve both the
    gate and ``--update`` re-pinning.

    ``hlo_context`` (``expect_gather``) is accepted and unused — the
    ``hlo`` builders return one shared entry list whose audit kwargs
    serve both auditors.
    """
    from . import collectives
    from .hlo import strip_locations

    key = program.key.canonical() if program.key else program.label
    lowered = program.lower(*args)
    text = strip_locations(lowered.as_text())
    ops = op_costs(text, expect_bf16=expect_bf16)
    report = {
        "key": key,
        "label": program.label,
        "kind": kind or (program.key.kind if program.key else "?"),
        "n_devices": n_devices,
        **summarize(ops),
        "ops": [o.to_dict() for o in ops
                if o.hazards or o.klass in ("dot", "conv")],
    }

    findings = []
    if do_compile:
        compiled = lowered.compile()
        report.update(backend_analysis(compiled))
        schedule = collectives.parse_schedule(compiled.as_text())
        summary = collectives.summarize_schedule(schedule)
        report["collectives"] = summary
        expectation = collectives.expected_schedule(
            kind=report["kind"], n_devices=n_devices,
            partitioner=partitioner, params=params)
        findings.extend(collectives.diff(expectation, summary, key=key))
        report["expected_collectives"] = expectation.to_dict()
    return report, findings


# -- pinned budgets -----------------------------------------------------------

DEFAULT_TOLERANCE = {"flops": 0.05, "bytes": 0.08, "collective_bytes": 0.02}


class Budget:
    """Per-ProgramKey pinned cost budgets, ``graftlint-baseline.json``
    discipline: every entry is exact numbers + tolerances, entries that
    match no audited program are reported stale, programs with no entry
    fail the gate (a new program must be pinned deliberately via
    ``scripts/graftcost.py --update``)."""

    VERSION = 1

    def __init__(self, data=None, path=None):
        data = data or {}
        if data and data.get("version", self.VERSION) != self.VERSION:
            raise ValueError(
                f"unsupported budget version {data.get('version')!r}")
        self.path = path
        self.comment = data.get("comment", "")
        self.tolerance = {**DEFAULT_TOLERANCE, **data.get("tolerance", {})}
        self.entries = dict(data.get("entries", {}))
        self._hits = {k: 0 for k in self.entries}

    @classmethod
    def load(cls, path):
        return cls(json.loads(Path(path).read_text()), path=str(path))

    @classmethod
    def empty(cls):
        return cls()

    def unused_entries(self):
        """Pinned keys no audited program produced this run — stale the
        moment a program family is renamed or removed; ``--update``
        drops them so the file tracks the registry instead of rotting."""
        return [k for k, n in self._hits.items() if n == 0]

    def _drift(self, name, actual, pinned, key, findings):
        tol = self.tolerance.get(name, 0.0)
        lo, hi = pinned * (1 - tol), pinned * (1 + tol)
        if not (lo <= actual <= hi):
            rel = (actual - pinned) / pinned if pinned else float("inf")
            findings.append(Finding(
                rule="cost-budget", path="analysis/cost", line=1,
                message=f"{key}: {name} {actual:,} vs pinned {pinned:,} "
                        f"({rel:+.1%}, tolerance ±{tol:.0%}) — re-pin "
                        f"deliberately with scripts/graftcost.py --update "
                        f"if the change is intended"))

    def check(self, report):
        """Findings for one program report against its pinned entry."""
        key = report["key"]
        entry = self.entries.get(key)
        findings = []
        if entry is None:
            findings.append(Finding(
                rule="cost-unpinned", path="analysis/cost", line=1,
                message=f"{key}: program has no pinned budget entry in "
                        f"{self.path or BUDGET_NAME}; pin it with "
                        f"scripts/graftcost.py --update"))
            return findings
        self._hits[key] += 1
        self._drift("flops", report["flops"], entry["flops"], key, findings)
        self._drift("bytes", report["bytes"], entry["bytes"], key, findings)
        actual_cb = report.get("collectives", {}).get("total_bytes", 0)
        self._drift("collective_bytes", actual_cb,
                    entry.get("collective_bytes", 0), key, findings)
        pinned_h = entry.get("hazards", {})
        for name, n in sorted(report.get("hazards", {}).items()):
            if n > pinned_h.get(name, 0):
                findings.append(Finding(
                    rule="cost-hazard", path="analysis/cost", line=1,
                    message=f"{key}: {n} {name} hazard(s) vs "
                            f"{pinned_h.get(name, 0)} grandfathered — a "
                            f"new TPU hazard class grew into this "
                            f"program"))
        # resharding ops are grandfathered per pinned count (the healthy
        # flagship legitimately carries a few GSPMD boundary permutes);
        # only growth beyond the pin flags
        from .collectives import RESHARD_OPS
        pinned_c = entry.get("collectives", {})
        actual_c = report.get("collectives", {}).get("counts", {})
        for op in RESHARD_OPS:
            if actual_c.get(op, 0) > pinned_c.get(op, 0):
                findings.append(Finding(
                    rule="collective-reshard", path="analysis/cost",
                    line=1,
                    message=f"{key}: {actual_c.get(op, 0)} {op} op(s) vs "
                            f"{pinned_c.get(op, 0)} pinned — GSPMD is "
                            f"resharding an activation the contract "
                            f"never asks to move; a sharding constraint "
                            f"disagrees with its neighbours"))
        return findings

    @staticmethod
    def entry_for(report):
        entry = {
            "flops": report["flops"],
            "bytes": report["bytes"],
            "collective_bytes": report.get("collectives", {}).get(
                "total_bytes", 0),
            "collectives": report.get("collectives", {}).get("counts", {}),
            "verdicts": report.get("verdicts", {}),
        }
        if report.get("hazards"):
            entry["hazards"] = dict(report["hazards"])
        return entry

    def pinned_data(self, reports):
        """The re-pinned budget payload for ``--update``: one entry per
        audited program, header comment and tolerances preserved."""
        return {
            "version": self.VERSION,
            "comment": self.comment or (
                "Pinned per-program static cost budgets "
                "(scripts/graftcost.py). flops/bytes are the "
                "deterministic StableHLO-walker totals, "
                "collective_bytes the compiled post-GSPMD schedule "
                "volume. Re-pin deliberately with --update; stale "
                "entries are reported so this file tracks the program "
                "registry."),
            "tolerance": dict(self.tolerance),
            "programs": len(reports),
            "entries": {r["key"]: self.entry_for(r) for r in reports},
        }


@dataclass
class CostReport:
    """One graftcost run over the audited program set."""
    reports: list = field(default_factory=list)
    findings: list = field(default_factory=list)
    stale: list = field(default_factory=list)

    @property
    def ok(self):
        return not self.findings

    def to_dict(self):
        return {
            "ok": self.ok,
            "programs": len(self.reports),
            "findings": [f.to_dict() for f in self.findings],
            "stale_budget_entries": list(self.stale),
            "reports": self.reports,
        }


def build_entries(include_mesh2d=True, shape=(48, 64)):
    """The audited program set: the flagship tiny-shape train/eval pair,
    the (4, 2)-mesh ZeRO SPMD variant (8 virtual devices), every
    iteration-ladder rung, the video warm-start variant, the quantized
    matching-tier variants (u8/i8 base rung + u8 warm), and the
    on-device data-engine pair (augmented train step + synth renderer)
    — exactly the programs ``hlo-budget.json`` pins."""
    import jax

    from . import hlo

    entries = list(hlo.build_flagship_programs(n_devices=2, shape=shape))
    if include_mesh2d and jax.device_count() >= 8:
        entries += hlo.build_flagship_programs(n_devices=8, shape=shape,
                                               mesh2d=True)
    entries += hlo.build_ladder_programs()
    entries += hlo.build_warm_programs()
    entries += hlo.build_quant_programs()
    entries += hlo.build_aug_programs()
    return entries


def audit_costs(entries=None, budget=None, **build_kwargs):
    """Run the cost model + collective audit + budget gate over every
    entry (defaults to :func:`build_entries`). Returns a
    :class:`CostReport`."""
    if entries is None:
        entries = build_entries(**build_kwargs)
    if budget is None:
        budget = Budget.empty()
    out = CostReport()
    for program, args, kwargs in entries:
        report, findings = program_cost(program, args, **kwargs)
        out.reports.append(report)
        out.findings.extend(findings)
        if budget.entries or budget.path:
            out.findings.extend(budget.check(report))
    # stale pins are reported, not findings: a shrunk program set should
    # prompt an --update, not break the build (graftlint's stale-entry
    # discipline)
    out.stale = budget.unused_entries()
    return out


def emit_events(cost_report, tele):
    """Forward per-program cost summaries as ``cost`` telemetry."""
    for r in cost_report.reports:
        tele.emit(
            "cost", program=r["key"], program_kind=r["kind"],
            flops=r["flops"],
            bytes=r["bytes"], intensity=r["intensity"],
            collective_bytes=r.get("collectives", {}).get("total_bytes", 0),
            verdicts=r.get("verdicts", {}),
            hazards=r.get("hazards", {}))


def render_reports(cost_report):
    """Human-readable "program costs" section (CLI + telemetry_report)."""
    out = ["== program costs =="]
    for r in cost_report.reports:
        coll = r.get("collectives", {})
        verd = ", ".join(f"{k}={v}" for k, v in
                         sorted(r.get("verdicts", {}).items())) or "-"
        haz = ", ".join(f"{k}={v}" for k, v in
                        sorted(r.get("hazards", {}).items()))
        out.append(
            f"{r['key']}: {r['flops'] / 1e6:.1f} MFLOP, "
            f"{r['bytes'] / 2 ** 20:.1f} MiB, intensity "
            f"{r['intensity']:.1f} flop/B, collectives "
            f"{coll.get('total_bytes', 0) / 2 ** 20:.2f} MiB "
            f"[{verd}]" + (f" hazards: {haz}" if haz else ""))
    for f in cost_report.findings:
        out.append(f"  ! {f.rule}: {f.message}")
    for key in cost_report.stale:
        out.append(f"  stale budget entry: {key}")
    return "\n".join(out)
