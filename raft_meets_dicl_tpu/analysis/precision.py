"""Rule ``f32-literal``: float32 leaking into mixed-precision models.

PR-3 moved the DICL matching nets to bf16 under a ``dtype``-threaded
policy; the win evaporates wherever a dtype-less ``jnp.zeros(...)`` (or
an explicit ``dtype=jnp.float32``) materializes inside the module: XLA
upcasts every consumer of the f32 operand, and a bf16 model silently
computes chunks of its graph in f32 — costing the exact HBM/FLOP the
policy was buying.

Scope: methods of ``nn.Module`` subclasses that *declare a precision
policy* — a class-level ``dtype`` or ``mixed_precision`` field — in
files under ``models/``. Flagged:

- dtype-less ``jnp.zeros/ones/full/empty/arange/linspace/eye/array``
  calls (they default to f32): pass ``dtype=self.dtype`` or an explicit
  dtype;
- ``dtype=jnp.float32`` in the same constructors (legal, but must be
  suppressed with a reason — e.g. FlowHead's documented f32 output
  convention).

``.astype(jnp.float32)`` is NOT flagged: explicit output-boundary casts
are the documented convention for flow fields.
"""

import ast

from . import astutil
from .lint import Finding, Rule

RULE = "f32-literal"

CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange", "linspace",
                "eye", "array", "identity"}
POLICY_FIELDS = {"dtype", "mixed_precision"}


def _policy_classes(tree):
    """ClassDefs subclassing nn.Module that declare a precision field."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {astutil.dotted_name(b) or "" for b in node.bases}
        if not any(b.rsplit(".", 1)[-1] == "Module" for b in bases):
            continue
        fields = set()
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                fields.add(stmt.target.id)
            elif isinstance(stmt, ast.Assign):
                fields.update(t.id for t in stmt.targets
                              if isinstance(t, ast.Name))
        if fields & POLICY_FIELDS:
            out.append(node)
    return out


def check(module):
    if "/models/" not in f"/{module.rel}":
        return []
    findings = []
    for cls in _policy_classes(module.tree):
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            dotted = astutil.dotted_name(node.func) or ""
            parts = dotted.split(".")
            if len(parts) != 2 or parts[0] != "jnp" or \
                    parts[1] not in CONSTRUCTORS:
                continue
            dtype_kw = next((kw for kw in node.keywords
                             if kw.arg == "dtype"), None)
            if dtype_kw is None:
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"dtype-less {dotted}() in mixed-precision "
                            f"module '{cls.name}' bakes float32 into "
                            f"the graph; pass dtype= explicitly"))
                continue
            kw_name = astutil.dotted_name(dtype_kw.value) or ""
            if kw_name in ("jnp.float32", "np.float32"):
                findings.append(Finding(
                    rule=RULE, path=module.rel, line=node.lineno,
                    message=f"explicit {kw_name} in {dotted}() inside "
                            f"mixed-precision module '{cls.name}'; "
                            f"suppress with a reason if intentional"))
    return findings


RULES = [Rule(
    name=RULE,
    doc="f32 constants / dtype-less jnp constructors inside "
        "mixed-precision model modules",
    check=check,
)]
