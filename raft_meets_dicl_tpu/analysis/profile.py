"""graftprof: measured device-time attribution over jax.profiler captures.

``analysis.cost`` (graftcost) *predicts* per-program, per-op-class
FLOP/byte totals from the lowered StableHLO; this module *measures*
them. It parses the capture directories the existing surfaces already
write (``train --profile``, ``/profilez``, ``scripts/profile_bench.py``)
— trace-event JSON always, ``.xplane.pb`` where a TF protobuf reader is
installed — attributes device time to the PR-7 registry's programs, and
buckets every op into graftcost's op classes plus the two runtime-only
ones (collective, infeed). The product is the **calibration table**:
measured seconds vs roofline-predicted seconds per program and op
class, with the measured/predicted ratio pinned per machine in
``prof-budget.json`` and drift-gated the same way graftcost gates
FLOP/byte totals.

Two attribution modes, because module names are not unique:

- **segmented capture** (``profile_entries`` / ``audit_profiles``, the
  CLI's default): every audited program runs inside its *own* trace
  segment, so attribution is exact regardless of module naming — all
  three ladder rungs lower to ``module @jit_step`` and would be
  indistinguishable in one mixed capture. The segment manifest records
  key, fingerprint and predicted costs next to the raw trace.
- **post-hoc attribution** (``attribute_trace``, used by ``/profilez``,
  ``train --profile`` and bench): an existing unsegmented capture is
  aggregated per ``hlo_module`` and op class, and module names are
  matched back to registered programs only where the mapping is
  unambiguous.

The roofline prediction is deliberately crude (peak FLOP/s and
bandwidth per platform, no overlap model): the *ratio* is the
calibrated quantity, pinned per machine with wide multiplicative
tolerances, so machine constants and model error cancel out of the
gate. What the gate catches is the ratio *moving* — a kernel change
that doubles measured time without touching the static cost model, the
exact regression class the static budget is blind to.
"""

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass, field
from pathlib import Path

from .lint import Finding

BUDGET_NAME = "prof-budget.json"
MANIFEST_NAME = "graftprof-manifest.json"

# graftcost's op classes plus the two that only exist at runtime
CLASSES = ("dot", "conv", "gather", "reduce", "elementwise",
           "collective", "infeed")

# measured/predicted ratios drift multiplicatively: pinned r gates
# [r / (1 + tol), r * (1 + tol)] — wide bands, the machine pin absorbs
# the roofline model's constants and only *movement* flags
DEFAULT_TOLERANCE = {"ratio": 1.5, "class_ratio": 3.0}

# per-class gating only where the class carries a visible share of the
# predicted step (tiny classes have noise-dominated ratios)
MIN_CLASS_SHARE = 0.05

# (peak FLOP/s, peak memory bytes/s) per jax platform; the TPU numbers
# are PERF.md's v4 measurements (197 TFLOP/s bf16 MXU peak), the rest
# are order-of-magnitude placeholders — the pinned calibration ratio
# absorbs the constant, see module docstring
_PEAKS = {
    "tpu": (197e12, 1.2e12),
    "gpu": (1.0e14, 1.0e12),
    "cpu": (1.0e11, 2.0e10),
}

_COLLECTIVE_TOKENS = ("all_reduce", "all_gather", "all_to_all",
                      "collective_permute", "reduce_scatter",
                      "collective_broadcast")
_GATHER_TOKENS = ("gather", "scatter", "dynamic_slice",
                  "dynamic_update_slice")
# "conv" only as a delimited token ("conv", "conv2d", "convolution...")
# — a bare substring test would claim every "convert" fusion
_CONV_RE = re.compile(r"(?<![a-z])conv(?:olution)?(?![a-z])|convolution")


class TraceError(ValueError):
    """A capture directory that cannot be attributed: no profiler
    output under it, unparseable trace JSON, or a trace with zero
    device op events (profiler ran but nothing executed)."""


def op_class(name):
    """Bucket one device-op name into graftcost's op classes.

    Works over both HLO spellings (hyphens: ``all-reduce``,
    ``dynamic-update-slice``) and StableHLO spellings (underscores),
    over fused names (``convolution_fusion``) and over instance
    suffixes (``dot.42``). Order matters: collectives before ``reduce``
    (``all-reduce``), gather tokens after collectives
    (``reduce-scatter``).
    """
    n = name.lower().lstrip("%").replace("-", "_")
    if any(t in n for t in _COLLECTIVE_TOKENS):
        return "collective"
    if "infeed" in n or "outfeed" in n:
        return "infeed"
    if _CONV_RE.search(n):
        return "conv"
    if "dot" in n or "einsum" in n:
        return "dot"
    if any(t in n for t in _GATHER_TOKENS):
        return "gather"
    if "reduce" in n:
        return "reduce"
    return "elementwise"


# -- trace parsing ------------------------------------------------------------


def find_trace_files(trace_dir, suffixes=(".trace.json.gz", ".trace.json")):
    """Every trace-event JSON file under a jax.profiler capture dir
    (``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``); also accepts
    files placed directly under ``trace_dir`` (test fixtures)."""
    out = []
    for suffix in suffixes:
        out += glob.glob(f"{trace_dir}/**/*{suffix}", recursive=True)
    return sorted(set(out))


def load_trace_events(path):
    """The ``traceEvents`` list of one trace-event JSON file (.gz or
    plain). Raises :class:`TraceError` on malformed content."""
    try:
        opener = gzip.open if str(path).endswith(".gz") else open
        with opener(path, "rt") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise TraceError(f"unreadable trace file {path}: {e}") from e
    events = data.get("traceEvents") if isinstance(data, dict) else None
    if not isinstance(events, list):
        raise TraceError(f"no traceEvents array in {path}")
    return events


def device_ops(events):
    """``(module, op, seconds)`` per device op execution.

    A device op event is a complete event (``ph == "X"``) whose args
    carry ``hlo_op`` — the XLA runtimes stamp every op execution with
    its HLO module and op name; host-side python/runtime events carry
    neither and are skipped. Durations are trace-event microseconds.
    """
    out = []
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        op = args.get("hlo_op")
        if not op:
            continue
        module = args.get("hlo_module", "?")
        out.append((module, op, float(ev.get("dur", 0)) / 1e6))  # graftlint: disable=host-sync -- trace-event microseconds, not a device value
    return out


def xplane_ops(path):
    """``(module, op, seconds)`` from an ``.xplane.pb`` — TPU/GPU
    captures where the trace JSON is absent. Requires the TF xplane
    protobuf; callers gate on :func:`have_xplane`."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xspace = xplane_pb2.XSpace()
    try:
        xspace.ParseFromString(Path(path).read_bytes())
    except Exception as e:  # noqa: BLE001 - protobuf parse errors vary
        raise TraceError(f"unreadable xplane {path}: {e}") from e

    out = []
    for plane in xspace.planes:
        if "TPU" not in plane.name and "/device:" not in plane.name:
            continue
        module = "?"
        evmeta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for event in line.events:
                name = evmeta[event.metadata_id].name
                # container events double-count their children
                if name.startswith(("%while", "jit_", "%tuple")):
                    continue
                out.append((module, name, event.duration_ps / 1e12))
    return out


def have_xplane():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401
        return True
    except Exception:  # noqa: BLE001 - tf optional, import errors vary
        return False


def collect_trace(trace_dir):
    """Parse one capture directory into device-op records.

    Returns ``{"ops": [(module, op, seconds)], "source", "files"}``.
    Prefers trace-event JSON (always written, module names included);
    falls back to ``.xplane.pb`` where the TF protobuf is importable.
    Raises :class:`TraceError` when the directory holds no capture or
    the capture holds no device ops.
    """
    trace_dir = str(trace_dir)
    files = find_trace_files(trace_dir)
    ops, source = [], "trace-json"
    for path in files:
        ops += device_ops(load_trace_events(path))
    if not ops:
        pbs = sorted(glob.glob(f"{trace_dir}/**/*.xplane.pb",
                               recursive=True))
        if pbs and have_xplane():
            source = "xplane"
            for path in pbs:
                ops += xplane_ops(path)
            files = pbs
        elif not files and not pbs:
            raise TraceError(
                f"no profiler capture under {trace_dir} (expected "
                f"*.trace.json[.gz] or *.xplane.pb)")
    if not ops:
        raise TraceError(
            f"capture under {trace_dir} contains no device op events "
            f"(nothing executed inside the trace window?)")
    return {"ops": ops, "source": source, "files": files}


def class_seconds(ops):
    """``{class: seconds}`` rollup over ``(module, op, seconds)``."""
    out = {}
    for _, op, s in ops:
        c = op_class(op)
        out[c] = out.get(c, 0.0) + s
    return out


# -- machine + roofline -------------------------------------------------------


def machine_spec():
    """The identity + peaks of the attached accelerator; calibration
    pins are scoped per ``machine_id`` so a CPU pin never gates a TPU
    run."""
    import jax

    dev = jax.devices()[0]
    platform = dev.platform
    kind = getattr(dev, "device_kind", platform) or platform
    machine_id = f"{platform}:{kind}".lower().replace(" ", "-")
    peak_flops, peak_bw = _PEAKS.get(platform, _PEAKS["cpu"])
    return {"machine_id": machine_id, "platform": platform,
            "device_kind": str(kind), "n_devices": jax.device_count(),
            "peak_flops": peak_flops, "peak_bytes_per_s": peak_bw}


def predicted_classes(op_cost_list, spec):
    """Re-bucket graftcost's per-op records with :func:`op_class` (so
    collectives/infeed land in their runtime classes, not elementwise)
    and roofline each class: ``max(flops/peak, bytes/bw)`` seconds."""
    classes = {}
    for o in op_cost_list:
        c = classes.setdefault(op_class(o.op),
                               {"flops": 0, "bytes": 0, "ops": 0})
        c["flops"] += o.flops
        c["bytes"] += o.bytes
        c["ops"] += 1
    for c in classes.values():
        c["seconds"] = max(c["flops"] / spec["peak_flops"],
                           c["bytes"] / spec["peak_bytes_per_s"])
    return classes


# -- segmented capture --------------------------------------------------------


def profile_entries(entries, out_dir, repeats=2):
    """Run every ``(program, args, kwargs)`` audit entry inside its own
    trace segment under ``out_dir`` and write the segment manifest.

    Per entry: lower (fingerprint + static per-class costs), one
    un-traced warmup call (compile outside the window), then
    ``repeats`` traced calls with a ``block_until_ready`` inside the
    window. Returns the manifest dict (also written to
    ``out_dir/graftprof-manifest.json``).
    """
    import jax

    from . import cost
    from .hlo import fingerprint, strip_locations

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    spec = machine_spec()
    segments = []
    for i, (program, args, kwargs) in enumerate(entries):
        key = program.key.canonical() if program.key else program.label
        text = strip_locations(program.lower(*args).as_text())
        ops = cost.op_costs(text,
                            expect_bf16=kwargs.get("expect_bf16", False))
        seg = out_dir / f"seg-{i:03d}"
        outv = program(*args)  # warmup: compile outside the window
        jax.block_until_ready(outv)  # graftlint: disable=host-sync -- profiling harness: sync fences the warmup out of the capture window
        jax.profiler.start_trace(str(seg))
        try:
            for _ in range(repeats):
                outv = program(*args)
            jax.block_until_ready(outv)  # graftlint: disable=host-sync -- profiling harness: sync closes the timed window so the trace holds all repeats
        finally:
            jax.profiler.stop_trace()
        segments.append({
            "dir": seg.name,
            "key": key,
            "label": program.label,
            "kind": kwargs.get("kind") or
            (program.key.kind if program.key else "?"),
            "fingerprint": fingerprint(text),
            "repeats": repeats,
            "predicted_classes": predicted_classes(ops, spec),
        })
    manifest = {"version": 1, "machine": spec, "segments": segments}
    (out_dir / MANIFEST_NAME).write_text(
        json.dumps(manifest, indent=2) + "\n")
    return manifest


def attribute_segments(out_dir, manifest=None):
    """Per-program measured reports from a segmented capture dir."""
    out_dir = Path(out_dir)
    if manifest is None:
        path = out_dir / MANIFEST_NAME
        if not path.exists():
            raise TraceError(f"no {MANIFEST_NAME} under {out_dir}")
        manifest = json.loads(path.read_text())
    spec = manifest["machine"]
    reports = []
    for seg in manifest["segments"]:
        collected = collect_trace(out_dir / seg["dir"])
        repeats = max(1, seg.get("repeats", 1))
        measured = {c: s / repeats
                    for c, s in class_seconds(collected["ops"]).items()}
        reports.append(_build_report(seg, measured, spec,
                                     source=collected["source"]))
    return reports


def _build_report(seg, measured_classes, spec, source):
    """One calibration-table row: measured vs predicted per class."""
    predicted = seg["predicted_classes"]
    classes = {}
    for c in sorted(set(measured_classes) | set(predicted)):
        m = measured_classes.get(c, 0.0)
        p = predicted.get(c, {}).get("seconds", 0.0)
        classes[c] = {"seconds": round(m, 6),
                      "predicted_seconds": round(p, 6)}
        if p > 0:
            classes[c]["ratio"] = round(m / p, 4)
    device_s = sum(measured_classes.values())
    predicted_s = sum(p.get("seconds", 0.0) for p in predicted.values())
    flops = sum(p.get("flops", 0) for p in predicted.values())
    nbytes = sum(p.get("bytes", 0) for p in predicted.values())
    report = {
        "key": seg["key"],
        "label": seg.get("label", seg["key"]),
        "kind": seg.get("kind", "?"),
        "fingerprint": seg.get("fingerprint"),
        "repeats": seg.get("repeats", 1),
        "source": source,
        "device_seconds": round(device_s, 6),
        "predicted_seconds": round(predicted_s, 6),
        "classes": classes,
        "flops": flops,
        "bytes": nbytes,
    }
    if predicted_s > 0:
        report["ratio"] = round(device_s / predicted_s, 4)
    if device_s > 0:
        report["achieved_flops"] = round(flops / device_s, 1)
        report["achieved_bytes_per_s"] = round(nbytes / device_s, 1)
    return report


# -- pinned calibration budget ------------------------------------------------


class ProfBudget:
    """Machine-scoped pinned calibration ratios, graftcost's ``Budget``
    discipline: unpinned program → finding, ratio outside the pinned
    multiplicative band → finding, stale pins reported (pruned by
    ``--update``). A fingerprint mismatch against the pin is *not* a
    finding — graftcost already gates the static side; here it renders
    as a stale-calibration note so a tolerated model tweak doesn't go
    red twice."""

    VERSION = 1

    def __init__(self, data=None, path=None):
        data = data or {}
        if data and data.get("version", self.VERSION) != self.VERSION:
            raise ValueError(
                f"unsupported prof-budget version {data.get('version')!r}")
        self.path = path
        self.comment = data.get("comment", "")
        self.tolerance = {**DEFAULT_TOLERANCE, **data.get("tolerance", {})}
        self.machines = {m: dict(v.get("entries", {}))
                         for m, v in data.get("machines", {}).items()}
        self._hits = {m: {k: 0 for k in e}
                      for m, e in self.machines.items()}

    @classmethod
    def load(cls, path):
        return cls(json.loads(Path(path).read_text()), path=str(path))

    @classmethod
    def empty(cls):
        return cls()

    def entries_for(self, machine_id):
        return self.machines.get(machine_id, {})

    def unused_entries(self, machine_id):
        """Pinned keys for this machine no profiled program matched."""
        return [k for k, n in self._hits.get(machine_id, {}).items()
                if n == 0]

    def _band(self, pinned, tol):
        return pinned / (1.0 + tol), pinned * (1.0 + tol)

    def check(self, report, machine_id):
        """Findings for one measured report against its machine pin."""
        key = report["key"]
        entries = self.machines.get(machine_id, {})
        entry = entries.get(key)
        findings = []
        if entry is None:
            findings.append(Finding(
                rule="prof-unpinned", path="analysis/profile", line=1,
                message=f"{key}: no pinned calibration for machine "
                        f"{machine_id} in {self.path or BUDGET_NAME}; "
                        f"pin it with scripts/graftprof.py --update"))
            return findings
        self._hits[machine_id][key] += 1
        if entry.get("fingerprint") and report.get("fingerprint") and \
                entry["fingerprint"] != report["fingerprint"]:
            # rendered as a note, not gated: the program changed since
            # the pin (graftcost's jurisdiction) — the ratio band below
            # still applies and catches real slowdowns
            report["stale_fingerprint"] = True
        ratio = report.get("ratio")
        pinned = entry.get("ratio")
        tol = self.tolerance.get("ratio", DEFAULT_TOLERANCE["ratio"])
        if ratio is not None and pinned:
            lo, hi = self._band(pinned, tol)
            if not (lo <= ratio <= hi):
                findings.append(Finding(
                    rule="prof-calibration", path="analysis/profile",
                    line=1,
                    message=f"{key}: measured/predicted ratio {ratio:.2f}"
                            f" vs pinned {pinned:.2f} on {machine_id} "
                            f"(band [{lo:.2f}, {hi:.2f}]) — re-pin "
                            f"deliberately with scripts/graftprof.py "
                            f"--update if the change is intended"))
        ctol = self.tolerance.get("class_ratio",
                                  DEFAULT_TOLERANCE["class_ratio"])
        total_pred = report.get("predicted_seconds") or 0.0
        pinned_classes = entry.get("classes", {})
        for cls, c in sorted(report.get("classes", {}).items()):
            p = pinned_classes.get(cls)
            share = (c.get("predicted_seconds", 0.0) / total_pred
                     if total_pred else 0.0)
            if p is None or "ratio" not in c or not p.get("ratio") or \
                    share < MIN_CLASS_SHARE:
                continue
            lo, hi = self._band(p["ratio"], ctol)
            if not (lo <= c["ratio"] <= hi):
                findings.append(Finding(
                    rule="prof-calibration", path="analysis/profile",
                    line=1,
                    message=f"{key}: {cls} ratio {c['ratio']:.2f} vs "
                            f"pinned {p['ratio']:.2f} on {machine_id} "
                            f"(band [{lo:.2f}, {hi:.2f}], "
                            f"{share:.0%} of predicted step)"))
        return findings

    @staticmethod
    def entry_for(report):
        entry = {
            "device_seconds": report["device_seconds"],
            "fingerprint": report.get("fingerprint"),
            "classes": {c: {k: v for k, v in d.items() if k == "ratio"}
                        for c, d in report.get("classes", {}).items()
                        if "ratio" in d},
        }
        if "ratio" in report:
            entry["ratio"] = report["ratio"]
        return entry

    def pinned_data(self, reports, machine_id):
        """The re-pinned payload for ``--update``: replaces this
        machine's entries, preserves every other machine's pins."""
        machines = {m: {"entries": e} for m, e in self.machines.items()}
        machines[machine_id] = {
            "entries": {r["key"]: self.entry_for(r) for r in reports}}
        return {
            "version": self.VERSION,
            "comment": self.comment or (
                "Pinned measured/predicted calibration ratios "
                "(scripts/graftprof.py). Scoped per machine_id — a "
                "ratio pinned on one accelerator never gates another. "
                "Tolerances are wide multiplicative bands: the roofline "
                "constants cancel in the ratio, only movement flags. "
                "Re-pin deliberately with --update."),
            "tolerance": dict(self.tolerance),
            "machines": machines,
        }


@dataclass
class ProfReport:
    """One graftprof run: measured reports + calibration findings."""
    reports: list = field(default_factory=list)
    findings: list = field(default_factory=list)
    stale: list = field(default_factory=list)
    machine: dict = field(default_factory=dict)

    @property
    def ok(self):
        return not self.findings

    def to_dict(self):
        return {
            "ok": self.ok,
            "machine": self.machine,
            "programs": len(self.reports),
            "findings": [f.to_dict() for f in self.findings],
            "stale_budget_entries": list(self.stale),
            "reports": self.reports,
        }


def audit_profiles(entries=None, budget=None, out_dir=None, repeats=2,
                   **build_kwargs):
    """Capture + attribute + gate every audit entry (defaults to
    graftcost's :func:`analysis.cost.build_entries` set, so the
    calibration table covers exactly the programs ``hlo-budget.json``
    pins). Returns a :class:`ProfReport`."""
    from . import cost

    if entries is None:
        entries = cost.build_entries(**build_kwargs)
    if budget is None:
        budget = ProfBudget.empty()
    tmp = None
    if out_dir is None:
        tmp = out_dir = tempfile.mkdtemp(prefix="rmd-graftprof-")
    try:
        manifest = profile_entries(entries, out_dir, repeats=repeats)
        out = ProfReport(machine=manifest["machine"])
        machine_id = manifest["machine"]["machine_id"]
        for report in attribute_segments(out_dir, manifest):
            out.reports.append(report)
            if budget.machines or budget.path:
                out.findings.extend(budget.check(report, machine_id))
        out.stale = budget.unused_entries(machine_id)
        return out
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


# -- post-hoc attribution (unsegmented captures) ------------------------------


def _module_map():
    """``module name -> [program key]`` over the live registry: jax
    names a jitted module ``jit_<fn.__name__>``, so the mapping is a
    guess — callers only trust unambiguous (single-program) names."""
    from ..compile.registry import registry as program_registry

    out = {}
    for prog in program_registry().programs():
        fn = getattr(prog, "__wrapped__", None)
        name = getattr(fn, "__name__", None) or \
            getattr(getattr(fn, "__wrapped__", None), "__name__", None)
        if not name:
            continue
        key = prog.key.canonical() if prog.key else prog.label
        out.setdefault(f"jit_{name}", []).append(key)
    return out


def attribute_trace(trace_dir, top_ops=5):
    """Best-effort attribution of an *unsegmented* capture (the
    ``/profilez`` and ``train --profile`` artifacts): device time per
    hlo module and op class, module names matched to registered
    programs where the mapping is unambiguous.

    Raises :class:`TraceError` on an unusable capture — callers on the
    serving path wrap this (an attribution failure must never fail the
    capture that produced the artifact).
    """
    collected = collect_trace(trace_dir)
    modmap = _module_map()
    per_module = {}
    for module, op, s in collected["ops"]:
        m = per_module.setdefault(module, {"seconds": 0.0, "classes": {},
                                           "ops": {}})
        m["seconds"] += s
        c = op_class(op)
        m["classes"][c] = m["classes"].get(c, 0.0) + s
        m["ops"][op] = m["ops"].get(op, 0.0) + s
    modules = []
    for name in sorted(per_module,
                       key=lambda n: -per_module[n]["seconds"]):
        m = per_module[name]
        keys = modmap.get(name, [])
        modules.append({
            "module": name,
            "program": keys[0] if len(keys) == 1 else None,
            "candidates": len(keys),
            "seconds": round(m["seconds"], 6),
            "classes": {c: round(s, 6)
                        for c, s in sorted(m["classes"].items(),
                                           key=lambda kv: -kv[1])},
            "top_ops": [{"op": o, "seconds": round(s, 6)}
                        for o, s in sorted(m["ops"].items(),
                                           key=lambda kv: -kv[1])
                        [:top_ops]],
        })
    return {
        "source": collected["source"],
        "device_seconds": round(sum(m["seconds"]
                                    for m in per_module.values()), 6),
        "op_events": len(collected["ops"]),
        "modules": modules,
    }


# -- telemetry / metrics / rendering ------------------------------------------


def emit_events(prof_report, tele):
    """Forward per-program calibration rows as ``profile`` telemetry."""
    drifted = {f.message.split(":", 1)[0] for f in prof_report.findings
               if f.rule == "prof-calibration"}
    for r in prof_report.reports:
        tele.emit(
            "profile", program=r["key"], program_kind=r["kind"],
            seconds=r["device_seconds"],
            predicted_seconds=r["predicted_seconds"],
            ratio=r.get("ratio"),
            classes={c: d.get("seconds", 0.0)
                     for c, d in r.get("classes", {}).items()},
            machine=prof_report.machine.get("machine_id", "?"),
            drift=r["key"] in drifted,
            stale_fingerprint=bool(r.get("stale_fingerprint")))


def publish_metrics(prof_report, registry):
    """Export the calibration table as ``rmd_prof_*`` gauges."""
    g_sec = registry.gauge(
        "rmd_prof_device_seconds",
        "measured device seconds per step, last attribution",
        ("program",))
    g_ratio = registry.gauge(
        "rmd_prof_calibration_ratio",
        "measured/predicted roofline-seconds ratio, last attribution",
        ("program",))
    g_cls = registry.gauge(
        "rmd_prof_class_seconds",
        "measured device seconds per op class, last attribution",
        ("klass",))
    totals = {}
    for r in prof_report.reports:
        g_sec.labels(program=r["kind"]).set(r["device_seconds"])
        if "ratio" in r:
            g_ratio.labels(program=r["kind"]).set(r["ratio"])
        for c, d in r.get("classes", {}).items():
            totals[c] = totals.get(c, 0.0) + d.get("seconds", 0.0)
    for c, s in totals.items():
        g_cls.labels(klass=c).set(round(s, 6))


def publish_attribution_metrics(summary, registry):
    """Export an :func:`attribute_trace` summary (module-granular) as
    the same ``rmd_prof_*`` gauges — the /profilez path."""
    g_sec = registry.gauge(
        "rmd_prof_device_seconds",
        "measured device seconds per step, last attribution",
        ("program",))
    g_cls = registry.gauge(
        "rmd_prof_class_seconds",
        "measured device seconds per op class, last attribution",
        ("klass",))
    totals = {}
    for m in summary.get("modules", []):
        g_sec.labels(program=m["program"] or m["module"]).set(m["seconds"])
        for c, s in m.get("classes", {}).items():
            totals[c] = totals.get(c, 0.0) + s
    for c, s in totals.items():
        g_cls.labels(klass=c).set(round(s, 6))


def render_reports(prof_report):
    """The human-readable calibration table (CLI text format)."""
    mach = prof_report.machine
    out = ["== profiling ==",
           f"machine: {mach.get('machine_id', '?')} "
           f"({mach.get('n_devices', '?')} device(s), roofline "
           f"{mach.get('peak_flops', 0) / 1e12:.1f} TFLOP/s, "
           f"{mach.get('peak_bytes_per_s', 0) / 2 ** 30:.0f} GiB/s)"]
    for r in prof_report.reports:
        ratio = f"{r['ratio']:.2f}" if "ratio" in r else "-"
        stale = " [stale fingerprint]" if r.get("stale_fingerprint") \
            else ""
        out.append(
            f"{r['key']}: measured {r['device_seconds'] * 1e3:.1f} ms "
            f"vs predicted {r['predicted_seconds'] * 1e3:.1f} ms "
            f"(ratio {ratio}), "
            f"{r.get('achieved_flops', 0) / 1e9:.2f} GFLOP/s, "
            f"{r.get('achieved_bytes_per_s', 0) / 2 ** 30:.2f} GiB/s"
            f"{stale}")
        for c, d in sorted(r.get("classes", {}).items(),
                           key=lambda kv: -kv[1].get("seconds", 0.0)):
            cr = f"{d['ratio']:.2f}" if "ratio" in d else "-"
            out.append(f"    {c:12s} {d.get('seconds', 0) * 1e3:8.2f} ms"
                       f" vs {d.get('predicted_seconds', 0) * 1e3:8.2f}"
                       f" ms  (ratio {cr})")
    for f in prof_report.findings:
        out.append(f"  ! {f.rule}: {f.message}")
    for key in prof_report.stale:
        out.append(f"  stale calibration entry: {key}")
    return "\n".join(out)


def render_attribution(summary, top_modules=6):
    """Compact text form of an :func:`attribute_trace` summary."""
    out = [f"device op time: {summary['device_seconds'] * 1e3:.1f} ms "
           f"over {summary['op_events']} op event(s) "
           f"[{summary['source']}]"]
    for m in summary.get("modules", [])[:top_modules]:
        who = m["module"]
        if m.get("program"):
            who += f" -> {m['program']}"
        elif m.get("candidates", 0) > 1:
            who += f" (ambiguous: {m['candidates']} programs)"
        classes = ", ".join(
            f"{c} {100 * s / m['seconds']:.0f}%"
            for c, s in list(m["classes"].items())[:4]) if m["seconds"] \
            else "-"
        out.append(f"  {m['seconds'] * 1e3:8.1f} ms  {who}  [{classes}]")
    return "\n".join(out)
