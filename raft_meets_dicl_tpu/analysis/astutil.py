"""Shared AST machinery for the graftlint rules.

The TPU-hazard rules all need the same three questions answered about a
module, so the plumbing lives here instead of in each rule:

1. *Which functions exist?* — a qualified-name table over the module's
   (possibly nested) function definitions (``function_table``).
2. *Which of them are jit roots?* — functions decorated with or passed
   to ``jax.jit``-family transforms, and functions registered as step
   programs through ``compile.register_step`` (``jit_roots``).
3. *What can a root reach?* — an intra-module call graph over plain-name
   calls **and** plain-name call arguments (functions handed to
   ``lax.scan``/``vmap``/``checkpoint`` are invoked by the callee, so a
   name passed into any call is treated as potentially called), walked
   breadth-first (``reachable``).

Resolution is lexical: a name used inside ``make_train_step.step``
resolves against ``make_train_step.step.<name>``, then
``make_train_step.<name>``, then ``<name>`` — mirroring Python's scoping
closely enough for the hazard rules (no imports are chased; cross-module
reachability is out of scope by design, the rules run per module).
"""

import ast
from dataclasses import dataclass, field

# decorator / call names that make a function a jit root
JIT_NAMES = {"jit", "pjit", "pmap"}
# functions whose function-valued argument becomes a registered step
REGISTER_NAMES = {"register_step"}


def dotted_name(node):
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tail(dotted):
    return dotted.rsplit(".", 1)[-1] if dotted else None


@dataclass
class FuncInfo:
    """One function definition with its lexical position."""
    qualname: str
    node: ast.AST
    scope: tuple  # enclosing function qualnames, outermost first
    params: tuple = field(default_factory=tuple)


class _Collector(ast.NodeVisitor):
    def __init__(self):
        self.table = {}
        self._stack = []  # qualname components (classes and functions)
        self._fn_stack = []  # enclosing *function* qualnames

    def _visit_fn(self, node):
        qual = ".".join(self._stack + [node.name])
        a = node.args
        params = tuple(p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs)
        if a.vararg:
            params += (a.vararg.arg,)
        if a.kwarg:
            params += (a.kwarg.arg,)
        self.table[qual] = FuncInfo(qual, node, tuple(self._fn_stack),
                                    params)
        self._stack.append(node.name)
        self._fn_stack.append(qual)
        self.generic_visit(node)
        self._fn_stack.pop()
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()


def function_table(tree):
    """qualname -> :class:`FuncInfo` for every function in the module."""
    c = _Collector()
    c.visit(tree)
    return c.table


def resolve(name, scope, table):
    """Resolve a bare name against the lexical scope chain; returns the
    qualname of a known function or None."""
    for i in range(len(scope), -1, -1):
        cand = scope[i - 1] + "." + name if i else name
        if cand in table:
            return cand
    return None


def _is_jit_expr(node):
    """Whether an expression is a jit-family transform reference or a
    ``partial(jax.jit, ...)``-style wrapper of one."""
    tail = _tail(dotted_name(node))
    if tail in JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        if _tail(dotted_name(node.func)) == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        # jax.jit(f, static_argnums=...) used as a decorator factory
        return _is_jit_expr(node.func)
    return False


def jit_roots(tree, table):
    """Qualnames of functions that enter jit: decorated with a jit-family
    transform, passed (as a plain name) to a jit-family call, or passed
    to ``register_step``."""
    roots = set()
    for qual, info in table.items():
        for dec in getattr(info.node, "decorator_list", ()):
            if _is_jit_expr(dec):
                roots.add(qual)

    class _Calls(ast.NodeVisitor):
        def __init__(self):
            self._fn_stack = []

        def _visit_fn(self, node):
            qual = (self._fn_stack[-1] + "." if self._fn_stack else "") \
                + node.name
            self._fn_stack.append(qual)
            self.generic_visit(node)
            self._fn_stack.pop()

        visit_FunctionDef = _visit_fn
        visit_AsyncFunctionDef = _visit_fn

        def visit_Call(self, node):
            scope = tuple(self._fn_stack)
            tail = _tail(dotted_name(node.func))
            if tail in JIT_NAMES:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        hit = resolve(arg.id, scope, table)
                        if hit:
                            roots.add(hit)
            if tail in REGISTER_NAMES:
                cands = list(node.args[1:2]) + [
                    kw.value for kw in node.keywords if kw.arg == "fn"]
                for arg in cands:
                    if isinstance(arg, ast.Name):
                        hit = resolve(arg.id, scope, table)
                        if hit:
                            roots.add(hit)
            self.generic_visit(node)

    _Calls().visit(tree)
    return roots


def call_graph(table):
    """qualname -> set(qualname): plain-name calls plus plain-name call
    arguments, resolved lexically. Nested function bodies belong to the
    nested function, not the enclosing one."""
    graph = {qual: set() for qual in table}
    for qual, info in table.items():
        scope = info.scope + (qual,)
        own_nested = {q for q, i in table.items() if qual in i.scope}

        for node in ast.walk(info.node):
            # skip statements owned by a nested def: they get their own
            # edges, and reaching them requires a call/pass-through edge
            if node is not info.node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Call):
                continue
            if _owner(node, info, table, own_nested) != qual:
                continue
            if isinstance(node.func, ast.Name):
                hit = resolve(node.func.id, scope, table)
                if hit:
                    graph[qual].add(hit)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    hit = resolve(arg.id, scope, table)
                    if hit:
                        graph[qual].add(hit)
    return graph


def _owner(node, info, table, own_nested):
    """Qualname of the innermost function whose body contains ``node``.

    Cheap containment test via line spans: the innermost nested function
    whose [lineno, end_lineno] range covers the node wins; falls back to
    ``info.qualname``.
    """
    line = getattr(node, "lineno", None)
    if line is None:
        return info.qualname
    best, best_span = info.qualname, None
    for q in own_nested:
        n = table[q].node
        if n.lineno <= line <= (n.end_lineno or n.lineno):
            span = (n.end_lineno or n.lineno) - n.lineno
            if best_span is None or span < best_span:
                best, best_span = q, span
    return best


def reachable(roots, graph):
    """BFS closure of ``roots`` over the call graph."""
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        cur = frontier.pop()
        for nxt in graph.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return seen


def jit_reachable(tree, table=None):
    """Qualnames of functions reachable from any jit root in ``tree``."""
    table = table if table is not None else function_table(tree)
    return reachable(jit_roots(tree, table), call_graph(table))


def body_nodes(info, table):
    """AST nodes owned directly by ``info``'s body (nested defs', class
    bodies' nodes excluded — they belong to their own functions)."""
    nested = [table[q].node for q in table
              if info.qualname in table[q].scope]

    def owned(node):
        line = getattr(node, "lineno", None)
        if line is None:
            return True
        for n in nested:
            if n is not info.node and \
                    n.lineno <= line <= (n.end_lineno or n.lineno):
                return False
        return True

    for node in ast.walk(info.node):
        if node is info.node:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if owned(node):
            yield node
