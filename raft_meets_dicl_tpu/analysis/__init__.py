"""graftlint: TPU-hazard static analysis + HLO program auditing.

Public surface:

- ``lint.run(root)`` — the AST lint pass (host-sync, tracer-branch,
  f32-literal, env-knob/env-docs rules) with suppression + baseline
  handling; ``lint.render_text`` / ``Report.to_dict`` for output,
  ``lint.emit_events`` to forward findings as ``lint`` telemetry.
- ``hlo.audit_registry()`` — lower/compile-time audit of the registered
  step programs: fingerprint stability, collective counts, f32 convs,
  baked-in constants.
- ``cost.audit_costs()`` — graftcost: static per-op FLOP/byte cost
  model over the lowered StableHLO (MXU tile-utilization verdicts,
  f32-upcast / gather-scalarization hazards) plus the
  ``collectives`` sharding-contract diff, gated against the pinned
  per-program budgets in ``hlo-budget.json``.

``scripts/graftlint.py`` and ``scripts/graftcost.py`` are the CLIs; the
``lint``- and ``cost``-marked tests run the passes in tier-1.

The lint half never *uses* jax (no tracing, no device access — pure
``ast`` over source text), so it runs anywhere the package imports,
with no accelerator attached; only ``hlo`` lowers and compiles
programs.
"""

from . import astutil, lint
from .lint import Baseline, Finding, Module, Report, Rule, run

__all__ = ["astutil", "lint", "Baseline", "Finding", "Module", "Report",
           "Rule", "run"]
