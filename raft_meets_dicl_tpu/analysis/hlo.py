"""Trace-time program auditor over the PR-7 compiled-program registry.

Static AST lint (``analysis.lint``) sees the source; this module sees
what XLA will actually run. For a registered :class:`compile.Program` it
lowers the jitted function and audits the result:

- **fingerprint stability** — the program is lowered *twice* and the
  canonicalized StableHLO (location metadata stripped) must hash
  identically. Nondeterministic lowering (iteration over an unordered
  container, a closure capturing fresh objects) makes every boot a
  persistent-cache miss and every AOT artifact unreachable — precisely
  the cold-start tax PR-7 exists to kill.
- **collective counts** — taken from the *compiled* (post-GSPMD) HLO,
  where sharding constraints have become all-gather/all-reduce/
  reduce-scatter ops. This guards the PR-6 ZeRO contract: a sharded
  train step must contain its gather/reduce pair, and any multi-device
  step with zero cross-device ops means the gradient sync silently
  vanished.
- **f32 convolutions under a bf16 policy** — a mixed-precision model
  whose lowered graph still convolves in f32 lost its policy somewhere
  between Flax and XLA.
- **baked-in constants > 1 MiB** — closure-captured weights serialized
  into the program body: HBM paid per executable, AOT artifacts bloated,
  and the persistent cache keyed on tensor *values*.

The compile needed for the collective audit routes through jax's
persistent compile cache like any other — on a warm cache the audit
triggers zero fresh backend compiles (the acceptance bar for running it
in tier-1).
"""

import hashlib
import re

from .lint import Finding

# strip MLIR location metadata: `loc(...)` trailers and `#loc...` lines
_LOC_RE = re.compile(r"\s*loc\([^)]*\)")
_LOC_LINE_RE = re.compile(r"^#loc.*$", re.MULTILINE)

_STABLEHLO_COLLECTIVES = ("all_reduce", "all_gather", "all_to_all",
                          "reduce_scatter", "collective_permute")
_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute)(?:-start)?\b")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "c64": 8, "c128": 16,
    # sub-f32 widths the quantized matching tier (and any f8 recipe)
    # streams: counting these at the 4-byte unknown-dtype fallback would
    # erase exactly the HBM saving the tier exists for
    "f8e4m3": 1, "f8e3m4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3b11fnuz": 1, "f8e8m0fnu": 1,
}

# sub-byte element widths in bits; byte counts round up per tensor
_DTYPE_BITS = {"i4": 4, "ui4": 4, "i2": 2, "ui2": 2}

_TENSOR_RE = re.compile(r"tensor<([0-9x]*)x?([a-z][a-z0-9]*)>")
_CONST_RE = re.compile(
    r"stablehlo\.constant[^:\n]*:\s*tensor<([0-9x]+)x([a-z]+[0-9]*)>")

LARGE_CONST_BYTES = 1 << 20  # 1 MiB


def strip_locations(text):
    """StableHLO text minus MLIR location metadata — the parts that may
    legitimately differ between two lowerings of the same program."""
    return _LOC_LINE_RE.sub("", _LOC_RE.sub("", text))


def fingerprint(text):
    """sha256 over the canonicalized module text."""
    return hashlib.sha256(strip_locations(text).encode()).hexdigest()


def _tensor_bytes(dims, dtype):
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)  # graftlint: disable=host-sync -- parses an HLO dims string, not a device value
    if dtype in _DTYPE_BITS:
        return (n * _DTYPE_BITS[dtype] + 7) // 8
    return n * _DTYPE_BYTES.get(dtype, 4)


def audit_stablehlo(text):
    """Counts over a lowered StableHLO module's text."""
    collectives = {}
    for op in _STABLEHLO_COLLECTIVES:
        n = text.count(f"stablehlo.{op} ") + text.count(f"stablehlo.{op}(")
        if n:
            collectives[op.replace("_", "-")] = n

    f32_convs = 0
    for line in text.splitlines():
        if "stablehlo.convolution" not in line:
            continue
        _, _, result = line.rpartition("->")
        m = _TENSOR_RE.search(result)
        if m and m.group(2) == "f32":
            f32_convs += 1

    large = []
    for m in _CONST_RE.finditer(text):
        nbytes = _tensor_bytes(m.group(1), m.group(2))
        if nbytes > LARGE_CONST_BYTES:
            large.append({"type": f"tensor<{m.group(1)}x{m.group(2)}>",
                          "bytes": nbytes})

    return {"collectives": collectives, "f32_convolutions": f32_convs,
            "large_constants": large}


def audit_compiled(text):
    """Collective counts over compiled (post-GSPMD) HLO text."""
    counts = {}
    for line in text.splitlines():
        if " = " not in line:
            continue
        for m in _HLO_COLLECTIVE_RE.finditer(line.split(" = ", 1)[1]):
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def audit_program(program, args, expect_bf16=False, n_devices=1,
                  expect_gather=False, do_compile=True, **cost_context):
    """Audit one registered program against concrete example args.

    Returns ``(report, findings)``. The program is lowered twice for the
    fingerprint-stability check; when ``do_compile``, the second lowering
    is compiled (persistent-cache eligible) and its post-GSPMD HLO
    provides the collective counts.

    ``cost_context`` (``partitioner``/``params``) is accepted and unused:
    the builders below return one ``(program, args, audit_kwargs)`` list
    shared with ``analysis.cost``, whose collective-contract auditor
    consumes those keys.
    """
    path = "analysis/hlo"  # findings anchor to the audit, not a file
    key = program.key.canonical() if program.key else program.label

    lowered_a = program.lower(*args)
    text_a = lowered_a.as_text()
    lowered_b = program.lower(*args)
    text_b = lowered_b.as_text()

    fp_a, fp_b = fingerprint(text_a), fingerprint(text_b)
    stable = fp_a == fp_b

    report = {
        "key": key,
        "label": program.label,
        "fingerprint": fp_a,
        "fingerprint_stable": stable,
        **audit_stablehlo(text_a),
    }

    findings = []
    if not stable:
        findings.append(Finding(
            rule="hlo-fingerprint", path=path, line=1,
            message=f"{key}: two lowerings produced different StableHLO "
                    f"({fp_a[:12]} vs {fp_b[:12]}) — nondeterministic "
                    f"lowering defeats the persistent compile cache and "
                    f"the AOT store"))
    if expect_bf16 and report["f32_convolutions"]:
        findings.append(Finding(
            rule="hlo-f32-conv", path=path, line=1,
            message=f"{key}: {report['f32_convolutions']} f32 "
                    f"convolution(s) lowered under a bf16 policy"))
    for c in report["large_constants"]:
        findings.append(Finding(
            rule="hlo-const-bake", path=path, line=1,
            message=f"{key}: {c['bytes'] / 2**20:.1f} MiB constant "
                    f"{c['type']} baked into the program (closure-"
                    f"captured array? pass it as an argument)"))

    if do_compile:
        compiled = lowered_b.compile()
        comp_collectives = audit_compiled(compiled.as_text())
        report["compiled_collectives"] = comp_collectives
        total = sum(comp_collectives.values())
        if n_devices > 1 and total == 0:
            findings.append(Finding(
                rule="hlo-collectives", path=path, line=1,
                message=f"{key}: compiled for {n_devices} devices with "
                        f"ZERO collectives — cross-device sync (grad "
                        f"all-reduce / ZeRO gather) vanished"))
        if expect_gather and not (
                comp_collectives.get("all-gather")
                and (comp_collectives.get("reduce-scatter")
                     or comp_collectives.get("all-reduce"))):
            findings.append(Finding(
                rule="hlo-collectives", path=path, line=1,
                message=f"{key}: sharded-state step missing its ZeRO "
                        f"gather/reduce pair (got {comp_collectives})"))

    return report, findings


def build_flagship_programs(n_devices=2, shape=(48, 64), mesh2d=False):
    """Register the raft-baseline tiny-shape train + eval steps on a CPU
    mesh and return ``[(program, args, audit_kwargs)]`` for auditing.

    Mirrors ``__graft_entry__``'s dry-run construction (same model
    config, tiny shapes) so the persistent compile cache and AOT store
    warmed by earlier boots serve this audit without fresh compiles.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import compile as programs, models, parallel

    flagship = {
        "name": "RAFT baseline", "id": "raft-baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    spec = models.load(flagship)
    model, loss = spec.model, spec.loss
    h, w = shape
    b = n_devices
    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(b, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(b, h, w, 3).astype(np.float32))
    flow = jnp.asarray(rng.randn(b, h, w, 2).astype(np.float32))
    valid = jnp.asarray(np.ones((b, h, w), bool))

    model_args = {"iterations": 2}
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1],
                           **model_args)
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-4))

    if mesh2d and n_devices >= 2 and n_devices % 2 == 0:
        mesh = parallel.make_mesh((n_devices // 2, 2))
        partitioner = parallel.Partitioner(mesh)
    else:
        mesh = parallel.data_mesh(n_devices)
        partitioner = None

    state = parallel.TrainState.create(variables, tx)
    state_sharding = None
    expect_gather = False
    if partitioner is not None:
        state = partitioner.shard_state(state)
        state_sharding = partitioner.state_shardings(state)
        expect_gather = parallel.partition.is_sharded(
            state_sharding.params)
    else:
        state = parallel.replicate(state, mesh)

    batch = parallel.shard_batch((img1, img2, flow, valid), mesh)

    train_key = programs.ProgramKey(
        kind="train_step", model="raft-baseline",
        flags=programs.flag_items(shape=(b, h, w), audit=1,
                                  mesh2d=bool(partitioner)))
    train_prog = parallel.make_train_step(
        model, loss, tx, mesh=mesh, model_args=model_args,
        state_sharding=state_sharding, donate=False, key=train_key)

    # make_eval_step extends caller keys with the effective model args
    # (the iterations-collision fix), so use the returned program rather
    # than re-fetching the pre-extension key from the registry
    eval_key = programs.ProgramKey(
        kind="eval_step", model="raft-baseline",
        flags=programs.flag_items(shape=(b, h, w), audit=1))
    eval_prog = parallel.make_eval_step(model, mesh=mesh,
                                        model_args=model_args, key=eval_key)

    eval_variables = jax.device_put(
        variables, parallel.partition.replicated(mesh))

    out = []
    out.append((train_prog, (state, *batch),
                {"n_devices": n_devices, "expect_gather": expect_gather,
                 "partitioner": partitioner,
                 "params": variables["params"]}))
    out.append((eval_prog, (eval_variables, batch[0], batch[1]),
                {"n_devices": n_devices}))
    return out


def build_ladder_programs(rungs=(2, 4, 6), shape=(48, 64), batch=1,
                          mixed_precision=True):
    """Register every iteration-ladder rung program of a tiny
    mixed-precision raft model and return ``[(program, args,
    audit_kwargs)]`` for auditing.

    The ladder contract the audit pins: each rung the ladder executes —
    base, distinct continuation increments, monolithic full budget — is
    exactly one registered program (one ``ProgramKey`` flag variant),
    however many latency classes or batch fill levels ride it; each
    lowers fingerprint-stably (else every boot misses the AOT store);
    and the bf16 policy survives into the rung graphs (no f32
    convolutions).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import evaluation, models
    from ..serve.ladder import LadderSpec

    cfg = {
        "name": "ladder audit", "id": "ladder-audit",
        "model": {"type": "raft/baseline",
                  "parameters": {"corr-levels": 2, "corr-radius": 2,
                                 "corr-channels": 32,
                                 "context-channels": 16,
                                 "recurrent-channels": 16,
                                 "mixed-precision": mixed_precision}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    spec = models.load(cfg)
    model = spec.model
    h, w = shape
    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iterations=1)

    lad = LadderSpec(rungs=rungs)
    base = evaluation.make_rung_fn(model, lad.rungs[0], model_id=spec.id)
    # one base execution provides correctly-shaped carries for the
    # continuation rungs' example args
    _, state = base(variables, img1, img2)

    kwargs = {"expect_bf16": mixed_precision, "n_devices": 1}
    entries = [(base, (variables, img1, img2), dict(kwargs))]
    for its, cont in lad.programs():
        if (its, cont) == (lad.rungs[0], False):
            continue
        prog = evaluation.make_rung_fn(model, its, cont=cont,
                                       model_id=spec.id)
        args = ((variables, img1, img2, state["flow"], state["hidden"])
                if cont else (variables, img1, img2))
        entries.append((prog, args, dict(kwargs)))
    return entries


def build_warm_programs(rungs=(2, 4, 6), shape=(48, 64), batch=1,
                        mixed_precision=True):
    """Register the video warm-start program variants of the ladder-audit
    model and return ``[(program, args, audit_kwargs)]`` for auditing.

    The warm-start contract the audit pins: each rung has at most *one*
    warm variant — one registered program per (rung, warm) pair, keyed
    only by the added ``warm`` flag, so the plain ladder keys (and their
    pinned budgets) are untouched; each lowers fingerprint-stably; and
    the in-program forward projection does not break the bf16 policy
    (no f32 convolutions). The cost delta vs. the plain rung — the
    projection's gather/compare overhead — is pinned by graftcost.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import evaluation, models
    from ..serve.ladder import LadderSpec

    cfg = {
        "name": "ladder audit", "id": "ladder-audit",
        "model": {"type": "raft/baseline",
                  "parameters": {"corr-levels": 2, "corr-radius": 2,
                                 "corr-channels": 32,
                                 "context-channels": 16,
                                 "recurrent-channels": 16,
                                 "mixed-precision": mixed_precision}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    spec = models.load(cfg)
    model = spec.model
    h, w = shape
    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iterations=1)

    lad = LadderSpec(rungs=rungs)
    # the carry a warm program consumes is the coarse-grid flow the
    # plain base rung produces — run it once for a correctly-shaped
    # example arg
    base = evaluation.make_rung_fn(model, lad.rungs[0], model_id=spec.id)
    _, state = base(variables, img1, img2)

    kwargs = {"expect_bf16": mixed_precision, "n_devices": 1}
    entries = []
    warm = evaluation.make_warm_fn(model, lad.rungs[0], model_id=spec.id)
    entries.append((warm, (variables, img1, img2, state["flow"]),
                    dict(kwargs)))
    return entries


def build_quant_programs(rungs=(2, 4, 6), shape=(48, 64), batch=1,
                         mixed_precision=True):
    """Register the quantized matching-tier program variants of the
    ladder-audit model and return ``[(program, args, audit_kwargs)]``
    for auditing.

    The quant contract the audit pins: the u8 and i8 base rungs plus the
    u8 warm variant are each exactly one registered program, keyed only
    by the added ``quant`` flag (plain ladder/warm keys and their pinned
    budgets untouched); each lowers fingerprint-stably; the bf16 policy
    survives (the dequantized lookup runs bf16, not f32); and — the
    tier's reason to exist — the sub-f32 volume bytes show up in the
    pinned HBM traffic, which is what the integer-width byte accounting
    in ``cost._tensor_nbytes`` makes honest.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import evaluation, models
    from ..serve.ladder import LadderSpec

    cfg = {
        "name": "ladder audit", "id": "ladder-audit",
        "model": {"type": "raft/baseline",
                  "parameters": {"corr-levels": 2, "corr-radius": 2,
                                 "corr-channels": 32,
                                 "context-channels": 16,
                                 "recurrent-channels": 16,
                                 "mixed-precision": mixed_precision}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    spec = models.load(cfg)
    model = spec.model
    h, w = shape
    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iterations=1)

    lad = LadderSpec(rungs=rungs)
    kwargs = {"expect_bf16": mixed_precision, "n_devices": 1}
    entries = []
    for mode in ("u8", "i8"):
        prog = evaluation.make_rung_fn(model, lad.rungs[0], model_id=spec.id,
                                       quant=mode)
        entries.append((prog, (variables, img1, img2), dict(kwargs)))
    # the warm variant serves video warm frames on the quant tier; its
    # example carry is the quant base rung's coarse flow
    base = evaluation.make_rung_fn(model, lad.rungs[0], model_id=spec.id,
                                   quant="u8")
    _, state = base(variables, img1, img2)
    warm = evaluation.make_warm_fn(model, lad.rungs[0], model_id=spec.id,
                                   quant="u8")
    entries.append((warm, (variables, img1, img2, state["flow"]),
                    dict(kwargs)))
    return entries


def build_aug_programs(shape=(48, 64), batch=2):
    """Register the on-device data-engine program variants and return
    ``[(program, args, audit_kwargs)]`` for auditing.

    The PR-19 contract the audit pins: the augmented train step is
    exactly one registered program keyed only by the added ``augment``
    flag (the plain audit train key and its pinned budget untouched —
    ``augment=None`` returns the identical Program), and the jitted
    synthetic scenario generator registers as its own ``synth_pair``
    program, so its render cost is budgeted like any other device
    program instead of hiding in the input pipeline.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import compile as programs, models, parallel
    from ..data import synth
    from ..data.device_augment import DeviceAugment

    flagship = {
        "name": "RAFT baseline", "id": "raft-baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    }
    spec = models.load(flagship)
    model, loss = spec.model, spec.loss
    h, w = shape
    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(batch, h, w, 3).astype(np.float32))
    flow = jnp.asarray(rng.randn(batch, h, w, 2).astype(np.float32))
    valid = jnp.asarray(np.ones((batch, h, w), bool))
    sample_ids = jnp.asarray(np.arange(batch, dtype=np.uint32))

    model_args = {"iterations": 2}
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1],
                           **model_args)
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-4))
    state = parallel.TrainState.create(variables, tx)

    # same fixed configuration as cfg/env/device-aug.yaml, so the pinned
    # program is the one a real --device-aug run compiles
    augment = DeviceAugment()
    key = programs.ProgramKey(
        kind="train_step", model="raft-baseline",
        flags=programs.flag_items(shape=(batch, h, w), audit=1,
                                  mesh2d=False))
    prog = parallel.make_train_step(
        model, loss, tx, model_args=model_args, donate=False, key=key,
        augment=augment)

    entries = [(prog, (state, img1, img2, flow, valid, sample_ids,
                       jnp.int32(0)),
                {"n_devices": 1})]

    # the synthetic generator: exact flow supervision rendered on device
    synth_key = programs.ProgramKey(
        kind="synth_pair", model="synth",
        flags=programs.flag_items(shape=(h, w), audit=1))
    synth_prog = programs.register_step(
        "synth_pair",
        jax.jit(lambda k: synth.render_pair(k, (h, w))),
        key=synth_key)
    entries.append((synth_prog, (jax.random.PRNGKey(0),),
                    {"n_devices": 1}))
    return entries


def audit_registry(entries=None, **build_kwargs):
    """Audit every (program, args, kwargs) entry; defaults to the
    flagship tiny-shape build. Returns ``(reports, findings)``."""
    if entries is None:
        entries = build_flagship_programs(**build_kwargs)
    reports, findings = [], []
    for program, args, kwargs in entries:
        rep, fnd = audit_program(program, args, **kwargs)
        reports.append(rep)
        findings.extend(fnd)
    return reports, findings


def render_reports(reports):
    """Human-readable audit section (CLI + telemetry_report reuse)."""
    out = ["== hlo audit =="]
    for r in reports:
        coll = r.get("compiled_collectives", r.get("collectives", {}))
        coll_s = (", ".join(f"{k}={v}" for k, v in sorted(coll.items()))
                  or "none")
        out.append(
            f"{r['key']}: fingerprint {r['fingerprint'][:12]} "
            f"({'stable' if r['fingerprint_stable'] else 'UNSTABLE'}), "
            f"collectives: {coll_s}, f32 convs: {r['f32_convolutions']}, "
            f"large consts: {len(r['large_constants'])}")
    return "\n".join(out)
