"""Rules ``env-knob`` / ``env-docs``: the central knob registry contract.

``utils/env.py`` is the single source of truth for every ``RMD_*``
environment variable — its typed accessors are the only sanctioned read
path, and the README knob table is generated from its registry. Three
checks hold that together:

- **env-knob (module)**: a direct ``os.environ``/``os.getenv`` *read* of
  an ``RMD_*`` name anywhere outside ``utils/env.py`` (writes — fault
  injection, save/restore in tests and the dry run — stay legal);
- **env-knob (project)**: every ``RMD_*`` string literal in the lint
  surface must name a registered knob (catches typos like
  ``env.get("RMD_PREFTCH")``), and every registered knob must be
  referenced somewhere (catches knobs that died in a refactor but kept
  their registry row and README line);
- **env-docs (project)**: the committed README table between the
  generation markers must match ``env.readme_table()`` byte for byte
  (``scripts/graftlint.py --fix-knob-table`` rewrites it);
- **env-dead-knob (project)**: every registered knob must be *read*
  through a typed accessor (``get``/``get_bool``/``get_int``/
  ``get_float``/``get_str``/``raw``/``is_set``/``knob``) somewhere in
  the lint surface. Stricter than the reference check above: a knob
  that tests still save/restore (a write) or a docstring still names
  stays "referenced" long after the code path that *consumed* it died
  in a refactor — registry row and README line intact, knob silently a
  no-op for every user who sets it.
"""

import ast
import re

from . import astutil
from .lint import Finding, Rule

RULE = "env-knob"
DOCS_RULE = "env-docs"
DEAD_RULE = "env-dead-knob"

# the sanctioned read surface of utils.env: a registered knob is *live*
# iff some call through one of these names passes its literal
ACCESSORS = frozenset({"get", "get_bool", "get_int", "get_float",
                       "get_str", "raw", "is_set", "knob"})

ENV_MODULE = "raft_meets_dicl_tpu/utils/env.py"
KNOB_RE = re.compile(r"^RMD_[A-Z0-9_]+$")


def _knob_literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and KNOB_RE.match(node.value):
        return node.value
    return None


def _environ_read_calls(tree):
    """(node, knob_name) for os.environ.get / os.getenv / environ
    subscript *reads* of RMD_* literals."""
    # subscript targets of plain assignments / deletes are writes
    write_subscripts = set()
    for node in ast.walk(tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript):
                write_subscripts.add(id(t))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            dotted = astutil.dotted_name(node.func) or ""
            if dotted.endswith("environ.get") or \
                    dotted.endswith("getenv") or \
                    dotted.endswith("environ.setdefault"):
                for arg in node.args[:1]:
                    name = _knob_literal(arg)
                    if name:
                        yield node, name
        elif isinstance(node, ast.Subscript) and \
                id(node) not in write_subscripts:
            dotted = astutil.dotted_name(node.value) or ""
            if dotted.endswith("environ"):
                name = _knob_literal(node.slice)
                if name:
                    yield node, name
        elif isinstance(node, ast.Compare) and node.ops and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)):
            dotted = astutil.dotted_name(node.comparators[0]) or ""
            if dotted.endswith("environ"):
                name = _knob_literal(node.left)
                if name:
                    yield node, name


def check(module):
    if module.rel == ENV_MODULE:
        return []
    findings = []
    for node, name in _environ_read_calls(module.tree):
        findings.append(Finding(
            rule=RULE, path=module.rel, line=node.lineno,
            message=f"direct environment read of {name}; go through "
                    f"utils.env (get/get_bool/get_int/get_float/raw) "
                    f"so the knob stays registered and documented"))
    return findings


def _knobs():
    from ..utils import env
    return env


def _covers_env_module(ctx):
    """Registry-completeness and docs checks only make sense when the
    linted tree actually contains the knob registry — a fixture tree or
    a partial ``--root`` doesn't reference every knob and has no README
    table to keep honest."""
    return any(m.rel == ENV_MODULE for m in ctx.modules)


def check_project(ctx):
    if not _covers_env_module(ctx):
        return []
    env = _knobs()
    findings = []
    referenced = set()
    for m in ctx.modules:
        if m.rel == ENV_MODULE:
            continue
        for node in ast.walk(m.tree):
            name = _knob_literal(node)
            if not name:
                continue
            referenced.add(name)
            if name not in env.KNOBS:
                findings.append(Finding(
                    rule=RULE, path=m.rel, line=node.lineno,
                    message=f"unregistered knob {name}: add it to "
                            f"utils.env.KNOBS (or fix the typo)"))
    for name in sorted(set(env.KNOBS) - referenced):
        findings.append(Finding(
            rule=RULE, path=ENV_MODULE, line=1,
            message=f"stale knob {name}: registered in utils.env.KNOBS "
                    f"but referenced nowhere in the lint surface"))
    return findings


def check_dead_knobs(ctx):
    """Registered knobs no typed accessor ever reads — dead controls.

    Direct ``environ`` reads also count as live (they draw their own
    ``env-knob`` finding; double-reporting the knob as dead on top would
    punish the same line twice). The accessor match is by call-name
    suffix, deliberately loose: ``rmd_env.get_bool(...)``, ``env.raw``,
    a bare ``get_int`` after ``from ..utils.env import get_int`` all
    count. Over-matching (some unrelated ``.get("RMD_X")``) only makes
    a knob *live*, never falsely dead — the safe direction for a gate.
    """
    if not _covers_env_module(ctx):
        return []
    env = _knobs()
    read = set()
    for m in ctx.modules:
        if m.rel == ENV_MODULE:
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and node.args:
                dotted = astutil.dotted_name(node.func) or ""
                if dotted.rpartition(".")[2] in ACCESSORS:
                    name = _knob_literal(node.args[0])
                    if name:
                        read.add(name)
        for _node, name in _environ_read_calls(m.tree):
            read.add(name)
    return [
        Finding(
            rule=DEAD_RULE, path=ENV_MODULE, line=1,
            message=f"dead knob {name}: registered in utils.env.KNOBS "
                    f"but never read through a typed accessor — the "
                    f"code path that consumed it is gone; drop the "
                    f"registry row (and regenerate the README table) "
                    f"or re-wire the read")
        for name in sorted(set(env.KNOBS) - read)
    ]


def check_docs(ctx):
    if not _covers_env_module(ctx):
        return []
    env = _knobs()
    readme = ctx.root / "README.md"
    if not readme.exists():
        return [Finding(rule=DOCS_RULE, path="README.md", line=1,
                        message="README.md missing")]
    text = readme.read_text()
    begin, end = text.find(env.TABLE_BEGIN), text.find(env.TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        return [Finding(
            rule=DOCS_RULE, path="README.md", line=1,
            message=f"README knob-table markers missing; add "
                    f"'{env.TABLE_BEGIN}' / '{env.TABLE_END}' and run "
                    f"scripts/graftlint.py --fix-knob-table")]
    committed = text[begin + len(env.TABLE_BEGIN):end].strip("\n")
    if committed != env.readme_table():
        line = text[:begin].count("\n") + 1
        return [Finding(
            rule=DOCS_RULE, path="README.md", line=line,
            message="README knob table is stale vs utils.env.KNOBS; "
                    "run scripts/graftlint.py --fix-knob-table")]
    return []


RULES = [
    Rule(name=RULE,
         doc="RMD_* env reads must route through utils.env; literals "
             "must name registered knobs; registered knobs must be "
             "referenced",
         check=check, project=check_project),
    Rule(name=DOCS_RULE,
         doc="README env-knob table generated from utils.env.KNOBS "
             "must not drift",
         project=check_docs),
    Rule(name=DEAD_RULE,
         doc="registered knobs must be read through a typed utils.env "
             "accessor somewhere (a knob nothing reads is a silent "
             "no-op for everyone who sets it)",
         project=check_dead_knobs),
]
