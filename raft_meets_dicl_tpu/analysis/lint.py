"""graftlint: the AST lint framework (rules live in sibling modules).

The framework owns everything rule-independent: walking the repo's
Python surface, parsing modules once, line-level suppressions, the
grandfathered-findings baseline, and the run report. Each rule module
exports a ``RULES`` list of :class:`Rule` objects whose ``check``
(per-module) and ``check_project`` (whole-surface, e.g. knob-registry
completeness) hooks yield :class:`Finding`s.

Suppression syntax, on the offending line::

    x = float(loss)  # graftlint: disable=host-sync -- eval summary, post-step

The ``-- reason`` is mandatory: a suppression without one is itself a
finding (``bad-suppression``), as is one naming an unknown rule. For
legacy cold-path clusters the committed ``graftlint-baseline.json``
carries glob-scoped entries with justifications instead of littering
dozens of files with pragmas; ``scripts/graftlint.py`` is the CLI.
"""

import ast
import fnmatch
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,-]+)"
    r"(?:\s*--\s*(?P<reason>\S.*))?")

# repo surface the lint pass covers by default, relative to the root;
# tests are exempt (they exercise violations on purpose)
DEFAULT_TARGETS = ("raft_meets_dicl_tpu", "scripts", "bench.py", "main.py",
                   "__graft_entry__.py")
EXCLUDE_PARTS = {"__pycache__", ".git", "runs", ".jax_cache"}

BASELINE_NAME = "graftlint-baseline.json"


@dataclass
class Finding:
    """One rule hit at a source location."""
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    severity: str = "error"  # error | warn
    status: str = "open"     # open | suppressed | baselined
    justification: str = ""

    @property
    def location(self):
        return f"{self.path}:{self.line}"

    def to_dict(self):
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "severity": self.severity, "status": self.status,
             "message": self.message}
        if self.justification:
            d["justification"] = self.justification
        return d


@dataclass
class Rule:
    """A named rule: ``check(module)`` runs per module, ``project(ctx)``
    once over the whole surface. Either may be None."""
    name: str
    doc: str
    check: object = None
    project: object = None


class Module:
    """One parsed source module plus its suppression table."""

    def __init__(self, path, rel, source):
        self.path = Path(path)
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.lines = source.splitlines()
        # lineno -> (frozenset(rule names) or None for all, reason)
        self.suppressions = {}
        self.bad_suppressions = []  # Findings, attached by the runner
        for i, text in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = frozenset(r.strip() for r in m.group(1).split(",")
                              if r.strip())
            reason = (m.group("reason") or "").strip()
            self.suppressions[i] = (rules, reason)

    def suppressed(self, rule, line):
        entry = self.suppressions.get(line)
        if entry is None:
            return None
        rules, reason = entry
        if rule in rules or "all" in rules:
            return reason or ""
        return None


class Baseline:
    """Grandfathered findings: ``{rule, glob, justification}`` entries
    matched against a finding's rule + repo-relative path."""

    def __init__(self, entries, path=None):
        self.path = path
        self.entries = list(entries)
        self._hits = [0] * len(self.entries)
        for i, e in enumerate(self.entries):
            for k in ("rule", "glob", "justification"):
                if not str(e.get(k, "")).strip():
                    raise ValueError(
                        f"baseline entry {i} missing '{k}' "
                        f"(justification is mandatory): {e!r}")

    @classmethod
    def load(cls, path):
        data = json.loads(Path(path).read_text())
        if data.get("version") != 1:
            raise ValueError(
                f"unsupported baseline version {data.get('version')!r}")
        return cls(data.get("entries", ()), path=str(path))

    @classmethod
    def empty(cls):
        return cls(())

    def match(self, finding):
        """Justification for a baselined finding, or None."""
        for i, e in enumerate(self.entries):
            if e["rule"] != finding.rule:
                continue
            if fnmatch.fnmatch(finding.path, e["glob"]):
                self._hits[i] += 1
                return e["justification"]
        return None

    def unused_entries(self):
        """Entries that matched nothing this run — stale once the code
        they grandfathered is fixed; the CLI reports them so the file
        shrinks instead of rotting."""
        return [e for e, n in zip(self.entries, self._hits) if n == 0]


@dataclass
class Report:
    """One lint run: every finding (with status resolved), per-status
    partitions, and the inputs that shaped the run."""
    findings: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)
    n_modules: int = 0

    @property
    def open(self):
        return [f for f in self.findings if f.status == "open"]

    @property
    def suppressed(self):
        return [f for f in self.findings if f.status == "suppressed"]

    @property
    def baselined(self):
        return [f for f in self.findings if f.status == "baselined"]

    @property
    def ok(self):
        return not self.open

    def to_dict(self):
        return {
            "ok": self.ok,
            "modules": self.n_modules,
            "open": len(self.open),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline_entries": self.stale_baseline,
            "findings": [f.to_dict() for f in self.findings],
        }


class ProjectContext:
    """What project-level rule hooks see: every parsed module plus the
    repo root (for non-Python artifacts like README.md)."""

    def __init__(self, root, modules):
        self.root = Path(root)
        self.modules = modules


def default_rules():
    from . import envknobs, hostsync, precision, telemetrykinds, tracerflow

    rules = []
    for mod in (hostsync, tracerflow, precision, envknobs, telemetrykinds):
        rules.extend(mod.RULES)
    return rules


def rule_names(rules):
    return {r.name for r in rules} | {"all", "bad-suppression",
                                      "parse-error"}


def iter_sources(root, targets=DEFAULT_TARGETS):
    """Yield (abs_path, rel_posix) for the lintable Python surface."""
    root = Path(root)
    for target in targets:
        p = root / target
        if p.is_file():
            yield p, Path(target).as_posix()
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if EXCLUDE_PARTS.intersection(f.parts):
                    continue
                yield f, f.relative_to(root).as_posix()


def load_modules(root, targets=DEFAULT_TARGETS):
    """Parse the lint surface; a syntax error becomes a finding, not a
    crash (the linter must never take the build down harder than the
    interpreter would)."""
    modules, findings = [], []
    for path, rel in iter_sources(root, targets):
        try:
            source = path.read_text()
            modules.append(Module(path, rel, source))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="parse-error", path=rel,
                line=getattr(e, "lineno", 1) or 1,
                message=f"cannot parse: {e}"))
    return modules, findings


def run(root, baseline=None, rules=None, targets=DEFAULT_TARGETS):
    """Run the lint pass over ``root``; returns a :class:`Report`."""
    rules = list(default_rules() if rules is None else rules)
    if baseline is None:
        bl_path = Path(root) / BASELINE_NAME
        baseline = (Baseline.load(bl_path) if bl_path.exists()
                    else Baseline.empty())
    known = rule_names(rules)

    modules, findings = load_modules(root, targets)
    for m in modules:
        for line, (names, reason) in sorted(m.suppressions.items()):
            unknown = names - known
            if unknown:
                findings.append(Finding(
                    rule="bad-suppression", path=m.rel, line=line,
                    message=f"suppression names unknown rule(s) "
                            f"{sorted(unknown)}"))
            if not reason:
                findings.append(Finding(
                    rule="bad-suppression", path=m.rel, line=line,
                    message="suppression without a reason (write "
                            "'graftlint: disable=<rule> -- <why>')"))
        for rule in rules:
            if rule.check is None:
                continue
            findings.extend(rule.check(m))

    ctx = ProjectContext(root, modules)
    for rule in rules:
        if rule.project is not None:
            findings.extend(rule.project(ctx))

    by_module = {m.rel: m for m in modules}
    for f in findings:
        m = by_module.get(f.path)
        if m is not None and f.rule != "bad-suppression":
            reason = m.suppressed(f.rule, f.line)
            if reason is not None:
                f.status = "suppressed"
                f.justification = reason
                continue
        just = baseline.match(f)
        if just is not None:
            f.status = "baselined"
            f.justification = just

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=findings,
                  stale_baseline=baseline.unused_entries(),
                  n_modules=len(modules))


def emit_events(report, tele):
    """Forward a report's findings as ``lint`` telemetry events."""
    for f in report.findings:
        tele.emit("lint", rule=f.rule, path=f.path, line=f.line,
                  status=f.status, severity=f.severity,
                  message=f.message)


def render_text(report):
    """Human-readable report text (the CLI's default output)."""
    out = []
    for f in report.open:
        out.append(f"{f.location}: {f.rule}: {f.message}")
    out.append(f"graftlint: {report.n_modules} modules, "
               f"{len(report.open)} open, "
               f"{len(report.suppressed)} suppressed, "
               f"{len(report.baselined)} baselined")
    for e in report.stale_baseline:
        out.append(f"stale baseline entry (matched nothing): "
                   f"{e['rule']} @ {e['glob']}")
    return "\n".join(out)
