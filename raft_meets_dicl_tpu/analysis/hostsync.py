"""Rule ``host-sync``: host-synchronizing calls on device values.

Every one of these forces the host to wait for the device pipeline:

- ``x.item()`` / ``x.block_until_ready()`` / ``jax.block_until_ready``
- ``float(x)`` / ``int(x)`` on something that plausibly holds a device
  array (see the argument heuristic below)
- ``np.asarray(x)`` / ``np.array(x)`` on the same
- ``jax.device_get(x)``

Inside code reachable from a jit root the call is *always* a bug — it
either fails under tracing or silently splits the program — so those are
``error`` severity. Elsewhere the call may be a legitimate cold-path
fetch (eval summaries, visualization, checkpoint metadata), but the cost
model still wants them visible: ``warn`` severity, expected to carry a
suppression or a baseline justification. PERF.md round 5 measured the
damage: one per-step ``float(loss)`` serialized the async dispatch
pipeline and cost 5.8 -> 1.2 s/step when removed.

The ``float()``/``int()``/``asarray()`` argument heuristic keeps config
parsing out of the findings: only bare names, subscripts (``aux["loss"]``)
and calls rooted at jnp/jax-ish modules count; literals
(``float("nan")``) and attribute chains (``float(args.lr)``) do not.
Modules that never import jax are skipped entirely — pure-host code
(data decoding, env parsing, visualization on numpy arrays) cannot
device-sync no matter how many ``float()`` casts it performs.
"""

import ast

from . import astutil
from .lint import Finding, Rule

RULE = "host-sync"

# attribute-call syncs, flagged on any receiver
SYNC_ATTRS = {"item", "block_until_ready"}
# module-function syncs: tail of the dotted callee name
SYNC_TAILS = {"device_get", "block_until_ready"}
# numpy materializers whose argument heuristic applies
NP_MATERIALIZERS = {"asarray", "array"}
DEVICE_MODULES = {"jnp", "jax", "lax", "F", "functional", "np_or_jnp"}


def _devicey(arg):
    """Whether a call argument plausibly holds a device array."""
    if isinstance(arg, (ast.Name, ast.Subscript)):
        return True
    if isinstance(arg, ast.Call):
        dotted = astutil.dotted_name(arg.func)
        if dotted:
            return dotted.split(".")[0] in DEVICE_MODULES
        return False
    return False


def _classify(node):
    """(kind, detail) when ``node`` is a host-sync call, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        dotted = astutil.dotted_name(fn) or ""
        root = dotted.split(".")[0]
        if fn.attr in SYNC_ATTRS:
            return ("attr", f".{fn.attr}()")
        if root in ("jax",) and fn.attr in SYNC_TAILS:
            return ("jax", f"jax.{fn.attr}()")
        if root in ("np", "numpy", "onp") and \
                fn.attr in NP_MATERIALIZERS and node.args and \
                _devicey(node.args[0]):
            return ("np", f"{root}.{fn.attr}()")
        return None
    if isinstance(fn, ast.Name):
        if fn.id in ("float", "int") and len(node.args) == 1 and \
                _devicey(node.args[0]):
            return ("cast", f"{fn.id}()")
        if fn.id in SYNC_TAILS:
            return ("jax", f"{fn.id}()")
    return None


def _owner_function(node, table):
    """Qualname of the innermost function containing ``node`` (by line
    span), or None at module level."""
    line = getattr(node, "lineno", None)
    if line is None:
        return None
    best, best_span = None, None
    for qual, info in table.items():
        n = info.node
        if n.lineno <= line <= (n.end_lineno or n.lineno):
            span = (n.end_lineno or n.lineno) - n.lineno
            if best_span is None or span < best_span:
                best, best_span = qual, span
    return best


def _imports_jax(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax" or mod.startswith("jax."):
                return True
    return False


def check(module):
    if not _imports_jax(module.tree):
        return []
    table = astutil.function_table(module.tree)
    hot = astutil.jit_reachable(module.tree, table)

    findings, seen = [], set()
    for node in ast.walk(module.tree):
        hit = _classify(node)
        if not hit:
            continue
        key = (node.lineno, node.col_offset)
        if key in seen:
            continue
        seen.add(key)
        owner = _owner_function(node, table)
        detail = hit[1]
        if owner in hot:
            findings.append(Finding(
                rule=RULE, path=module.rel, line=node.lineno,
                severity="error",
                message=f"{detail} inside jit-reachable '{owner}': host "
                        f"sync under tracing (fails or splits the "
                        f"program)"))
        else:
            findings.append(Finding(
                rule=RULE, path=module.rel, line=node.lineno,
                severity="warn",
                message=f"{detail} forces a device sync; move it off "
                        f"the hot path, batch the fetch, or justify it"))
    return findings


RULES = [Rule(
    name=RULE,
    doc="host-synchronizing calls (.item, float(), np.asarray, "
        "device_get, block_until_ready); error when jit-reachable",
    check=check,
)]
