"""Run-wide structured telemetry: spans, step phases, JSONL event sink.

Round 5 cut the real training loop from 5.8 to 1.2 s/step only after
hand-timing exposed three invisible host-side stalls (PERF.md); this
module makes those visible on *every* run. Each run directory gets an
``events.jsonl`` whose records follow a versioned schema (``SCHEMA``),
covering per-step phase timings, throughput, compiles and persistent
compile-cache hits/misses, memory watermarks, non-finite-guard flushes,
and stage/epoch/checkpoint boundaries.

Design constraints, in order:

1. **The hot path must stay hot.** Spans are two ``perf_counter`` calls
   and a dict update; events buffer in memory and flush at boundaries
   (epoch/stage/run) or every ``_FLUSH_EVERY`` records; device step time
   is sampled by piggybacking on the amortized finiteness fetch instead
   of a per-step ``block_until_ready`` (which would serialize the async
   pipeline — the exact regression round 5 removed).
2. **Off means off.** ``RMD_TELEMETRY=0`` routes every call site through
   :class:`NullTelemetry` no-ops; no file is opened, no listener fires.
3. **One sink per process.** ``activate()`` installs the process-wide
   sink returned by ``get()``; the jax.monitoring listeners (compile
   durations, compile-cache hits/misses) are registered once and forward
   to whatever sink is active.
"""

import collections
import contextlib
import json
import os
import threading
import time

from . import blackbox as _blackbox
from . import goodput as _goodput

SCHEMA_VERSION = 1

# Minor revision within the major schema: bumped when kinds or optional
# fields are *added*. Producers stamp the plain major in ``v`` (older
# readers keep working); a reader seeing ``v`` with the same major but a
# larger fractional minor (e.g. 1.2 from a newer producer) should skip
# the record, not reject the file — see :class:`NewerSchema`.
SCHEMA_MINOR = 5

# kind -> required payload fields (beyond the {v, t, kind} envelope).
# Extra fields are allowed everywhere: the schema pins the floor a
# consumer can rely on, not the ceiling.
SCHEMA = {
    "run_start": {"dir"},
    "run_end": set(),
    "stage_start": {"stage", "step"},
    "stage_end": {"stage", "step"},
    "epoch_start": {"stage", "epoch", "step"},
    "epoch_end": {"stage", "epoch", "step"},
    "step": {"step", "phases", "step_time", "throughput_ema"},
    "device_sync": {"step", "seconds"},
    "compile": {"label", "seconds"},
    "cache": {"event"},
    "memory": {"host_rss_gib", "live_arrays"},
    # non-finite guard: "action" says what the policy did (raise | skip |
    # rollback); skip/rollback events carry trip counts and the recent
    # sample-id window so a trip is reproducible offline
    "nonfinite": {"step"},
    "checkpoint": {"path", "step", "seconds"},
    # one evaluation/validation sweep: samples/s, per-bucket batch and
    # compile counts, pad-waste ratio (see evaluation.EvalRunStats)
    "eval": {"name", "samples", "batches", "seconds"},
    # SPMD state placement (PR 6): the mesh shape plus per-chip vs.
    # replicated byte accounting for params and optimizer state
    # (parallel.partition.Partitioner.report) — emitted once per stage
    # when the training state is placed on the mesh
    "sharding": {"mesh", "params_bytes_per_chip", "opt_bytes_per_chip"},
    # compiled-program registry (PR 7): one event per AOT artifact
    # interaction — event is save | hit | miss | fallback (plus the
    # fleet store transfers publish | fetch), with program
    # kind/model/digest and bytes/seconds where applicable. A 'fallback'
    # means an artifact existed but could not be used (corruption,
    # version mismatch, incompatible inputs): the boot paid a cold JIT
    # it expected to skip, which the report flags as an anomaly.
    "aot": {"event"},
    # boot configuration: the effective persistent compile-cache and AOT
    # program directories (instead of silently defaulting), plus the
    # prefetch knob — emitted once per CLI run
    "boot": {"compile_cache"},
    # fault-tolerance trail (PR 5): graceful-stop request (SIGTERM/SIGINT),
    # --resume auto pickup, corrupt-checkpoint quarantine, decode-worker
    # respawn, per-sample decode failure absorbed by the loader
    # graftlint static-analysis/HLO-audit findings (PR 8): one event per
    # finding when the lint pass runs with a telemetry sink attached;
    # status is open | baselined | suppressed, severity error | warn
    "lint": {"rule", "path", "line", "status"},
    # graftcost static cost model (PR 12): one event per audited
    # program — deterministic StableHLO-walker FLOP/byte totals,
    # arithmetic intensity, compiled collective-schedule bytes, and the
    # tile-utilization verdict / hazard counts the budget gate pins
    "cost": {"program", "flops", "bytes"},
    # serving path (serve/): event is request (success, with
    # admission/queue/dispatch/device latency spans) | error (typed
    # per-request failure, kind = malformed | oversized | decode |
    # internal) | reject (admission shed, reason = queue_full |
    # shutdown) | batch (one dispatch: bucket, size, fill, compiles) |
    # warmup (one warm-pool triple: compiles, AOT hits/saves)
    "serve": {"event"},
    "preempt": {"signal", "step"},
    "resume": {"path", "step"},
    "quarantine": {"path"},
    "respawn": {"worker"},
    "bad_sample": {"index"},
    # live observability plane (PR 13): event is request (one completed
    # request with its trace id, batch linkage and exact critical-path
    # phase decomposition — phases sum to total) | batch (one dispatch
    # span: batch id, bucket/class, member trace ids, compiled-program
    # fingerprint)
    "trace": {"event"},
    # rolling per-latency-class SLO window: attainment = good/(good+bad)
    # within window_s, burn_rate = (1-attainment)/(1-objective) — burn
    # > 1 means the class is missing its objective at the current rate
    "slo": {"klass", "target_ms", "attainment", "burn_rate"},
    # trainer step-trace window (steptrace.StepTraceSummary.event):
    # per-phase rolling p50/p99 + straggler/data-starved flags, emitted
    # at the amortized finite-check cadence; also reused by evaluation
    # as a per-bucket progress heartbeat (scope="eval")
    "steptrace": {"step", "phases"},
    # wall-clock goodput breakdown (goodput.GoodputLedger.snapshot):
    # classes sum to total; emitted at stage boundaries and run end
    "goodput": {"total", "classes"},
    # flight-recorder bundle written next to the emergency checkpoint
    # on crash / nonfinite escalation / SIGTERM (blackbox.dump)
    "postmortem": {"reason", "path"},
    # streaming-video engine (PR 15): event is frame (one sequence-runner
    # frame: warm/cold start, iterations spent, EPE when ground truth is
    # known) | sequence (one finished sequence: frames, mean iterations,
    # warm-hit ratio) | products (one fw/bw pass: occlusion ratio, mean
    # confidence)
    "video": {"event"},
    # serve video-session cache (video.cache.SessionCache): event is
    # hit (warm-start state served) | miss (cold start: absent, expired,
    # or shape mismatch) | evict (capacity LRU or TTL expiry) | import
    # (a handed-off carry snapshot installed on the fleet handoff path)
    "session": {"event"},
    # serving fleet (fleet/, PR 20): event is route (one request
    # dispatched to a replica) | retry (safe-failure re-dispatch) |
    # shed (typed fleet rejection, reason = queue_full |
    # replica_unavailable) | drain (burn/liveness-triggered replica
    # drain) | handoff (one sticky session's carry moved or evicted,
    # outcome = moved | evicted) | replica_up | replica_down |
    # restart (supervisor respawn, with backoff_ms)
    "fleet": {"event"},
    # graftprof measured attribution (PR 16): one event per profiled
    # program — measured device seconds vs the roofline-predicted
    # seconds, per-op-class breakdown, the machine the calibration ran
    # on, and whether the measured/predicted ratio drifted outside its
    # pinned prof-budget.json band (the report flags drift=true rows as
    # anomalies)
    "profile": {"program", "seconds"},
}


class UnknownKind(ValueError):
    """An event kind this reader's SCHEMA doesn't know — typically a
    file written by a newer producer. Readers that want forward compat
    catch this and skip the record; everything else treats it as the
    plain ValueError it is."""


class NewerSchema(ValueError):
    """Same major schema version, newer minor revision — the record is
    from a newer producer and safe to skip, not a corrupt line."""

_FLUSH_EVERY = 128
_EMA_ALPHA = 0.1


def validate_event(ev):
    """Check one event against the schema; raises ValueError on mismatch.

    Returns the event for chaining. This is the contract the tests and
    ``telemetry_report`` hold every producer to.
    """
    if not isinstance(ev, dict):
        raise ValueError(f"event is not an object: {ev!r}")
    v = ev.get("v")
    if v != SCHEMA_VERSION:
        if (isinstance(v, float) and not isinstance(v, bool)
                and int(v) == SCHEMA_VERSION and v > SCHEMA_VERSION):
            raise NewerSchema(
                f"newer minor schema revision {v!r}: {ev!r}")
        raise ValueError(f"unknown schema version {v!r}: {ev!r}")
    if not isinstance(ev.get("t"), (int, float)):
        raise ValueError(f"missing/invalid timestamp: {ev!r}")
    kind = ev.get("kind")
    if kind not in SCHEMA:
        raise UnknownKind(f"unknown event kind {kind!r}: {ev!r}")
    missing = SCHEMA[kind] - ev.keys()
    if missing:
        raise ValueError(f"{kind} event missing {sorted(missing)}: {ev!r}")
    if kind == "step":
        phases = ev["phases"]
        if not isinstance(phases, dict) or not all(
                isinstance(v, (int, float)) for v in phases.values()):
            raise ValueError(f"step phases must map name -> seconds: {ev!r}")
        counters = ev.get("counters", {})
        if not isinstance(counters, dict) or not all(
                isinstance(v, (int, float)) for v in counters.values()):
            raise ValueError(f"step counters must map name -> number: {ev!r}")
    if kind == "cache" and ev["event"] not in ("hit", "miss"):
        raise ValueError(f"cache event must be hit|miss: {ev!r}")
    return ev


def enabled():
    """The documented kill switch: RMD_TELEMETRY=0 disables everything."""
    from ..utils import env

    return env.get_bool("RMD_TELEMETRY")


class NullTelemetry:
    """No-op sink — the RMD_TELEMETRY=0 path and the default before
    ``activate``. Call sites never branch; they just talk to this."""

    path = None
    last_step = None
    enabled = False

    def emit(self, kind, **fields):
        pass

    def span(self, name):
        return contextlib.nullcontext()

    def add_phase(self, name, seconds):
        pass

    def add_count(self, name, value):
        pass

    def step_event(self, step, **fields):
        pass

    def counts(self):
        return {}

    def dropped(self):
        return 0

    def flush(self):
        pass

    def close(self):
        pass


class Telemetry:
    """JSONL event sink with a span/phase API.

    ``path=None`` keeps events in memory only (``self.events``) — used by
    bench.py and tests; a path appends JSON lines to that file.

    ``nonblocking=True`` (the serve hot path) hands disk I/O to a daemon
    writer thread behind a bounded queue (``RMD_TELEMETRY_BUFFER``): a
    slow disk can never backpressure the scheduler. On overflow the
    event is dropped and counted (:meth:`dropped`, surfaced as the
    ``rmd_telemetry_dropped_total`` metric) — losing a trace record
    under pressure is the contract; losing a request is not.

    ``RMD_TELEMETRY_MAX_MB`` > 0 rotates ``events.jsonl`` once it would
    exceed that size: the current file moves to ``<path>.1`` (replacing
    any previous rotation) and writing restarts. Default off — training
    runs keep one unbroken file.
    """

    enabled = True

    def __init__(self, path=None, nonblocking=False):
        from ..utils import env

        self.path = os.fspath(path) if path is not None else None
        self.events = []          # in-memory tail (memory-only mode: all)
        self.last_step = None
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._buffer = []
        self._fd = None
        self._size = None
        self._max_bytes = int(env.get_float("RMD_TELEMETRY_MAX_MB") * 2 ** 20)
        self._phases = {}
        self._step_counters = {}
        self._counts = {}
        self._dropped = 0
        self._last_step_t = None
        self._ema = None
        self._nonblocking = bool(nonblocking) and self.path is not None
        if self._nonblocking:
            self._capacity = max(1, env.get_int("RMD_TELEMETRY_BUFFER"))
            self._queue = collections.deque()
            self._wake = threading.Event()
            self._stopping = False
            self._writer = threading.Thread(
                target=self._writer_loop, name="telemetry-writer",
                daemon=True)
            self._writer.start()

    # -- event plumbing ----------------------------------------------------

    def emit(self, kind, **fields):
        ev = {"v": SCHEMA_VERSION, "t": time.time(), "kind": kind, **fields}
        # taps run before the sink lock so a consumer may itself emit
        # (goodput events at stage boundaries, postmortem on dump)
        _goodput.observe(kind, fields)
        _blackbox.observe(kind, fields)
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if kind == "compile":
                # label-qualified count: lets consumers (eval compile
                # accounting) separate the instrumented program they care
                # about from incidental eager-op compiles
                k = f"compile:{fields.get('label')}"
                self._counts[k] = self._counts.get(k, 0) + 1
            if self.path is None:
                self.events.append(ev)
                return ev
            if self._nonblocking:
                if len(self._queue) >= self._capacity:
                    self._dropped += 1
                else:
                    self._queue.append(ev)
                    self._wake.set()
                return ev
            self._buffer.append(ev)
            if (len(self._buffer) >= _FLUSH_EVERY
                    or kind not in ("step", "device_sync", "compile", "cache",
                                    "steptrace")):
                self._flush_locked()
        return ev

    def _flush_locked(self):
        if not self._buffer:
            return
        batch, self._buffer = self._buffer, []
        self._write_batch(batch)

    def _write_batch(self, batch):
        with self._io_lock:
            if self._fd is None:
                self._fd = open(self.path, "a")
                self._size = os.path.getsize(self.path)
            data = "".join(json.dumps(ev) + "\n" for ev in batch)
            if (self._max_bytes > 0 and self._size > 0
                    and self._size + len(data) > self._max_bytes):
                self._fd.close()
                os.replace(self.path, self.path + ".1")
                self._fd = open(self.path, "a")
                self._size = 0
            self._fd.write(data)
            self._fd.flush()
            self._size += len(data)

    def _writer_loop(self):
        while True:
            self._wake.wait(0.2)
            self._wake.clear()
            self._drain()
            with self._lock:
                if self._stopping and not self._queue:
                    return

    def _drain(self):
        with self._lock:
            if not self._queue:
                return
            batch = list(self._queue)
            self._queue.clear()
        self._write_batch(batch)

    def flush(self):
        if self._nonblocking:
            self._drain()
            return
        with self._lock:
            if self.path is not None:
                self._flush_locked()

    def close(self):
        if self._nonblocking:
            with self._lock:
                self._stopping = True
            self._wake.set()
            self._writer.join(timeout=5.0)
            self._drain()
            with self._io_lock:
                if self._fd is not None:
                    self._fd.close()
                    self._fd = None
            return
        with self._lock:
            if self.path is not None:
                self._flush_locked()
        with self._io_lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None

    def counts(self):
        """Event counts by kind (cheap snapshot, used by bench summaries)."""
        with self._lock:
            return dict(self._counts)

    def dropped(self):
        """Events shed by the bounded non-blocking buffer (0 in the
        default blocking mode)."""
        with self._lock:
            return self._dropped

    # -- phases / steps ----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name):
        """Accumulate wall time under ``name`` for the current step."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_phase(name, time.perf_counter() - t0)

    def add_phase(self, name, seconds):
        """Externally-timed phase contribution (e.g. from the prefetch
        worker thread — attribution runs up to ``depth`` batches ahead,
        the aggregate breakdown is what matters)."""
        with self._lock:
            self._phases[name] = self._phases.get(name, 0.0) + seconds

    def add_count(self, name, value):
        """Per-step scalar counter (e.g. ``wire_bytes``, the host→device
        transfer volume): accumulates like a phase and drains into the
        next ``step`` event under ``counters``."""
        with self._lock:
            self._step_counters[name] = self._step_counters.get(name, 0) + value

    def step_event(self, step, **fields):
        """Close out one optimizer step: drain accumulated phases and
        counters, update the throughput EMA, emit the ``step`` record."""
        now = time.perf_counter()
        with self._lock:
            phases = self._phases
            self._phases = {}
            counters = self._step_counters
            self._step_counters = {}
        if self._last_step_t is None:
            step_time = sum(phases.values())
        else:
            step_time = now - self._last_step_t
        self._last_step_t = now

        inst = 1.0 / step_time if step_time > 0 else 0.0
        self._ema = (inst if self._ema is None
                     else _EMA_ALPHA * inst + (1 - _EMA_ALPHA) * self._ema)

        if counters:
            fields = dict(fields, counters=counters)
        ev = self.emit(
            "step", step=step,
            phases={k: round(v, 6) for k, v in phases.items()},
            step_time=round(step_time, 6),
            throughput_ema=round(self._ema, 4),
            **fields,
        )
        self.last_step = ev
        return ev


# -- process-wide active sink + jax.monitoring forwarding -------------------

_active = NullTelemetry()
_listeners_installed = False
_jit_label = threading.local()


def get():
    """The process's active sink (NullTelemetry unless activated)."""
    return _active


def activate(sink):
    """Install ``sink`` as the process-wide telemetry target and hook the
    jax.monitoring compile/cache events into it. Returns the sink."""
    global _active
    _active = sink
    if sink.enabled:
        _install_listeners()
    return sink


def deactivate():
    """Swap back to the null sink (closing the old one)."""
    global _active
    old, _active = _active, NullTelemetry()
    old.close()
    return old


def create(path=None, nonblocking=False):
    """Factory honoring the kill switch: a real sink, or the null one.

    ``nonblocking=True`` is the serve-path variant: disk writes move to
    a bounded background writer so ``emit`` never blocks the scheduler.
    """
    return Telemetry(path, nonblocking=nonblocking) if enabled() \
        else NullTelemetry()


@contextlib.contextmanager
def jit_label(label, program=None):
    """Scope the compile-attribution label (and, optionally, the owning
    registry Program whose per-program counters the monitoring listener
    increments) around a jitted call."""
    prev = getattr(_jit_label, "value", None)
    prev_prog = getattr(_jit_label, "program", None)
    _jit_label.value = label
    _jit_label.program = program
    try:
        yield
    finally:
        _jit_label.value = prev
        _jit_label.program = prev_prog


def instrument_jit(label, fn):
    """Label a jitted callable so compiles triggered inside it are
    attributed to ``label`` in compile events. Pure passthrough wrapper —
    donation/sharding semantics of ``fn`` are untouched."""

    def wrapped(*args, **kwargs):
        with jit_label(label):
            return fn(*args, **kwargs)

    wrapped.__wrapped__ = fn
    wrapped.telemetry_label = label
    if hasattr(fn, "lower"):
        # forward the AOT entry point so instrumented step builders stay
        # lowerable (tests lower every model id; compile events from an
        # explicit .lower().compile() are attributed to the bare 'jit')
        wrapped.lower = fn.lower
    return wrapped


def install_listeners():
    """Register the process-wide jax.monitoring forwarders (idempotent).

    jax emits '/jax/core/compile/backend_compile_duration' per backend
    compile and '/jax/compilation_cache/cache_{hits,misses}' per
    persistent-cache lookup; both forward to whatever sink is active at
    fire time, labeled by the innermost ``jit_label`` scope. Compile
    durations also increment the scoped registry Program's counters —
    those count even with the sink disabled, so eval/warmup compile
    accounting never falls back to guessing (the pre-PR-7 overcount).
    """
    global _listeners_installed
    if _listeners_installed:
        return
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax always present in practice
        return

    def on_event(event, **kwargs):
        if not _active.enabled:
            return
        if event == "/jax/compilation_cache/cache_hits":
            _active.emit("cache", event="hit",
                         label=getattr(_jit_label, "value", None))
        elif event == "/jax/compilation_cache/cache_misses":
            _active.emit("cache", event="miss",
                         label=getattr(_jit_label, "value", None))

    def on_duration(event, duration, **kwargs):
        if event != "/jax/core/compile/backend_compile_duration":
            return
        program = getattr(_jit_label, "program", None)
        if program is not None:
            program.record_compile(float(duration))
        if not _active.enabled:
            return
        _active.emit("compile",
                     label=getattr(_jit_label, "value", None) or "jit",
                     seconds=round(float(duration), 6))

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)
    _listeners_installed = True


# backwards-compatible internal name
_install_listeners = install_listeners


def memory_snapshot():
    """Host RSS + live jax arrays + device peak bytes (where exposed).

    The promoted form of the old ad-hoc ``RMD_DEBUG_MEM`` print — cheap
    enough to take at every epoch boundary.
    """
    rss = 0.0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) / 2 ** 20
                    break
    except OSError:  # pragma: no cover - non-procfs platforms
        pass

    snap = {"host_rss_gib": round(rss, 3), "live_arrays": 0}
    try:
        import jax

        snap["live_arrays"] = len(jax.live_arrays())
        stats = jax.local_devices()[0].memory_stats() or {}
        if "peak_bytes_in_use" in stats:
            snap["device_peak_gib"] = round(
                stats["peak_bytes_in_use"] / 2 ** 30, 3)
        if "bytes_in_use" in stats:
            snap["device_bytes_gib"] = round(
                stats["bytes_in_use"] / 2 ** 30, 3)
    except Exception:  # noqa: BLE001 - telemetry must never break the run
        pass
    return snap
