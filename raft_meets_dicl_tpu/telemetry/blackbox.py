"""Flight recorder: a bounded in-memory ring that turns a dying run
into a postmortem artifact.

While training runs, the recorder keeps the last N step-trace records
(``RMD_BLACKBOX_STEPS``) and a short ring of recent telemetry events —
append-only host work, no sync, no I/O.  When the run dies — crash,
non-finite escalation, or SIGTERM — :meth:`FlightRecorder.dump` writes
one JSON bundle next to the emergency checkpoint:

- the step-trace ring (the last N steps as the loop saw them),
- the recent-event ring,
- the run config (as recorded by ``cmd/train.py``),
- a snapshot of every registered ``RMD_*`` knob (value + whether set),
- the git revision,
- the last metrics scrape (the ``rmd_*`` registry rendered at dump
  time), and
- the reason + the checkpoint the bundle sits next to,

and emits a ``postmortem`` telemetry event pointing at it.  Dumping is
once-per-process (first reason wins): the nonfinite raise path and the
crash handler in ``cmd/train.py`` may both fire for one death.

Like the sink and the goodput ledger, a process-wide active recorder
(:func:`activate` / :func:`get` / the no-op :class:`NullRecorder`)
keeps the training loop free of conditionals.
"""

import json
import time
from collections import deque
from pathlib import Path

from ..utils import env, vcs

DEFAULT_STEPS = 64
EVENT_RING = 128


class NullRecorder:
    """Inactive recorder: every operation is a no-op."""

    enabled = False

    def record_step(self, record):
        pass

    def observe(self, kind, fields):
        pass

    def dump(self, directory, reason, **extra):
        return None


def knob_snapshot():
    """Current value of every registered RMD_* knob (and whether the
    environment actually sets it)."""
    out = {}
    for name in sorted(env.KNOBS):
        out[name] = {"value": env.get(name), "set": env.is_set(name)}
    return out


class FlightRecorder:
    """Bounded ring of recent step traces + telemetry events."""

    enabled = True

    def __init__(self, capacity=DEFAULT_STEPS, event_capacity=EVENT_RING,
                 config=None, registry=None):
        self.capacity = int(capacity)
        self._steps = deque(maxlen=self.capacity)
        self._events = deque(maxlen=int(event_capacity))
        self.config = config
        self.registry = registry
        self.dumped = None  # path of the bundle once written

    # -- recording (hot path: append-only, no sync, no I/O) ------------------

    def record_step(self, record):
        self._steps.append(record)

    def observe(self, kind, fields):
        """Event tap called by ``Telemetry.emit``; keeps the low-rate
        run events (everything but the per-step firehose)."""
        if kind in ("step", "steptrace", "device_sync"):
            return
        self._events.append({"kind": kind, **fields})

    # -- postmortem ----------------------------------------------------------

    def bundle(self, reason, **extra):
        scrape = None
        if self.registry is not None:
            try:
                scrape = self.registry.render()
            except Exception:  # noqa: BLE001 - postmortem must not raise
                scrape = None
        out = {
            "reason": reason,
            "time": time.time(),
            "git": vcs.get_git_head_hash(),
            "steps": list(self._steps),
            "events": list(self._events),
            "config": self.config,
            "knobs": knob_snapshot(),
            "metrics": scrape,
        }
        out.update(extra)
        return out

    def dump(self, directory, reason, tele=None, **extra):
        """Write the postmortem bundle into ``directory``; returns its
        path (or the already-written path — first reason wins)."""
        if self.dumped is not None:
            return self.dumped
        directory = Path(directory)
        try:
            directory.mkdir(parents=True, exist_ok=True)
            path = directory / f"postmortem-{reason.replace(':', '-')}.json"
            with open(path, "w") as f:
                json.dump(self.bundle(reason, **extra), f, indent=2,
                          default=str)
        except Exception:  # noqa: BLE001 - postmortem must not mask the death
            return None
        self.dumped = path
        if tele is not None:
            tele.emit("postmortem", reason=reason, path=str(path),
                      steps=len(self._steps), events=len(self._events),
                      checkpoint=extra.get("checkpoint"))
        return path


_active = NullRecorder()


def activate(recorder=None, **kwargs):
    """Install ``recorder`` (or a fresh one built from ``kwargs``) as
    the process-wide active recorder; returns it."""
    global _active
    _active = recorder if recorder is not None else FlightRecorder(**kwargs)
    return _active


def deactivate():
    global _active
    _active = NullRecorder()


def get():
    return _active


def observe(kind, fields):
    """Event tap called by ``Telemetry.emit``."""
    _active.observe(kind, fields)
