"""Shared observability sidecar: the HTTP plane behind both commands.

PR 13 gave ``serve`` a stdlib HTTP server (daemon thread, no
dependency) exposing /metrics, /healthz, /statusz and /profilez; the
trainer needs the identical surface, so the server lives here and both
``serve/observe.py`` and ``cmd/train.py`` bind their own observer to
it.  An *observer* is any object with four methods::

    metrics_text() -> str                  # Prometheus text exposition
    health()       -> (payload, code)      # JSON body + HTTP status
    status()       -> payload              # JSON snapshot
    profile(seconds) -> payload            # jax profiler capture

``ROUTES`` below is the authoritative route table — graftlint's
``sidecar-route`` rule checks every entry appears in the README
observability section, so the docs can't silently drift from the
server.

The server binds ``127.0.0.1`` (an observability sidecar, not a public
API) and ``port=0`` picks an ephemeral port (tests).
"""

import glob
import json
import os
import shutil
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import env
from . import metrics as metrics_mod

# every route the sidecar serves; graftlint:sidecar-route checks these
# against the README observability table
ROUTES = ("/metrics", "/healthz", "/statusz", "/profilez")

# liveness: both loops (serve dispatch, train step) go around at least
# every second in the healthy case; 10x margin tolerates a loaded host
STALE_HEARTBEAT_S = 10.0
MAX_PROFILE_S = 60.0
DEFAULT_PROFILE_S = 3.0


class ProfileBusy(RuntimeError):
    pass


def evict_captures(keep=None, tmp_root=None):
    """Bounded /profilez retention: drop the oldest ``rmd-profilez-*``
    capture dirs beyond the last ``keep`` (``RMD_PROFILE_KEEP``). Every
    capture used to leak its mkdtemp forever; now each capture evicts.
    Returns the evicted paths."""
    if keep is None:
        keep = env.get_int("RMD_PROFILE_KEEP")
    keep = max(1, int(keep))  # graftlint: disable=host-sync -- plain python int from an env knob, not a device value
    root = tmp_root or tempfile.gettempdir()
    dirs = [d for d in glob.glob(os.path.join(root, "rmd-profilez-*"))
            if os.path.isdir(d)]
    dirs.sort(key=os.path.getmtime, reverse=True)
    evicted = []
    for d in dirs[keep:]:
        shutil.rmtree(d, ignore_errors=True)
        evicted.append(d)
    return evicted


def capture_profile(lock, seconds, max_seconds=MAX_PROFILE_S,
                    registry=None):
    """Capture ``seconds`` of jax profiler trace into a fresh directory.

    Single-flight on ``lock``: a second request while one runs raises
    :class:`ProfileBusy` (the handler maps it to a 409), so a scrape
    loop can't stack captures. Retention is bounded
    (:func:`evict_captures`), and unless ``RMD_PROFILE_ATTRIBUTION``
    is off the response carries an inline graftprof attribution summary
    next to the artifact path (never failing the capture; a ``registry``
    additionally gets the ``rmd_prof_*`` gauges).
    """
    seconds = min(max(float(str(seconds)), 0.1), float(max_seconds))  # graftlint: disable=host-sync -- query-string scalar, not a device value
    if not lock.acquire(blocking=False):
        raise ProfileBusy("a profile capture is already running")
    try:
        import jax

        out = tempfile.mkdtemp(prefix="rmd-profilez-")
        jax.profiler.start_trace(out)
        time.sleep(seconds)
        jax.profiler.stop_trace()
        payload = {"dir": out, "seconds": seconds}
        evict_captures()
        if env.get_bool("RMD_PROFILE_ATTRIBUTION"):
            try:
                from ..analysis import profile as prof

                summary = prof.attribute_trace(out)
                payload["attribution"] = summary
                if registry is not None:
                    prof.publish_attribution_metrics(summary, registry)
            except Exception as e:  # noqa: BLE001 - attribution is advisory; the artifact is the product
                payload["attribution_error"] = \
                    f"{type(e).__name__}: {e}"
        return payload
    finally:
        lock.release()


class Handler(BaseHTTPRequestHandler):
    observer = None  # bound by SidecarServer via subclass attribute

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _send(self, code, body, content_type="application/json"):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code, payload):
        self._send(code, json.dumps(payload, indent=2) + "\n")

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        obs = self.observer
        try:
            if url.path == "/metrics":
                self._send(200, obs.metrics_text(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/healthz":
                payload, code = obs.health()
                self._send_json(code, payload)
            elif url.path == "/statusz":
                self._send_json(200, obs.status())
            elif url.path == "/profilez":
                qs = parse_qs(url.query)
                seconds = qs.get("seconds", [DEFAULT_PROFILE_S])[0]
                self._send_json(200, obs.profile(seconds))
            else:
                self._send_json(404, {"error": f"no route {url.path}"})
        except ProfileBusy as e:
            self._send_json(409, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - a scrape must not kill the host process
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})


class SidecarServer:
    """The bound HTTP server + its daemon thread."""

    def __init__(self, observer, port, host="127.0.0.1",
                 thread_name="obs-sidecar", handler_cls=None):
        handler = type("BoundHandler", (handler_cls or Handler,),
                       {"observer": observer})
        self.observer = observer
        self.httpd = ThreadingHTTPServer((host, int(port)), handler)  # graftlint: disable=host-sync -- TCP port number, not a device value
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name=thread_name, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5.0)

    @property
    def url(self):
        return f"http://{self.httpd.server_address[0]}:{self.port}"


class TrainObserver:
    """Aggregates one trainer's live state for the HTTP plane.

    - liveness: step-loop heartbeat age (the loop stamps a perf_counter
      each instance) under the stale threshold;
    - readiness: the first optimizer step has completed;
    - /statusz: stage/epoch/step, last checkpoint, nonfinite counters,
      the step-phase summary and the goodput breakdown.

    Everything it reads is host-side state the training loop already
    maintains at the amortized finite-check cadence — a scrape never
    syncs the device.
    """

    def __init__(self, ctx, sink=None, registry=None, ledger=None,
                 stale_heartbeat_s=STALE_HEARTBEAT_S):
        self.ctx = ctx
        self.sink = sink
        self.ledger = ledger
        self.registry = registry or metrics_mod.registry()
        self.stale_heartbeat_s = float(stale_heartbeat_s)  # graftlint: disable=host-sync -- config scalar, not a device value
        self._profile_lock = threading.Lock()
        self._m_ready = self.registry.gauge(
            "rmd_train_ready", "trainer readiness (first step completed)")
        self._m_heartbeat = self.registry.gauge(
            "rmd_train_heartbeat_age_seconds",
            "seconds since the step loop last went around")
        self._m_step = self.registry.gauge(
            "rmd_train_step_index", "current global optimizer step")
        self._m_dropped = self.registry.gauge(
            "rmd_telemetry_dropped_total",
            "telemetry events shed by the bounded non-blocking buffer")
        self._m_phase_p50 = self.registry.gauge(
            "rmd_train_step_phase_p50_seconds",
            "rolling per-phase p50 of the step-trace window", ("phase",))
        self._m_phase_p99 = self.registry.gauge(
            "rmd_train_step_phase_p99_seconds",
            "rolling per-phase p99 of the step-trace window", ("phase",))
        self._m_goodput = self.registry.gauge(
            "rmd_train_goodput_seconds",
            "wall-clock seconds attributed to each goodput class",
            ("klass",))
        self._m_goodput_ratio = self.registry.gauge(
            "rmd_train_goodput_ratio",
            "productive share of total wall clock so far")
        self._m_hbm = self.registry.gauge(
            "rmd_train_hbm_peak_gib",
            "device memory high-water mark (epoch-boundary sample)")
        self._m_rss = self.registry.gauge(
            "rmd_train_host_rss_gib",
            "host resident set size (epoch-boundary sample)")
        self._m_grad = self.registry.gauge(
            "rmd_train_grad_norm",
            "global gradient norm sampled at the finite-fetch cadence")
        self._m_update = self.registry.gauge(
            "rmd_train_update_norm",
            "global update norm sampled at the finite-fetch cadence")

    # -- state ---------------------------------------------------------------

    def ready(self):
        return bool(getattr(self.ctx, "steps_completed", 0) > 0)

    def heartbeat_age(self):
        age = getattr(self.ctx, "heartbeat_age", None)
        return age() if age else 0.0

    def live(self):
        return self.heartbeat_age() < self.stale_heartbeat_s

    def _refresh_gauges(self):
        ctx = self.ctx
        self._m_ready.set(1.0 if self.ready() else 0.0)
        self._m_heartbeat.set(round(self.heartbeat_age(), 3))
        self._m_step.set(float(getattr(ctx, "step", 0)))
        if self.sink is not None:
            self._m_dropped.set(self.sink.dropped())
        summary = getattr(ctx, "steptraces", None)
        if summary is not None:
            snap = summary.snapshot()
            for phase, pcts in snap.get("phases", {}).items():
                self._m_phase_p50.labels(phase=phase).set(pcts["p50_ms"]
                                                          / 1000.0)
                self._m_phase_p99.labels(phase=phase).set(pcts["p99_ms"]
                                                          / 1000.0)
        if self.ledger is not None:
            self.ledger.publish(self.registry)
        mem = getattr(ctx, "last_memory", None)
        if mem:
            if mem.get("device_peak_gib") is not None:
                self._m_hbm.set(mem["device_peak_gib"])
            if mem.get("host_rss_gib") is not None:
                self._m_rss.set(mem["host_rss_gib"])
        norms = getattr(ctx, "last_norms", None)
        if norms:
            grad, update = norms
            if grad is not None:
                self._m_grad.set(grad)
            if update is not None:
                self._m_update.set(update)

    # -- endpoint payloads ---------------------------------------------------

    def metrics_text(self):
        self._refresh_gauges()
        return self.registry.render()

    def health(self):
        ready, age = self.ready(), self.heartbeat_age()
        live = age < self.stale_heartbeat_s
        return {
            "ready": ready,
            "live": live,
            "heartbeat_age_s": round(age, 3),
        }, (200 if ready and live else 503)

    def status(self):
        ctx = self.ctx
        summary = getattr(ctx, "steptraces", None)
        stage = getattr(ctx, "current_stage", None)
        chkpt = getattr(ctx, "last_checkpoint", None)
        out = {
            "ready": self.ready(),
            "heartbeat_age_s": round(self.heartbeat_age(), 3),
            "stage": getattr(stage, "index", None),
            "epoch": getattr(ctx, "current_epoch", None),
            "step": getattr(ctx, "step", 0),
            "steps_completed": getattr(ctx, "steps_completed", 0),
            "last_checkpoint": ({"path": str(chkpt[0]), "step": chkpt[1]}
                                if chkpt else None),
            "nonfinite": {
                "count": getattr(ctx, "_nf_last_count", 0),
                "consecutive": getattr(ctx, "_nf_consecutive", 0),
                "rollbacks": getattr(ctx, "_nf_rollbacks", 0),
            },
            "steps": summary.snapshot() if summary is not None else {},
            "goodput": (self.ledger.snapshot()
                        if self.ledger is not None else {}),
            "telemetry_dropped": (self.sink.dropped()
                                  if self.sink is not None else 0),
        }
        return out

    def profile(self, seconds):
        return capture_profile(self._profile_lock, seconds,
                               registry=self.registry)


def train_observer(ctx, port, sink=None, registry=None, ledger=None):
    """Build and start the trainer sidecar; returns the
    :class:`SidecarServer` (``.port`` resolves port 0)."""
    obs = TrainObserver(ctx, sink=sink, registry=registry, ledger=ledger)
    return SidecarServer(obs, port, thread_name="train-observe").start()
