"""Render a run's ``events.jsonl`` into a phase-breakdown report.

Pure functions over the event stream (no jax import): used by
``scripts/telemetry_report.py`` for the CLI rendering and by the tests
to hold the producers to the schema. The report answers the question
round 5 needed a dedicated debugging round for: *where do each step's
milliseconds go, and did anything anomalous happen?*
"""

import json
import logging

from .core import NewerSchema, UnknownKind, validate_event

# a compile this many optimizer steps after its stage started is a
# recompile — the per-stage step build compiles during the first step
DEFAULT_WARMUP_STEPS = 3
DEFAULT_SPIKE_FACTOR = 3.0

# one SLO window consuming error budget faster than this sustains is
# worth a flag (burn 1.0 = exactly at the objective)
SLO_BURN_FLAG = 1.0


def load_events(path, skipped=None):
    """Parse + validate a JSONL file. Returns (events, errors) where
    errors are (line_number, message) for records that fail the schema —
    a report over a partially-corrupt file still renders what it can.

    Forward compatibility: records an *older* reader can't know —
    unknown event kinds and same-major/newer-minor schema revisions —
    are warn-and-skipped rather than counted as errors, so old reports
    read new runs. Pass a list as ``skipped`` to collect their
    (line_number, message) pairs; they are logged either way.
    """
    events, errors = [], []
    with open(path) as fd:
        for n, line in enumerate(fd, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(validate_event(json.loads(line)))
            except (UnknownKind, NewerSchema) as e:
                logging.warning(f"{path}:{n}: skipping record from a "
                                f"newer producer: {e}")
                if skipped is not None:
                    skipped.append((n, str(e)))
            except (json.JSONDecodeError, ValueError) as e:
                errors.append((n, str(e)))
    return events, errors


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def phase_stats(events):
    """Per-phase timing stats over all step events.

    Returns {phase: {mean, p95, max, total, share}} in seconds, where
    ``share`` is the phase's fraction of total step wall time, plus the
    synthetic phases ``step`` (step wall time) and ``other`` (wall time
    not covered by any span: callbacks, validation, scheduler ticks).
    """
    steps = [e for e in events if e["kind"] == "step"]
    if not steps:
        return {}

    total_wall = sum(e["step_time"] for e in steps)
    names = sorted({n for e in steps for n in e["phases"]})
    out = {}
    for name in names:
        vals = sorted(e["phases"].get(name, 0.0) for e in steps)
        total = sum(vals)
        out[name] = {
            "mean": total / len(vals),
            "p95": _percentile(vals, 0.95),
            "max": vals[-1],
            "total": total,
            "share": total / total_wall if total_wall else 0.0,
        }

    walls = sorted(e["step_time"] for e in steps)
    out["step"] = {
        "mean": total_wall / len(walls),
        "p95": _percentile(walls, 0.95),
        "max": walls[-1],
        "total": total_wall,
        "share": 1.0,
    }
    covered = sum(s["total"] for n, s in out.items() if n != "step")
    other = max(0.0, total_wall - covered)
    out["other"] = {
        "mean": other / len(steps),
        "p95": float("nan"),
        "max": float("nan"),
        "total": other,
        "share": other / total_wall if total_wall else 0.0,
    }
    return out


def counter_stats(events):
    """Per-step scalar counters (``wire_bytes`` & co.) aggregated over
    all step events: {name: {mean, max, total}}. Counters accumulate at
    the producer's cadence (the prefetcher may attribute two puts to one
    step event), so ``mean`` is total / number of steps — the per-step
    average that survives the bunching."""
    steps = [e for e in events if e["kind"] == "step"]
    names = sorted({n for e in steps for n in e.get("counters", {})})
    out = {}
    for name in names:
        vals = [e.get("counters", {}).get(name, 0) for e in steps]
        out[name] = {
            "mean": sum(vals) / len(vals),
            "max": max(vals),
            "total": sum(vals),
        }
    return out


def device_step_time(events):
    """Mean device-pipeline seconds/step from the periodic sync samples.

    Each ``device_sync`` event covers the ``steps`` dispatches since the
    previous sample; ``wall`` (when present) is the wall time across them
    and ``seconds`` the drain time at the sample point — drain ≈ 0 means
    the host, not the device, is the bottleneck.
    """
    syncs = [e for e in events if e["kind"] == "device_sync"]
    covered = sum(e.get("steps", 1) for e in syncs)
    if not covered:
        return None
    wall = sum(e.get("wall", e["seconds"]) for e in syncs)
    drain = sum(e["seconds"] for e in syncs)
    return {"samples": len(syncs), "steps_covered": covered,
            "mean_step": wall / covered, "mean_drain": drain / len(syncs)}


def find_anomalies(events, warmup_steps=DEFAULT_WARMUP_STEPS,
                   spike_factor=DEFAULT_SPIKE_FACTOR):
    """Flag step-time spikes, recompiles after warmup, and non-finite
    flushes. Returns a list of human-readable strings (empty = clean)."""
    flags = []

    # per-stage spike detection: stages change shapes/optimizers, so a
    # global median would mis-flag every stage transition
    by_stage = {}
    for e in events:
        if e["kind"] == "step":
            by_stage.setdefault(e.get("stage"), []).append(e)
    for stage, steps in by_stage.items():
        if len(steps) < 4:
            continue
        walls = sorted(s["step_time"] for s in steps)
        median = walls[len(walls) // 2]
        if median <= 0:
            continue
        for s in steps:
            if s["step_time"] > spike_factor * median:
                flags.append(
                    f"step-time spike: step {s['step']} took "
                    f"{s['step_time'] * 1e3:.0f} ms "
                    f"({s['step_time'] / median:.1f}x the stage median)")

    # recompiles: a compile after `warmup_steps` optimizer steps of the
    # current stage means something re-traced mid-stage (shape drift,
    # cache invalidation) — exactly the silent cost telemetry exists for
    steps_in_stage = 0
    for e in events:
        if e["kind"] == "stage_start":
            steps_in_stage = 0
        elif e["kind"] == "step":
            steps_in_stage += 1
        elif e["kind"] == "compile" and steps_in_stage > warmup_steps:
            flags.append(
                f"recompile after warmup: '{e['label']}' compiled for "
                f"{e['seconds']:.2f} s after {steps_in_stage} steps in-stage")

    # AOT fallbacks: an artifact existed but could not be used (corrupt,
    # version-mismatched, incompatible inputs) — the boot paid a cold JIT
    # it expected to skip
    for e in events:
        if e["kind"] == "aot" and e.get("event") == "fallback":
            flags.append(
                f"AOT fallback to cold JIT: "
                f"{e.get('program', '?')}[{e.get('model', '?')}]"
                + (f" ({e['reason']})" if "reason" in e else ""))

    # SLO burn: any window that consumed error budget faster than
    # sustainable; paired with the trace tail so a burning class is
    # attributable to a phase (queue-dominated = load/batching, not
    # the model)
    slo = slo_stats(events)
    if slo:
        for klass, s in slo["classes"].items():
            if s["worst_burn_rate"] > SLO_BURN_FLAG:
                flags.append(
                    f"SLO burn: class '{klass or 'default'}' hit burn "
                    f"rate {s['worst_burn_rate']:.2f} "
                    f"(target {s['target_ms']:.0f} ms, latest attainment "
                    f"{s['attainment'] * 100:.1f}%)")
    traces = trace_stats(events)
    if traces and traces["tail"]["queue_dominated"]:
        tail = traces["tail"]
        flags.append(
            f"queue-dominated tail: slowest decile "
            f"({tail['count']} requests, mean "
            f"{tail['total_s'] * 1e3:.1f} ms) spends most of its time "
            f"queued ({tail['phases_s'].get('queue', 0.0) * 1e3:.1f} ms "
            f"mean) — add capacity or shrink max-wait, the model is "
            f"not the bottleneck")

    for e in events:
        if e["kind"] == "nonfinite":
            action = e.get("action", "raise")
            detail = f" ({e['trips']} update(s) dropped)" \
                if action == "skip" and "trips" in e else ""
            flags.append(
                f"non-finite guard tripped at step {e['step']} "
                f"[{action}]{detail}"
                + (f" (stage {e['stage']})" if "stage" in e else ""))
        elif e["kind"] == "quarantine":
            flags.append(f"corrupt checkpoint quarantined: {e['path']}")
        elif e["kind"] == "respawn":
            flags.append(
                f"decode worker {e['worker']} died "
                f"(exit code {e.get('exitcode')}) and was respawned")
        elif e["kind"] == "bad_sample":
            flags.append(
                f"sample {e['index']} failed to decode and was substituted"
                + (f": {e['error']}" if "error" in e else ""))
        elif e["kind"] == "preempt":
            flags.append(
                f"run preempted by {e['signal']} at step {e['step']} "
                "(emergency checkpoint written)")
        elif e["kind"] == "postmortem":
            flags.append(
                f"postmortem bundle written ({e.get('reason', '?')}): "
                f"{e.get('path', '?')}")

    # chronic data starvation: the steptrace summary marking the run as
    # starved means the input pipeline — not the device — paces training
    straces = steptrace_stats(events)
    if straces and straces["last"] is not None:
        if straces["last"].get("data_starved"):
            flags.append(
                "data-starved training: median step spends most of its "
                "time in data_wait — scale the input pipeline")
        if straces["starved"] > 1:
            flags.append(
                f"{straces['starved']} steptrace window(s) flagged "
                "data-starved")

    # calibration drift: a program's measured/predicted ratio left its
    # pinned prof-budget.json band — the device got slower (or faster)
    # without the static cost model noticing
    for e in prof_stats(events)["drifted"]:
        ratio = e.get("ratio")
        ratio_s = f"{ratio:.2f}" if ratio is not None else "?"
        flags.append(
            f"calibration drift: {e.get('program', '?')[:72]} "
            f"measured/predicted ratio {ratio_s} outside its pinned "
            f"band on {e.get('machine', '?')} — profile regression or "
            f"stale pin (scripts/graftprof.py --update)")

    return flags


def lint_stats(events):
    """Aggregate ``lint`` events (graftlint findings forwarded via
    ``analysis.lint.emit_events``): per-rule counts split by status,
    plus the open findings themselves (the ones that fail the gate)."""
    per_rule = {}
    open_findings = []
    total = 0
    for e in events:
        if e["kind"] != "lint":
            continue
        total += 1
        rule = e["rule"]
        status = e.get("status", "open")
        agg = per_rule.setdefault(rule, {"open": 0, "suppressed": 0,
                                         "baselined": 0})
        agg[status] = agg.get(status, 0) + 1
        if status == "open":
            open_findings.append(e)
    return {"total": total, "per_rule": per_rule,
            "open": open_findings}


def cost_stats(events):
    """Aggregate ``cost`` events (graftcost per-program summaries
    forwarded via ``analysis.cost.emit_events``): one row per audited
    program plus hazard totals across the set."""
    programs = [e for e in events if e["kind"] == "cost"]
    hazards = {}
    for e in programs:
        for name, n in (e.get("hazards") or {}).items():
            hazards[name] = hazards.get(name, 0) + n
    return {"programs": programs, "hazards": hazards}


def prof_stats(events):
    """Aggregate ``profile`` events (graftprof measured attributions
    forwarded via ``analysis.profile.emit_events``): one row per
    profiled program plus the drifted subset the anomaly section
    flags."""
    programs = [e for e in events if e["kind"] == "profile"]
    drifted = [e for e in programs if e.get("drift")]
    return {"programs": programs, "drifted": drifted}


def fault_events(events):
    """The run's fault-tolerance trail, in order: non-finite skips and
    rollbacks, preemption stops, auto-resume pickups, checkpoint
    quarantines, decode-worker respawns, absorbed bad samples, and
    flight-recorder postmortem dumps."""
    kinds = ("nonfinite", "preempt", "resume", "quarantine", "respawn",
             "bad_sample", "postmortem")
    return [e for e in events if e["kind"] in kinds]


def goodput_stats(events):
    """The run's wall-clock goodput breakdown, from the last ``goodput``
    event (the ledger's snapshots are cumulative, so the newest one —
    run-end when the run finished cleanly — covers the whole run)."""
    snaps = [e for e in events if e["kind"] == "goodput"]
    if not snaps:
        return None
    last = snaps[-1]
    classes = dict(last.get("classes") or {})
    total = last.get("total") or sum(classes.values())
    return {
        "total": total,
        "classes": classes,
        "goodput": last.get("goodput",
                            (classes.get("productive", 0.0)
                             / total if total else 0.0)),
        "replayed_steps": last.get("replayed_steps", 0),
        "snapshots": len(snaps),
        "final": bool(last.get("final")),
    }


def steptrace_stats(events):
    """Trainer step-trace windows + eval progress heartbeats from the
    ``steptrace`` events. The trainer events carry rolling per-phase
    p50/p99 snapshots — the last one is the freshest view; the eval
    events (scope="eval") are per-bucket liveness markers."""
    train = [e for e in events
             if e["kind"] == "steptrace" and e.get("scope") != "eval"]
    evals = [e for e in events
             if e["kind"] == "steptrace" and e.get("scope") == "eval"]
    if not train and not evals:
        return None
    out = {"windows": len(train), "last": train[-1] if train else None,
           "stragglers": sum(1 for e in train if e.get("straggler")),
           "starved": sum(1 for e in train if e.get("data_starved")),
           "eval_buckets": [
               {"name": e.get("name"), "bucket": e.get("bucket"),
                "batches": e.get("window"), "samples": e.get("samples"),
                "seconds": e.get("total"), "phases": e.get("phases", {})}
               for e in evals]}
    return out


def postmortem_stats(events):
    """Flight-recorder dumps: one entry per ``postmortem`` event."""
    return [{"reason": e.get("reason"), "path": e.get("path"),
             "steps": e.get("steps"), "events": e.get("events"),
             "checkpoint": e.get("checkpoint")}
            for e in events if e["kind"] == "postmortem"]


def aot_stats(events):
    """Compiled-program / AOT summaries: per (program kind, model) the
    artifact hits, misses, saves, fallbacks, bytes moved, and
    serialize/deserialize milliseconds, plus the boot configuration
    (effective compile-cache and program directories) when present."""
    out = {"boot": None, "programs": {}}
    for e in events:
        if e["kind"] == "boot":
            out["boot"] = {
                "compile_cache": e.get("compile_cache"),
                "aot_dir": e.get("aot_dir"),
                "aot": e.get("aot"),
                "prefetch": e.get("prefetch"),
            }
        elif e["kind"] == "aot":
            key = (e.get("program", "?"), e.get("model", "?"))
            agg = out["programs"].setdefault(key, {
                "hit": 0, "miss": 0, "save": 0, "fallback": 0,
                "bytes": 0, "seconds": 0.0, "reasons": []})
            ev = e.get("event")
            if ev in agg:
                agg[ev] += 1
            agg["bytes"] += e.get("bytes", 0)
            agg["seconds"] += e.get("seconds", 0.0)
            if ev == "fallback" and "reason" in e:
                agg["reasons"].append(e["reason"])
    return out


def eval_stats(events):
    """Per-sweep evaluation summaries from ``eval`` events: name,
    samples/s, compile count, pad-waste ratio, and the per-bucket batch
    breakdown (shape-bucketed evaluation, PR 4)."""
    out = []
    for e in events:
        if e["kind"] != "eval":
            continue
        secs = e["seconds"]
        out.append({
            "name": e["name"],
            "samples": e["samples"],
            "batches": e["batches"],
            "seconds": secs,
            "samples_per_sec": e.get(
                "samples_per_sec",
                e["samples"] / secs if secs else 0.0),
            "compiles": e.get("compiles", 0),
            "pad_waste_ratio": e.get("pad_waste_ratio", 0.0),
            "buckets": e.get("buckets", {}),
            "phases": e.get("phases", {}),
        })
    return out


def serve_stats(events):
    """Aggregate the serving path's ``serve`` events: request latency
    percentiles, per-span means, typed rejects/errors, per-bucket batch
    and compile counts, and warm-pool outcomes (PR 10)."""
    requests = []
    rejects = {}
    errors = {}
    buckets = {}
    warmups = []
    spans = {}
    classes = {}
    for e in events:
        if e["kind"] != "serve":
            continue
        ev = e.get("event")
        if ev == "request":
            requests.append(e)
            for name, secs in e.get("spans", {}).items():
                spans.setdefault(name, []).append(secs)
            # ladder requests carry their latency class + the iteration
            # budget actually spent (the adaptive classes vary it)
            k = e.get("klass")
            if k:
                c = classes.setdefault(
                    k, {"lat": [], "iterations": {}, "rungs": {}})
                c["lat"].append(e.get("seconds", 0.0))
                it = e.get("iterations", 0)
                c["iterations"][it] = c["iterations"].get(it, 0) + 1
        elif ev == "reject":
            reason = e.get("reason", "?")
            rejects[reason] = rejects.get(reason, 0) + 1
        elif ev == "error":
            err = e.get("error", "?")
            errors[err] = errors.get(err, 0) + 1
        elif ev == "batch":
            b = buckets.setdefault(e.get("bucket", "?"), {
                "batches": 0, "requests": 0, "fill": 0, "compiles": 0})
            b["batches"] += 1
            b["requests"] += e.get("size", 0)
            b["fill"] += e.get("fill", 0)
            b["compiles"] += e.get("compiles", 0)
            k = e.get("klass")
            if k:
                c = classes.setdefault(
                    k, {"lat": [], "iterations": {}, "rungs": {}})
                rung = e.get("rungs", 0)
                c["rungs"][rung] = c["rungs"].get(rung, 0) + 1
        elif ev == "warmup":
            warmups.append(e)
    if not (requests or rejects or errors or buckets or warmups):
        return None

    latencies = sorted(e.get("seconds", 0.0) for e in requests)
    return {
        "requests": len(requests),
        "rejects": rejects,
        "errors": errors,
        "p50_s": _percentile(latencies, 0.50),
        "p99_s": _percentile(latencies, 0.99),
        "mean_s": (sum(latencies) / len(latencies) if latencies else 0.0),
        "spans_s": {name: sum(vals) / len(vals)
                    for name, vals in sorted(spans.items())},
        "buckets": buckets,
        "classes": {k: {
            "requests": len(c["lat"]),
            "p50_s": _percentile(sorted(c["lat"]), 0.50),
            "p99_s": _percentile(sorted(c["lat"]), 0.99),
            "iterations": dict(sorted(c["iterations"].items())),
            "rungs": dict(sorted(c["rungs"].items())),
        } for k, c in sorted(classes.items())},
        "warmups": [{
            "model": w.get("model", "?"), "bucket": w.get("bucket", "?"),
            "wire": w.get("wire", "?"), "compiles": w.get("compiles", 0),
            "aot_hits": w.get("aot_hits", 0),
            "aot_saves": w.get("aot_saves", 0),
            "rung": w.get("rung"),
        } for w in warmups],
    }


def fleet_stats(events):
    """Aggregate the serving-fleet plane (PR 20): routed requests per
    replica, safe-failure retries, typed fleet sheds, drains by trigger,
    session handoffs by outcome, and supervisor restarts."""
    flt = [e for e in events if e["kind"] == "fleet"]
    if not flt:
        return {}
    stats = {
        "routes": 0, "per_replica": {}, "retries": 0,
        "sheds": {}, "drains": {}, "handoffs": {},
        "replicas_up": 0, "replicas_down": 0, "restarts": [],
    }
    for e in flt:
        ev = e.get("event")
        if ev == "route":
            stats["routes"] += 1
            r = str(e.get("replica", "?"))
            stats["per_replica"][r] = stats["per_replica"].get(r, 0) + 1
        elif ev == "retry":
            stats["retries"] += 1
        elif ev == "shed":
            reason = e.get("reason", "?")
            stats["sheds"][reason] = stats["sheds"].get(reason, 0) + 1
        elif ev == "drain":
            # both sides emit a drain event (router trigger + replica
            # acknowledgement); count triggers by reason once per side
            reason = e.get("reason", e.get("source", "?"))
            stats["drains"][reason] = stats["drains"].get(reason, 0) + 1
        elif ev == "handoff":
            outcome = e.get("outcome", "?")
            stats["handoffs"][outcome] = \
                stats["handoffs"].get(outcome, 0) + 1
        elif ev == "replica_up":
            stats["replicas_up"] += 1
        elif ev == "replica_down":
            stats["replicas_down"] += 1
        elif ev == "restart":
            stats["restarts"].append({
                "replica": e.get("replica"),
                "exit_code": e.get("exit_code"),
                "backoff_ms": e.get("backoff_ms"),
            })
    return stats


def video_stats(events):
    """Aggregate the streaming-video plane (PR 15): ``video`` frame and
    sequence events from the sequence runner / bench, ``session``
    warm-start cache events, and the serving path's video batches."""
    frames = []
    sequences = []
    sessions = {"hits": 0, "misses": 0, "evictions": {}}
    session_seen = False
    batches = {"batches": 0, "requests": 0, "warm": 0, "products": 0}
    for e in events:
        kind = e["kind"]
        if kind == "video":
            ev = e.get("event")
            if ev == "frame":
                frames.append(e)
            elif ev == "sequence":
                sequences.append(e)
        elif kind == "session":
            session_seen = True
            ev = e.get("event")
            if ev == "hit":
                sessions["hits"] += 1
            elif ev == "miss":
                sessions["misses"] += 1
            elif ev == "evict":
                reason = e.get("reason", "?")
                sessions["evictions"][reason] = \
                    sessions["evictions"].get(reason, 0) + 1
        elif (kind == "serve" and e.get("event") == "batch"
                and e.get("video")):
            batches["batches"] += 1
            batches["requests"] += e.get("size", 0)
            batches["warm"] += e.get("warm_members", 0)
            if e.get("products"):
                batches["products"] += 1
    if not (frames or sequences or session_seen or batches["batches"]):
        return None

    def frame_summary(group):
        if not group:
            return None
        its = [e.get("iterations", 0) for e in group]
        epes = [e["epe"] for e in group if "epe" in e]
        return {
            "frames": len(group),
            "mean_iterations": sum(its) / len(its),
            "mean_epe": sum(epes) / len(epes) if epes else None,
        }

    return {
        "warm": frame_summary([e for e in frames if e.get("warm")]),
        "cold": frame_summary([e for e in frames if not e.get("warm")]),
        "sequences": [{
            "frames": s.get("frames", 0),
            "warm_frames": s.get("warm_frames", 0),
            "mean_iterations": s.get("mean_iterations", 0.0),
            "frames_per_sec": s.get("frames_per_sec", 0.0),
            "mean_epe": s.get("mean_epe"),
        } for s in sequences],
        "sessions": sessions if session_seen else None,
        "batches": batches if batches["batches"] else None,
    }


def slo_stats(events):
    """Per-class SLO window summaries from the periodic ``slo`` events: the
    *latest* window per class (the current state) plus the worst burn
    rate seen across the run."""
    latest, worst = {}, {}
    for e in events:
        if e["kind"] != "slo":
            continue
        k = e.get("klass", "")
        latest[k] = e
        if e["burn_rate"] > worst.get(k, {}).get("burn_rate", -1.0):
            worst[k] = e
    if not latest:
        return None
    return {
        "classes": {k: {
            "target_ms": e["target_ms"],
            "objective": e.get("objective"),
            "window_s": e.get("window_s"),
            "good": e.get("good", 0),
            "bad": e.get("bad", 0),
            "attainment": e["attainment"],
            "burn_rate": e["burn_rate"],
            "worst_burn_rate": worst[k]["burn_rate"],
        } for k, e in sorted(latest.items())},
    }


def trace_stats(events, decile=0.9):
    """Aggregate per-request ``trace`` events: per-class counts and the
    slowest-decile critical-path phase breakdown (mean ms per phase,
    dominant phase named) — the offline twin of TraceSummary.tail()."""
    requests = [e for e in events
                if e["kind"] == "trace" and e.get("event") == "request"]
    batches = [e for e in events
               if e["kind"] == "trace" and e.get("event") == "batch"]
    if not requests:
        return None
    ranked = sorted(requests, key=lambda e: e.get("total", 0.0))
    cut = max(1, len(ranked) - int(len(ranked) * decile))
    slow = ranked[-cut:]
    phases = {}
    for e in slow:
        for name, secs in (e.get("phases") or {}).items():
            phases.setdefault(name, []).append(secs)
    mean = {name: sum(vals) / len(vals) for name, vals in phases.items()}
    dominant = max(mean, key=mean.get) if mean else None
    classes = {}
    for e in requests:
        k = e.get("klass") or ""
        classes.setdefault(k, []).append(e.get("total", 0.0))
    return {
        "requests": len(requests),
        "batches": len(batches),
        "classes": {k: {
            "count": len(v),
            "p50_s": _percentile(sorted(v), 0.50),
            "p99_s": _percentile(sorted(v), 0.99),
        } for k, v in sorted(classes.items())},
        "tail": {
            "count": len(slow),
            "total_s": sum(e.get("total", 0.0) for e in slow) / len(slow),
            "phases_s": {k: mean[k] for k in sorted(mean)},
            "dominant": dominant,
            "queue_dominated": dominant == "queue",
        },
    }


def sharding_stats(events):
    """Per-stage SPMD placement summaries from ``sharding`` events: mesh
    shape and the per-chip vs. replicated byte accounting the partitioner
    reported when it placed the training state (PR 6)."""
    out = []
    for e in events:
        if e["kind"] != "sharding":
            continue
        out.append({
            "stage": e.get("stage"),
            "mesh": e.get("mesh", {}),
            "params_per_chip": e["params_bytes_per_chip"],
            "params_replicated": e.get("params_bytes_replicated", 0),
            "opt_per_chip": e["opt_bytes_per_chip"],
            "opt_replicated": e.get("opt_bytes_replicated", 0),
            "params_sharded_leaves": e.get("params_sharded_leaves", 0),
            "params_leaves": e.get("params_leaves", 0),
        })
    return out


def _fmt_ms(seconds):
    try:
        return f"{seconds * 1e3:9.2f}"
    except (TypeError, ValueError):  # pragma: no cover
        return "        -"


def render(events, errors=(), warmup_steps=DEFAULT_WARMUP_STEPS,
           spike_factor=DEFAULT_SPIKE_FACTOR):
    """The full plain-text report."""
    lines = []
    steps = [e for e in events if e["kind"] == "step"]
    compiles = [e for e in events if e["kind"] == "compile"]
    caches = [e for e in events if e["kind"] == "cache"]
    stages = [e for e in events if e["kind"] == "stage_start"]
    memory = [e for e in events if e["kind"] == "memory"]
    checkpoints = [e for e in events if e["kind"] == "checkpoint"]

    lines.append("== run summary ==")
    lines.append(
        f"events: {len(events)}  stages: {len(stages)}  "
        f"optimizer steps: {len(steps)}  checkpoints: {len(checkpoints)}")
    if errors:
        lines.append(f"schema errors: {len(errors)} "
                     f"(first: line {errors[0][0]}: {errors[0][1]})")
    if steps:
        ema = steps[-1]["throughput_ema"]
        lines.append(f"final throughput EMA: {ema:.3f} steps/s")

    stats = phase_stats(events)
    if stats:
        lines.append("")
        lines.append("== step phase breakdown (ms) ==")
        lines.append(f"{'phase':<14} {'mean':>9} {'p95':>9} {'max':>9} "
                     f"{'share':>7}")
        order = sorted((n for n in stats if n not in ("step", "other")),
                       key=lambda n: -stats[n]["total"])
        for name in order + ["other", "step"]:
            s = stats[name]
            lines.append(
                f"{name:<14} {_fmt_ms(s['mean'])} {_fmt_ms(s['p95'])} "
                f"{_fmt_ms(s['max'])} {s['share'] * 100:6.1f}%")

    counters = counter_stats(events)
    if counters:
        lines.append("")
        lines.append("== step counters ==")
        for name, s in counters.items():
            if name.endswith("_bytes"):
                lines.append(
                    f"{name:<14} {s['mean'] / 2 ** 20:9.2f} MiB/step mean  "
                    f"{s['total'] / 2 ** 20:9.2f} MiB total")
            else:
                lines.append(
                    f"{name:<14} {s['mean']:9.2f}/step mean  "
                    f"{s['total']:9.2f} total")

    dev = device_step_time(events)
    if dev:
        lines.append("")
        lines.append(
            f"device pipeline: {dev['mean_step'] * 1e3:.2f} ms/step over "
            f"{dev['steps_covered']} sampled steps "
            f"({dev['samples']} syncs, mean drain "
            f"{dev['mean_drain'] * 1e3:.2f} ms)")

    straces = steptrace_stats(events)
    if straces and straces["last"]:
        last = straces["last"]
        lines.append("")
        lines.append(f"== step traces ({straces['windows']} windows) ==")
        lines.append(f"{'phase':<12} {'p50':>9} {'p99':>9}")
        for phase, pcts in last.get("phases", {}).items():
            lines.append(f"{phase:<12} {pcts['p50_ms']:9.2f} "
                         f"{pcts['p99_ms']:9.2f}")
        total = last.get("total_ms", {})
        lines.append(f"{'total':<12} {total.get('p50', 0):9.2f} "
                     f"{total.get('p99', 0):9.2f}")
        if straces["stragglers"] or straces["starved"]:
            lines.append(
                f"flags: {straces['stragglers']} straggler window(s), "
                f"{straces['starved']} data-starved window(s)")
    if straces and straces["eval_buckets"]:
        lines.append("")
        lines.append(f"== eval progress ({len(straces['eval_buckets'])} "
                     f"buckets) ==")
        for b in straces["eval_buckets"]:
            lines.append(
                f"{b['name'] or 'eval':<16} {b['bucket'] or '?':<12} "
                f"{b['batches'] or 0:4d} batches  "
                f"{b['samples'] or 0:5d} samples  "
                f"{b['seconds'] or 0:8.2f} s")

    goodput = goodput_stats(events)
    if goodput:
        lines.append("")
        lines.append("== goodput ==")
        total = goodput["total"]
        lines.append(
            f"wall clock: {total:.2f} s, goodput "
            f"{goodput['goodput'] * 100:.1f}% productive"
            + (f", {goodput['replayed_steps']} step(s) replayed"
               if goodput["replayed_steps"] else ""))
        for klass, secs in sorted(goodput["classes"].items(),
                                  key=lambda kv: -kv[1]):
            if secs <= 0 and klass != "productive":
                continue
            share = secs / total * 100 if total else 0.0
            lines.append(f"{klass:<14} {secs:9.2f} s {share:6.1f}%")

    shardings = sharding_stats(events)
    if shardings:
        lines.append("")
        lines.append("== sharding ==")
        for s in shardings:
            mesh = " × ".join(f"{k}={v}" for k, v in s["mesh"].items()) \
                or "?"
            stage = f"stage {s['stage']}" if s["stage"] is not None else "-"
            mib = 2 ** 20

            def ratio(per, full):
                return f"{per / full * 100:.0f}%" if full else "-"

            lines.append(
                f"{stage:<10} mesh [{mesh}]  params "
                f"{s['params_per_chip'] / mib:.1f} MiB/chip "
                f"({ratio(s['params_per_chip'], s['params_replicated'])} of "
                f"replicated), opt "
                f"{s['opt_per_chip'] / mib:.1f} MiB/chip "
                f"({ratio(s['opt_per_chip'], s['opt_replicated'])}), "
                f"{s['params_sharded_leaves']}/{s['params_leaves']} "
                "param tensors sharded")

    evals = eval_stats(events)
    if evals:
        lines.append("")
        lines.append("== evaluation ==")
        lines.append(f"{'sweep':<16} {'samples':>8} {'smp/s':>8} "
                     f"{'compiles':>9} {'pad-waste':>10}")
        for ev in evals:
            lines.append(
                f"{ev['name']:<16} {ev['samples']:>8d} "
                f"{ev['samples_per_sec']:>8.2f} {ev['compiles']:>9d} "
                f"{ev['pad_waste_ratio'] * 100:>9.1f}%")
            for key, b in sorted(ev["buckets"].items()):
                lines.append(
                    f"  bucket {key:<12} {b['samples']:>6d} samples in "
                    f"{b['batches']} batches, {b.get('compiles', 0)} "
                    "compiles")

    srv = serve_stats(events)
    if srv:
        lines.append("")
        lines.append("== serving ==")
        shed = sum(srv["rejects"].values())
        errs = sum(srv["errors"].values())
        summary = f"requests: {srv['requests']} served"
        if shed:
            detail = ", ".join(f"{r}={n}" for r, n in
                               sorted(srv["rejects"].items()))
            summary += f", {shed} rejected ({detail})"
        if errs:
            detail = ", ".join(f"{k}={n}" for k, n in
                               sorted(srv["errors"].items()))
            summary += f", {errs} errors ({detail})"
        lines.append(summary)
        if srv["requests"]:
            lines.append(
                f"latency: p50 {srv['p50_s'] * 1e3:.1f} ms, "
                f"p99 {srv['p99_s'] * 1e3:.1f} ms, "
                f"mean {srv['mean_s'] * 1e3:.1f} ms")
            spans = srv["spans_s"]
            if spans:
                lines.append("spans:   " + ", ".join(
                    f"{name} {secs * 1e3:.1f} ms"
                    for name, secs in spans.items()))
        for k, c in sorted(srv.get("classes", {}).items()):
            its = ", ".join(f"{n} its x{cnt}"
                            for n, cnt in c["iterations"].items())
            lines.append(
                f"  class {k:<9} {c['requests']:>4d} requests: "
                f"p50 {c['p50_s'] * 1e3:.1f} ms, "
                f"p99 {c['p99_s'] * 1e3:.1f} ms [{its or '-'}]")
        for key, b in sorted(srv["buckets"].items()):
            lines.append(
                f"  bucket {key:<12} {b['requests']:>6d} requests in "
                f"{b['batches']} batches ({b['fill']} pad fill), "
                f"{b['compiles']} compiles")
        for w in srv["warmups"]:
            rung = f", rung {w['rung']}" if w.get("rung") else ""
            lines.append(
                f"  warm pool {w['model']}[{w['bucket']}] ({w['wire']}"
                f"{rung}): {w['compiles']} compiles, {w['aot_hits']} AOT "
                f"hits, {w['aot_saves']} AOT saves")

    video = video_stats(events)
    if video:
        lines.append("")
        lines.append("== video ==")
        for arm in ("cold", "warm"):
            s = video[arm]
            if not s:
                continue
            epe = (f", EPE {s['mean_epe']:.3f}"
                   if s["mean_epe"] is not None else "")
            lines.append(
                f"{arm} frames: {s['frames']}, mean "
                f"{s['mean_iterations']:.1f} iterations{epe}")
        for s in video["sequences"]:
            epe = (f", EPE {s['mean_epe']:.3f}"
                   if s.get("mean_epe") is not None else "")
            lines.append(
                f"  sequence: {s['frames']} frames "
                f"({s['warm_frames']} warm), "
                f"{s['mean_iterations']:.1f} mean iterations, "
                f"{s['frames_per_sec']:.2f} frames/s{epe}")
        sess = video["sessions"]
        if sess:
            total = sess["hits"] + sess["misses"]
            ratio = sess["hits"] / total * 100 if total else 0.0
            evict = ", ".join(f"{r}={n}" for r, n in
                              sorted(sess["evictions"].items()))
            lines.append(
                f"sessions: {sess['hits']} warm hits / {total} lookups "
                f"({ratio:.0f}%)"
                + (f", evictions {evict}" if evict else ""))
        b = video["batches"]
        if b:
            lines.append(
                f"serve batches: {b['batches']} video batches, "
                f"{b['requests']} requests ({b['warm']} warm members, "
                f"{b['products']} with fw/bw products)")

    flt = fleet_stats(events)
    if flt:
        lines.append("")
        lines.append("== fleet ==")
        per = ", ".join(f"{r}={n}" for r, n in
                        sorted(flt["per_replica"].items()))
        lines.append(
            f"routed: {flt['routes']} requests"
            + (f" ({per})" if per else "")
            + (f", {flt['retries']} retries" if flt["retries"] else ""))
        if flt["sheds"]:
            lines.append("sheds:  " + ", ".join(
                f"{r}={n}" for r, n in sorted(flt["sheds"].items())))
        if flt["drains"]:
            lines.append("drains: " + ", ".join(
                f"{r}={n}" for r, n in sorted(flt["drains"].items())))
        if flt["handoffs"]:
            lines.append("handoffs: " + ", ".join(
                f"{o}={n}" for o, n in sorted(flt["handoffs"].items())))
        if flt["replicas_up"] or flt["replicas_down"]:
            lines.append(
                f"membership: {flt['replicas_up']} up, "
                f"{flt['replicas_down']} down, "
                f"{len(flt['restarts'])} supervisor restarts")
        for r in flt["restarts"][:8]:
            lines.append(
                f"  restart replica {r['replica']}: exit "
                f"{r['exit_code']}, backoff {r['backoff_ms']} ms")

    traces = trace_stats(events)
    if traces:
        lines.append("")
        lines.append("== tracing ==")
        lines.append(
            f"traced: {traces['requests']} requests in "
            f"{traces['batches']} batches")
        for k, c in sorted(traces["classes"].items()):
            lines.append(
                f"  class {k or 'default':<9} {c['count']:>4d} requests: "
                f"p50 {c['p50_s'] * 1e3:.1f} ms, "
                f"p99 {c['p99_s'] * 1e3:.1f} ms")
        tail = traces["tail"]
        breakdown = ", ".join(
            f"{name} {secs * 1e3:.1f} ms"
            for name, secs in tail["phases_s"].items())
        lines.append(
            f"slowest decile ({tail['count']} requests, mean "
            f"{tail['total_s'] * 1e3:.1f} ms): {breakdown or '-'} "
            f"[dominant: {tail['dominant'] or '-'}]")

    slo = slo_stats(events)
    if slo:
        lines.append("")
        lines.append("== slo ==")
        lines.append(f"{'class':<10} {'target':>9} {'attain':>8} "
                     f"{'burn':>7} {'worst':>7} {'window':>12}")
        for k, s in slo["classes"].items():
            window = f"{s['good']}+{s['bad']}/{s['window_s']:.0f}s"
            lines.append(
                f"{k or 'default':<10} {s['target_ms']:>7.1f}ms "
                f"{s['attainment'] * 100:>7.1f}% {s['burn_rate']:>7.2f} "
                f"{s['worst_burn_rate']:>7.2f} {window:>12}")

    aot = aot_stats(events)
    if aot["boot"] or aot["programs"]:
        lines.append("")
        lines.append("== compiled programs ==")
        boot = aot["boot"]
        if boot:
            lines.append(
                f"compile cache: {boot['compile_cache'] or 'disabled'}")
            lines.append(
                f"AOT programs:  {boot['aot_dir'] or 'disabled'}")
            if boot.get("prefetch") is not None:
                lines.append(
                    "prefetch:      "
                    + ("on (double-buffered device_put)"
                       if boot["prefetch"] else "off (synchronous)"))
        for (program, model), agg in sorted(aot["programs"].items()):
            lines.append(
                f"{program}[{model}]: {agg['hit']} AOT hits, "
                f"{agg['miss']} misses, {agg['save']} saves, "
                f"{agg['fallback']} fallbacks "
                f"({agg['bytes'] / 2 ** 20:.1f} MiB, "
                f"{agg['seconds'] * 1e3:.0f} ms serialize/load)")

    if compiles or caches:
        lines.append("")
        lines.append("== compiles ==")
        by_label = {}
        for c in compiles:
            agg = by_label.setdefault(c["label"], [0, 0.0])
            agg[0] += 1
            agg[1] += c["seconds"]
        for label, (n, secs) in sorted(by_label.items()):
            lines.append(f"{label:<20} {n:3d} compiles  {secs:8.2f} s")
        hits = sum(1 for c in caches if c["event"] == "hit")
        misses = sum(1 for c in caches if c["event"] == "miss")
        lines.append(f"persistent compile cache: {hits} hits, "
                     f"{misses} misses")

    fault = fault_events(events)
    if fault:
        lines.append("")
        lines.append(f"== fault tolerance ({len(fault)} events) ==")
        for e in fault:
            kind = e["kind"]
            if kind == "nonfinite":
                action = e.get("action", "raise")
                if action == "rollback":
                    lines.append(
                        f"  rollback at step {e.get('from_step', e['step'])}"
                        f" -> step {e.get('to_step', '?')} "
                        f"('{e.get('path', '?')}')")
                elif action == "skip":
                    lines.append(
                        f"  skip at step {e['step']}: {e.get('trips', 1)} "
                        f"update(s) dropped "
                        f"({e.get('window_trips', '?')} in window)")
                else:
                    lines.append(f"  non-finite abort at step {e['step']}")
            elif kind == "preempt":
                lines.append(
                    f"  preempt ({e['signal']}) at step {e['step']}")
            elif kind == "resume":
                lines.append(
                    f"  resume from '{e['path']}' at step {e['step']}")
            elif kind == "quarantine":
                lines.append(f"  quarantined '{e['path']}'")
            elif kind == "respawn":
                lines.append(
                    f"  respawned decode worker {e['worker']} "
                    f"(exit code {e.get('exitcode')})")
            elif kind == "bad_sample":
                lines.append(
                    f"  substituted bad sample {e['index']}"
                    + (f" ({e['error']})" if "error" in e else ""))
            elif kind == "postmortem":
                lines.append(
                    f"  postmortem bundle ({e.get('reason', '?')}): "
                    f"'{e.get('path', '?')}'")

    posts = postmortem_stats(events)
    if posts:
        lines.append("")
        lines.append(f"== postmortem ({len(posts)}) ==")
        for p in posts:
            lines.append(
                f"{p['reason'] or '?':<20} {p['steps'] or 0:4d} step "
                f"trace(s), {p['events'] or 0:4d} event(s): '{p['path']}'"
                + (f" (checkpoint '{p['checkpoint']}')"
                   if p.get("checkpoint") else ""))

    lint = lint_stats(events)
    if lint["total"]:
        lines.append("")
        lines.append(f"== lint ({lint['total']} findings) ==")
        for rule, agg in sorted(lint["per_rule"].items()):
            lines.append(
                f"{rule:<16} {agg['open']:3d} open, "
                f"{agg['suppressed']:3d} suppressed, "
                f"{agg['baselined']:3d} baselined")
        for e in lint["open"]:
            lines.append(f"  ! {e['path']}:{e['line']}: {e['rule']}: "
                         f"{e.get('message', '')}")

    cost = cost_stats(events)
    if cost["programs"]:
        lines.append("")
        lines.append(f"== program costs ({len(cost['programs'])} "
                     f"programs) ==")
        for e in cost["programs"]:
            verd = ", ".join(f"{k}={v}" for k, v in
                             sorted((e.get("verdicts") or {}).items()))
            lines.append(
                f"{e.get('program', '?')[:72]}: "
                f"{e['flops'] / 1e6:.1f} MFLOP, "
                f"{e['bytes'] / 2**20:.1f} MiB, "
                f"{e.get('intensity', 0):.1f} flop/B, collectives "
                f"{e.get('collective_bytes', 0) / 2**20:.2f} MiB"
                + (f" [{verd}]" if verd else ""))
        if cost["hazards"]:
            lines.append("  hazards: " + ", ".join(
                f"{k}={v}" for k, v in sorted(cost["hazards"].items())))

    prof = prof_stats(events)
    if prof["programs"]:
        machines = sorted({e.get("machine", "?")
                           for e in prof["programs"]})
        lines.append("")
        lines.append(f"== profiling ({len(prof['programs'])} programs, "
                     f"machine {', '.join(machines)}) ==")
        for e in prof["programs"]:
            ratio = e.get("ratio")
            ratio_s = f"{ratio:.2f}" if ratio is not None else "-"
            classes = ", ".join(
                f"{k} {v * 1e3:.1f}ms" for k, v in sorted(
                    (e.get("classes") or {}).items(),
                    key=lambda kv: -kv[1])[:3])
            lines.append(
                f"{e.get('program', '?')[:72]}: measured "
                f"{e['seconds'] * 1e3:.1f} ms vs predicted "
                f"{e.get('predicted_seconds', 0) * 1e3:.1f} ms "
                f"(ratio {ratio_s})"
                + (f" [{classes}]" if classes else "")
                + (" [drift]" if e.get("drift") else "")
                + (" [stale fingerprint]"
                   if e.get("stale_fingerprint") else ""))

    if memory:
        peak_rss = max(m["host_rss_gib"] for m in memory)
        lines.append("")
        line = (f"memory watermarks: host rss {peak_rss:.2f} GiB, "
                f"live arrays max {max(m['live_arrays'] for m in memory)}")
        dev_peaks = [m["device_peak_gib"] for m in memory
                     if "device_peak_gib" in m]
        if dev_peaks:
            line += f", device peak {max(dev_peaks):.2f} GiB"
        lines.append(line)

    flags = find_anomalies(events, warmup_steps=warmup_steps,
                           spike_factor=spike_factor)
    lines.append("")
    if flags:
        lines.append(f"== anomalies ({len(flags)}) ==")
        lines.extend(f"  ! {f}" for f in flags)
    else:
        lines.append("== anomalies: none ==")

    return "\n".join(lines)


# -- multi-run merge ---------------------------------------------------------

# merged-timeline landmarks: the low-rate run-shape events worth
# interleaving across hosts (the per-step firehose would drown them)
MERGE_KINDS = ("run_start", "stage_start", "stage_end", "compile",
               "checkpoint", "resume", "preempt", "postmortem",
               "nonfinite", "run_end")

# eager-op compiles (model init fires hundreds of ms-scale 'jit' ones)
# are noise at timeline granularity; only program-scale compiles are
# landmarks
MERGE_COMPILE_MIN_S = 0.5


def _is_landmark(e):
    if e["kind"] not in MERGE_KINDS:
        return False
    if e["kind"] == "compile":
        return e.get("seconds", 0.0) >= MERGE_COMPILE_MIN_S
    return True


def merge_stats(runs):
    """Cross-run statistics for a merged report.

    ``runs`` is a list of ``{"label": str, "events": [...]}`` dicts (one
    per host / run id, events already schema-validated). All runs share
    the ``t`` wall clock (``time.time()``), so cross-host deltas are as
    honest as the hosts' NTP. Returns per-run rows (start skew vs the
    earliest host, median step time, straggler delta vs the fastest
    host, goodput) plus the merged landmark timeline.
    """
    rows = []
    t0s, medians = {}, {}
    for run in runs:
        label, events = run["label"], run["events"]
        ts = [e["t"] for e in events]
        steps = sorted(e["step_time"] for e in events
                       if e["kind"] == "step")
        t0s[label] = min(ts) if ts else None
        medians[label] = steps[len(steps) // 2] if steps else None
        gp = goodput_stats(events)
        rows.append({
            "label": label,
            "t0": t0s[label],
            "t_end": max(ts) if ts else None,
            "events": len(events),
            "steps": len(steps),
            "median_step_s": medians[label],
            "goodput": gp["goodput"] if gp else None,
        })

    anchor = min((t for t in t0s.values() if t is not None), default=None)
    fastest = min((m for m in medians.values() if m is not None),
                  default=None)
    for row in rows:
        # skew: how late this host's stream starts vs the earliest one
        row["skew_s"] = (row["t0"] - anchor
                         if anchor is not None and row["t0"] is not None
                         else None)
        # straggler delta: median step time vs the fastest host's median
        row["straggler_x"] = (row["median_step_s"] / fastest
                              if fastest and row["median_step_s"]
                              else None)

    timeline = []
    for run in runs:
        for e in run["events"]:
            if _is_landmark(e):
                timeline.append((e["t"], run["label"], e))
    timeline.sort(key=lambda item: item[0])
    return {"anchor": anchor, "rows": rows, "timeline": timeline}


def _describe_landmark(e):
    kind = e["kind"]
    if kind == "compile":
        return f"compile '{e.get('label', '?')}' {e['seconds']:.2f} s"
    if kind == "checkpoint":
        return f"checkpoint @ step {e.get('step', '?')}"
    if kind == "stage_start":
        return f"stage {e.get('stage', '?')} start"
    if kind == "stage_end":
        return f"stage {e.get('stage', '?')} end"
    if kind == "resume":
        return f"resume @ step {e.get('step', '?')}"
    if kind == "preempt":
        return f"preempt ({e.get('signal', '?')}) @ step {e.get('step', '?')}"
    if kind == "postmortem":
        return f"postmortem ({e.get('reason', '?')})"
    if kind == "nonfinite":
        return f"nonfinite @ step {e.get('step', '?')}"
    return kind


def render_merged(runs):
    """Render multiple runs' event streams as one report: a per-host
    table (skew / median step / straggler delta / goodput) followed by
    the merged landmark timeline on the shared wall clock."""
    merged = merge_stats(runs)
    width = max([len(r["label"]) for r in merged["rows"]] + [4])
    lines = [f"== merged report ({len(runs)} run(s)) ==", ""]
    lines.append(f"{'run':<{width}} {'events':>7} {'steps':>6} "
                 f"{'skew':>9} {'med step':>9} {'straggler':>9} "
                 f"{'goodput':>8}")
    for r in merged["rows"]:
        skew = (f"{r['skew_s']:+8.2f}s" if r["skew_s"] is not None
                else f"{'-':>9}")
        med = (_fmt_ms(r["median_step_s"])
               if r["median_step_s"] is not None else "-")
        strag = (f"{r['straggler_x']:8.2f}x"
                 if r["straggler_x"] is not None else f"{'-':>9}")
        gp = (f"{r['goodput'] * 100:7.1f}%"
              if r["goodput"] is not None else f"{'-':>8}")
        lines.append(f"{r['label']:<{width}} {r['events']:>7} "
                     f"{r['steps']:>6} {skew:>9} {med:>9} {strag:>9} "
                     f"{gp:>8}")

    stragglers = [r for r in merged["rows"]
                  if r["straggler_x"] is not None
                  and r["straggler_x"] > DEFAULT_SPIKE_FACTOR / 2]
    for r in stragglers:
        lines.append(f"  ! straggler: '{r['label']}' steps "
                     f"{r['straggler_x']:.2f}x slower than the fastest "
                     f"host")

    if merged["timeline"]:
        anchor = merged["anchor"] or merged["timeline"][0][0]
        lines.append("")
        lines.append(f"== merged timeline ({len(merged['timeline'])} "
                     f"landmark(s), t0 = earliest host) ==")
        for t, label, e in merged["timeline"]:
            lines.append(f"  +{t - anchor:9.2f}s  {label:<{width}}  "
                         f"{_describe_landmark(e)}")

    return "\n".join(lines)
