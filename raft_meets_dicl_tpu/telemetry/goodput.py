"""Wall-clock goodput ledger for a training run.

Classifies every second between :meth:`GoodputLedger.start` and "now"
into one of :data:`CLASSES`:

========== =============================================================
class      wall time …
========== =============================================================
productive driving training steps (the remainder after every overhead
           class below is subtracted — by construction the classes sum
           exactly to the total)
compile    inside jax compilation (the ``compile`` event listener)
data_starved blocked on the input pipeline (the per-step ``data_wait``
           phase)
checkpoint writing checkpoints (``checkpoint`` events, emergency saves
           included)
eval       inside validation sweeps (``eval`` events)
resume_replay between a ``resume`` restore and the first step completed
           past the restored step, net of time already charged to
           another class — the cost of getting back to where the
           preempted run died
preempted  between the preemption signal (``preempt`` event) and ledger
           close, net of the emergency-checkpoint charge — teardown
           wall clock the preemption burned
========== =============================================================

The ledger is a pure event consumer: :func:`observe` is tapped from
``Telemetry.emit`` (before the sink lock, so a ledger can itself emit),
which means checkpoint/eval/compile/preempt/resume accounting needs no
extra wiring at the call sites.  The step loop additionally charges
``data_starved`` through the ``step`` event's drained phases.

A process-wide active ledger mirrors the telemetry sink pattern:
:func:`activate` installs one, :func:`get` returns it (or the no-op
:class:`NullLedger`), and the ``RMD_GOODPUT`` switch gates activation.
"""

import threading
import time

CLASSES = ("productive", "compile", "data_starved", "checkpoint", "eval",
           "resume_replay", "preempted")

# overhead classes charged explicitly; productive is the remainder
_CHARGED = tuple(c for c in CLASSES if c != "productive")


class NullLedger:
    """Inactive ledger: every operation is a no-op."""

    enabled = False

    def start(self, t=None):
        return self

    def charge(self, klass, seconds):
        pass

    def observe(self, kind, fields):
        pass

    def snapshot(self, t=None):
        return {}

    def emit_event(self, tele, **fields):
        pass

    def publish(self, registry):
        pass

    def close(self, t=None):
        return {}


class GoodputLedger:
    """Accounts a run's wall clock into goodput classes.

    All times are ``time.perf_counter`` seconds.  ``snapshot`` computes
    ``productive`` as ``total - sum(charged classes)`` (clamped at 0),
    so the classes always sum to the total wall clock.
    """

    enabled = True

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = None
        self._t_close = None
        self._charges = {c: 0.0 for c in _CHARGED}
        self._accounted = 0.0
        # windows: (armed-at, accounted-at-arm); replay also needs the
        # step to wait for
        self._replay = None
        self._replay_until = None
        self._preempt = None
        self.replayed_steps = 0

    def start(self, t=None):
        self._t0 = time.perf_counter() if t is None else float(t)
        return self

    # -- charging ------------------------------------------------------------

    def charge(self, klass, seconds):
        if klass not in self._charges:
            raise ValueError(f"unknown goodput class {klass!r}")
        seconds = float(seconds)
        if seconds <= 0:
            return
        with self._lock:
            self._charges[klass] += seconds
            self._accounted += seconds

    def _window_unaccounted(self, armed, now):
        """Wall clock of the window net of charges made inside it —
        what the window burned beyond already-classified work."""
        t_arm, accounted_arm = armed
        return max(0.0, (now - t_arm) - (self._accounted - accounted_arm))

    # -- event tap -----------------------------------------------------------

    def observe(self, kind, fields):
        """Consume one telemetry event (tapped from ``Telemetry.emit``)."""
        if self._t0 is None:
            return
        if kind == "compile":
            self.charge("compile", fields.get("seconds") or 0.0)
        elif kind == "checkpoint":
            self.charge("checkpoint", fields.get("seconds") or 0.0)
        elif kind == "eval":
            self.charge("eval", fields.get("seconds") or 0.0)
        elif kind == "step":
            phases = fields.get("phases") or {}
            self.charge("data_starved", phases.get("data_wait") or 0.0)
            self.step_completed(fields.get("step"))
        elif kind == "resume":
            self.resume_from(fields.get("step"))
        elif kind == "preempt":
            with self._lock:
                if self._preempt is None:
                    self._preempt = (time.perf_counter(), self._accounted)

    def resume_from(self, step):
        """Arm the resume-replay window: everything from here until the
        first step completed past ``step`` (net of other charges) is
        replay — restore, rebuild, recompile, re-warm."""
        with self._lock:
            self._replay = (time.perf_counter(), self._accounted)
            self._replay_until = int(step or 0)

    def step_completed(self, step):
        if self._replay is None or step is None:
            return
        with self._lock:
            if self._replay is None or int(step) < self._replay_until:
                return
            armed, self._replay = self._replay, None
            now = time.perf_counter()
            seconds = self._window_unaccounted(armed, now)
            self.replayed_steps = max(0, int(step) - self._replay_until)
            self._charges["resume_replay"] += seconds
            self._accounted += seconds

    # -- reporting -----------------------------------------------------------

    def snapshot(self, t=None):
        if self._t0 is None:
            return {}
        now = (self._t_close if t is None and self._t_close is not None
               else (time.perf_counter() if t is None else float(t)))
        with self._lock:
            total = max(0.0, now - self._t0)
            classes = {c: round(v, 4) for c, v in self._charges.items()}
            accounted = sum(classes.values())
            classes["productive"] = round(max(0.0, total - accounted), 4)
            # classes must sum to total: absorb the float residual (and
            # any over-charge clamp) into the reported total
            return {
                "total": round(sum(classes.values()), 4),
                "wall": round(total, 4),
                "classes": classes,
                "goodput": round(classes["productive"]
                                 / max(sum(classes.values()), 1e-9), 4),
                "replayed_steps": self.replayed_steps,
            }

    def emit_event(self, tele, **fields):
        """Emit the ``goodput`` event with the current breakdown."""
        snap = self.snapshot()
        if snap:
            tele.emit("goodput", **snap, **fields)

    def publish(self, registry):
        """Refresh the ``rmd_train_goodput_*`` gauges from a snapshot."""
        snap = self.snapshot()
        if not snap:
            return
        g = registry.gauge(
            "rmd_train_goodput_seconds",
            "wall-clock seconds attributed to each goodput class",
            ("klass",))
        for klass, seconds in snap["classes"].items():
            g.labels(klass=klass).set(seconds)
        registry.gauge(
            "rmd_train_goodput_ratio",
            "productive share of total wall clock so far",
        ).set(snap["goodput"])

    def close(self, t=None):
        """Freeze the ledger: settle the preemption window and pin the
        total so later snapshots stop growing."""
        now = time.perf_counter() if t is None else float(t)
        with self._lock:
            if self._preempt is not None:
                armed, self._preempt = self._preempt, None
                seconds = self._window_unaccounted(armed, now)
                self._charges["preempted"] += seconds
                self._accounted += seconds
            self._t_close = now
        return self.snapshot()


_active = NullLedger()


def activate(ledger=None):
    """Install ``ledger`` (or a fresh started one) as the process-wide
    active ledger; returns it."""
    global _active
    _active = ledger if ledger is not None else GoodputLedger().start()
    return _active


def deactivate():
    global _active
    _active = NullLedger()


def get():
    return _active


def observe(kind, fields):
    """Event tap called by ``Telemetry.emit``."""
    _active.observe(kind, fields)
