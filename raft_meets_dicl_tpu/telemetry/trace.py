"""Per-request tracing for the serving path.

Every admitted request carries a :class:`RequestTrace` from admission to
release; every dispatch gets a :class:`BatchTrace` linking the batch
span to its member request spans (and to the compiled program that ran
it). The marks telescope into an **exact** critical-path decomposition:

====================  ===================================================
phase                 interval
====================  ===================================================
``admission``         submit → enqueue (validate + quantize + wire-encode)
``queue``             enqueue → dispatch pull (batcher lane wait)
``batch_form``        dispatch pull → program launched (fan-in: decode
                      faults culled, pad-tile assemble, ladder pick, run)
``device``            program launched → result fetched (device + D2H)
``respond``           fetched → ticket released (crop + sticky-order
                      release)
====================  ===================================================

The phases are differences of one monotonic clock at consecutive marks,
so ``sum(phases) == total`` to float precision — a tail request always
attributes its full latency, nothing hides between phases. Completed
requests feed a bounded :class:`TraceSummary` whose :meth:`snapshot`
gives per-class p50/p99 and the slowest-decile phase breakdown the
``/statusz`` endpoint and BENCH_SERVE report serve live.

Host-side only: two ``perf_counter`` calls per mark, no jax.
"""

import itertools
import threading
import time
from collections import deque

# mark order defines the telescoping phase decomposition
MARKS = ("submit", "enqueue", "dispatch", "launched", "fetched", "released")
PHASES = ("admission", "queue", "batch_form", "device", "respond")

_req_ids = itertools.count(1)
_batch_ids = itertools.count(1)


class RequestTrace:
    """Ordered monotonic marks for one request's life; phases are the
    gaps between consecutive marks actually hit."""

    __slots__ = ("trace_id", "klass", "bucket", "batch_id", "marks")

    def __init__(self, klass="", bucket=None):
        self.trace_id = f"req-{next(_req_ids):06d}"
        self.klass = klass
        self.bucket = bucket
        self.batch_id = None
        self.marks = {}

    def mark(self, name, t=None):
        if name not in MARKS:
            raise ValueError(f"unknown trace mark {name!r} "
                             f"(one of {'/'.join(MARKS)})")
        self.marks[name] = time.perf_counter() if t is None else t
        return self

    def phases(self):
        """``{phase: seconds}`` between consecutive hit marks. With all
        marks present the values telescope: they sum to exactly
        ``released - submit``."""
        out = {}
        hit = [(m, self.marks[m]) for m in MARKS if m in self.marks]
        for (m0, t0), (_m1, t1) in zip(hit, hit[1:]):
            out[PHASES[MARKS.index(m0)]] = t1 - t0
        return out

    def total(self):
        if "submit" in self.marks and "released" in self.marks:
            return self.marks["released"] - self.marks["submit"]
        return None

    def record(self):
        """The completed-request record ``slo``/``TraceSummary``/the
        ``trace`` event all share."""
        phases = self.phases()
        return {
            "trace": self.trace_id,
            "batch": self.batch_id,
            "klass": self.klass,
            "bucket": (f"{self.bucket[0]}x{self.bucket[1]}"
                       if self.bucket else None),
            "phases": {k: round(v, 6) for k, v in phases.items()},
            "total": round(self.total() or sum(phases.values()), 6),
        }


class BatchTrace:
    """One dispatch span: which requests fanned in, on which compiled
    program (bucket/class/fingerprint)."""

    __slots__ = ("batch_id", "bucket", "klass", "size", "fill",
                 "program", "members", "t_start", "t_end")

    def __init__(self, bucket, klass, program=None):
        self.batch_id = f"batch-{next(_batch_ids):06d}"
        self.bucket = bucket
        self.klass = klass
        self.program = program
        self.size = 0
        self.fill = 0
        self.members = []
        self.t_start = time.perf_counter()
        self.t_end = None

    def link(self, request_trace):
        request_trace.batch_id = self.batch_id
        self.members.append(request_trace.trace_id)
        self.size = len(self.members)
        return request_trace

    def finish(self):
        self.t_end = time.perf_counter()
        return self

    def record(self):
        return {
            "batch": self.batch_id,
            "bucket": f"{self.bucket[0]}x{self.bucket[1]}",
            "klass": self.klass,
            "size": self.size,
            "fill": self.fill,
            "program": self.program,
            "members": list(self.members),
            "seconds": round(
                (self.t_end or time.perf_counter()) - self.t_start, 6),
        }


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class TraceSummary:
    """Bounded live aggregate of completed request records.

    Keeps the last ``capacity`` records (deque — the serve hot path adds
    one dict append per request) and answers :meth:`snapshot`: per-class
    count/p50/p99 plus the slowest-decile phase breakdown with the
    dominant phase named, so a queue-dominated tail is visible at a
    glance (``/statusz``, the obs smoke test, BENCH_SERVE columns).
    """

    def __init__(self, capacity=4096):
        self._lock = threading.Lock()
        self._records = deque(maxlen=capacity)

    def add(self, record):
        with self._lock:
            self._records.append(record)

    def __len__(self):
        with self._lock:
            return len(self._records)

    def snapshot(self):
        with self._lock:
            records = list(self._records)
        classes = {}
        for rec in records:
            classes.setdefault(rec.get("klass") or "", []).append(
                rec["total"])
        out = {"count": len(records), "classes": {}, "tail": None}
        for klass, totals in sorted(classes.items()):
            totals.sort()
            out["classes"][klass] = {
                "count": len(totals),
                "p50_ms": round(_percentile(totals, 0.50) * 1e3, 3),
                "p99_ms": round(_percentile(totals, 0.99) * 1e3, 3),
            }
        tail = self.tail(records)
        if tail is not None:
            out["tail"] = tail
        return out

    def tail(self, records=None, decile=0.9):
        """Mean phase breakdown of the slowest ``1 - decile`` fraction
        of requests (by total), with the dominant phase flagged."""
        if records is None:
            with self._lock:
                records = list(self._records)
        if not records:
            return None
        ranked = sorted(records, key=lambda r: r["total"])
        cut = max(1, len(ranked) - int(len(ranked) * decile))
        slow = ranked[-cut:]
        phases = {}
        for rec in slow:
            for name, secs in rec.get("phases", {}).items():
                phases[name] = phases.get(name, 0.0) + secs
        n = len(slow)
        mean = {k: round(v / n * 1e3, 3) for k, v in phases.items()}
        dominant = max(mean, key=mean.get) if mean else None
        return {
            "count": n,
            "total_ms": round(sum(r["total"] for r in slow) / n * 1e3, 3),
            "phases_ms": mean,
            "dominant": dominant,
            "queue_dominated": dominant == "queue",
        }
