"""Per-step trace decomposition for the training loop.

The serve plane decomposes a request's critical path with
:mod:`telemetry.trace`; this is the trainer's twin.  A
:class:`StepTrace` records one ``time.perf_counter`` timestamp per mark
on the step loop's own thread::

    start -> data -> prep -> put -> dispatched -> synced -> done

and the phases are the differences between consecutive hit marks on
that one clock, so they telescope *exactly* to the step total — no
residual, no second clock, and crucially **no host↔device sync**: the
``device`` phase is simply how long the loop blocked on the amortized
finite-check fetch (zero on the steps in between, where ``synced``
lands immediately after ``dispatched``).

========== ============================================================
phase      wall time between
========== ============================================================
data_wait  start → data: blocked on the (prefetched) input queue
host_prep  data → prep: host-side batch prep, schedules, callbacks
device_put prep → put: consumer-side transfer cost (≈0 when the
           prefetch worker already staged the batch)
dispatch   put → dispatched: the async ``step_fn`` dispatch call
device     dispatched → synced: blocked on the finite-check fetch
           (only at the amortized cadence)
interleave synced → done: optimizer/ckpt/eval interleave + inspector
========== ============================================================

:class:`StepTraceSummary` aggregates the bounded recent window (rolling
p50/p99 per phase, straggler/data-starved flags) and builds the
``steptrace`` telemetry events the loop emits at the finite-check
cadence.
"""

import time
from collections import deque

MARKS = ("start", "data", "prep", "put", "dispatched", "synced", "done")
PHASES = ("data_wait", "host_prep", "device_put", "dispatch", "device",
          "interleave")

# a step is a straggler when its total exceeds this multiple of the
# window median; the window is data-starved when the median data_wait
# share of the step exceeds this fraction
STRAGGLER_FACTOR = 2.0
STARVED_SHARE = 0.5


class StepTrace:
    """Timestamps of one training step on a single perf_counter clock."""

    __slots__ = ("step", "marks")

    def __init__(self, step=None):
        self.step = step
        self.marks = {}

    def mark(self, name, t=None):
        if name not in MARKS:
            raise ValueError(f"unknown step mark {name!r}")
        self.marks[name] = time.perf_counter() if t is None else float(t)
        return self

    def total(self):
        if "start" in self.marks and "done" in self.marks:
            return self.marks["done"] - self.marks["start"]
        return None

    def phases(self):
        """Phase durations between consecutive *hit* marks.

        Differences of one clock at consecutive marks: the phases sum
        to ``total()`` with no residual.  A phase spanning skipped
        marks is attributed to the phase named by its left mark, so
        attribution always covers the whole step.
        """
        hit = [m for m in MARKS if m in self.marks]
        out = {}
        for m0, m1 in zip(hit, hit[1:]):
            t0, t1 = self.marks[m0], self.marks[m1]
            out[PHASES[MARKS.index(m0)]] = t1 - t0
        return out

    def record(self):
        phases = self.phases()
        return {
            "step": self.step,
            "phases": {k: round(v, 6) for k, v in phases.items()},
            "total": round(self.total() or sum(phases.values()), 6),
        }


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


class StepTraceSummary:
    """Bounded rolling window of step records + the pending batch that
    has not yet been emitted as a ``steptrace`` event.

    ``add`` is append-only host work (no sync); the loop drains the
    pending batch into one event per finite-check window.
    """

    def __init__(self, capacity=512, straggler_factor=STRAGGLER_FACTOR,
                 starved_share=STARVED_SHARE):
        self.capacity = int(capacity)
        self.straggler_factor = float(straggler_factor)
        self.starved_share = float(starved_share)
        self._records = deque(maxlen=self.capacity)
        self._pending = []
        self.steps = 0

    def add(self, trace):
        rec = trace.record() if isinstance(trace, StepTrace) else dict(trace)
        self._records.append(rec)
        self._pending.append(rec)
        self.steps += 1
        return rec

    def __len__(self):
        return len(self._records)

    # -- aggregation ---------------------------------------------------------

    def snapshot(self):
        """Rolling per-phase p50/p99 (ms) over the bounded window, plus
        straggler / data-starved flags."""
        records = list(self._records)
        if not records:
            return {"count": 0, "phases": {}, "total_ms": {},
                    "straggler": False, "data_starved": False}
        by_phase = {}
        totals = []
        starved = []
        for rec in records:
            totals.append(rec["total"])
            for phase, dur in rec["phases"].items():
                by_phase.setdefault(phase, []).append(dur)
            if rec["total"] > 0:
                starved.append(rec["phases"].get("data_wait", 0.0)
                               / rec["total"])
        totals.sort()
        phases = {}
        for phase, vals in by_phase.items():
            vals.sort()
            phases[phase] = {
                "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
                "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
            }
        median_total = _percentile(totals, 0.50)
        last_total = records[-1]["total"]
        starved.sort()
        return {
            "count": len(records),
            "phases": phases,
            "total_ms": {
                "p50": round(median_total * 1e3, 3),
                "p99": round(_percentile(totals, 0.99) * 1e3, 3),
            },
            "straggler": bool(median_total > 0 and last_total
                              > self.straggler_factor * median_total),
            "data_starved": bool(starved and _percentile(
                starved, 0.50) > self.starved_share),
        }

    def drain(self):
        """Pending records since the last drain (the emit window)."""
        pending, self._pending = self._pending, []
        return pending

    def event(self, step):
        """Build the ``steptrace`` event fields for the window since the
        last emit; drains the pending batch. Returns None when the
        window is empty."""
        window = self.drain()
        if not window:
            return None
        snap = self.snapshot()
        return {
            "step": step,
            "window": len(window),
            "phases": snap["phases"],
            "total_ms": snap["total_ms"],
            "straggler": snap["straggler"],
            "data_starved": snap["data_starved"],
        }
