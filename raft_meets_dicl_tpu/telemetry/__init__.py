"""Unified telemetry: span timers, JSONL event sink, run reports.

See ``core`` for the sink/schema and ``report`` for rendering. Typical
producer usage::

    from .. import telemetry

    tele = telemetry.activate(telemetry.create(run_dir / "events.jsonl"))
    tele.emit("run_start", dir=str(run_dir))
    with tele.span("dispatch"):
        state, aux = step_fn(state, lr, *batch)
    tele.step_event(step, stage=0, epoch=0)

``RMD_TELEMETRY=0`` turns every call into a no-op (``create`` returns the
null sink and ``activate`` skips the jax.monitoring hookup).
"""

from . import (
    blackbox,
    core,
    goodput,
    metrics,
    report,
    sidecar,
    slo,
    steptrace,
    trace,
)
from .core import (
    SCHEMA,
    SCHEMA_MINOR,
    SCHEMA_VERSION,
    NewerSchema,
    NullTelemetry,
    Telemetry,
    UnknownKind,
    activate,
    create,
    deactivate,
    enabled,
    get,
    install_listeners,
    instrument_jit,
    jit_label,
    memory_snapshot,
    validate_event,
)

__all__ = [
    "blackbox", "core", "goodput", "metrics", "report", "sidecar",
    "slo", "steptrace", "trace",
    "SCHEMA", "SCHEMA_MINOR", "SCHEMA_VERSION",
    "NewerSchema", "NullTelemetry", "Telemetry", "UnknownKind",
    "activate", "create", "deactivate", "enabled", "get",
    "install_listeners", "instrument_jit", "jit_label",
    "memory_snapshot", "validate_event",
]
