"""Per-latency-class SLO tracking: rolling burn-rate windows.

Each ladder class gets a target (``RMD_SLO_FAST_MS`` /
``RMD_SLO_BALANCED_MS`` / ``RMD_SLO_QUALITY_MS``; ladderless requests
and classes without their own knob fall back to ``RMD_SLO_DEFAULT_MS``;
0 disables tracking for that class). Within a rolling window
(``RMD_SLO_WINDOW_S``) each completed request is *good* iff its
end-to-end latency met the target; the standard SRE pair follows:

- ``attainment = good / (good + bad)``
- ``burn_rate = (1 - attainment) / (1 - objective)``

with ``objective`` from ``RMD_SLO_OBJECTIVE`` (default 0.99). Burn 1.0
means the class is consuming its error budget exactly at the sustainable
rate; >1 means the window misses the objective — the telemetry report
flags it, and pairs it with the trace summary's tail decomposition so a
burning class is immediately attributable to queue vs. batch-formation
vs. device time.

Snapshots feed the ``rmd_slo_*`` gauges and periodic ``slo`` events;
everything is host-side arithmetic on a deque.
"""

import threading
import time
from collections import deque


class ClassSLO:
    """Rolling good/bad window for one latency class."""

    def __init__(self, klass, target_ms, objective=0.99, window_s=60.0):
        if target_ms <= 0:
            raise ValueError(f"target_ms must be > 0, got {target_ms}")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.klass = klass
        self.target_ms = float(target_ms)
        self.objective = float(objective)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._window = deque()  # (monotonic stamp, good)

    def record(self, total_s, now=None):
        """One completed request with end-to-end latency ``total_s``."""
        now = time.monotonic() if now is None else now
        good = total_s * 1e3 <= self.target_ms
        with self._lock:
            self._window.append((now, good))
            self._prune(now)
        return good

    def _prune(self, now):
        horizon = now - self.window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def snapshot(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune(now)
            good = sum(1 for _, g in self._window if g)
            total = len(self._window)
        bad = total - good
        attainment = good / total if total else 1.0
        burn = (1.0 - attainment) / (1.0 - self.objective)
        return {
            "klass": self.klass,
            "target_ms": self.target_ms,
            "objective": self.objective,
            "window_s": self.window_s,
            "good": good,
            "bad": bad,
            "attainment": round(attainment, 6),
            "burn_rate": round(burn, 4),
        }


def targets():
    """Configured per-class targets (ms) from the knob registry; classes
    at 0 are untracked. The empty-string class is the ladderless
    default and the fallback for classes without their own knob."""
    from ..utils import env

    return {
        "fast": env.get_float("RMD_SLO_FAST_MS"),
        "balanced": env.get_float("RMD_SLO_BALANCED_MS"),
        "quality": env.get_float("RMD_SLO_QUALITY_MS"),
        "": env.get_float("RMD_SLO_DEFAULT_MS"),
    }


class SLOTracker:
    """Per-class :class:`ClassSLO` map fed from the serve release path.

    Unconfigured classes are ignored (no target — nothing to burn).
    ``maybe_emit`` rate-limits ``slo`` events to one per class per
    ``emit_interval_s``.
    """

    def __init__(self, class_targets=None, objective=None, window_s=None,
                 emit_interval_s=None):
        from ..utils import env

        if class_targets is None:
            class_targets = targets()
        if objective is None:
            objective = env.get_float("RMD_SLO_OBJECTIVE")
        if window_s is None:
            window_s = env.get_float("RMD_SLO_WINDOW_S")
        if emit_interval_s is None:
            emit_interval_s = max(1.0, window_s / 6.0)
        self.emit_interval_s = float(emit_interval_s)
        default = class_targets.get("", 0.0)
        self._slos = {}
        for klass, target in class_targets.items():
            target = target or default
            if target and target > 0:
                self._slos[klass] = ClassSLO(
                    klass, target, objective=objective, window_s=window_s)
        self._lock = threading.Lock()
        self._last_emit = {}

    def __bool__(self):
        return bool(self._slos)

    def classes(self):
        return sorted(self._slos)

    def record(self, klass, total_s, now=None):
        slo = self._slos.get(klass)
        if slo is None:
            return None
        return slo.record(total_s, now=now)

    def snapshot(self, now=None):
        return {k: s.snapshot(now=now)
                for k, s in sorted(self._slos.items())}

    def maybe_emit(self, sink, now=None):
        """Emit one ``slo`` event per class whose interval elapsed."""
        now = time.monotonic() if now is None else now
        emitted = []
        for klass, slo in self._slos.items():
            with self._lock:
                last = self._last_emit.get(klass)
                if last is not None and now - last < self.emit_interval_s:
                    continue
                self._last_emit[klass] = now
            snap = slo.snapshot(now=now)
            sink.emit("slo", **snap)
            emitted.append(snap)
        return emitted
