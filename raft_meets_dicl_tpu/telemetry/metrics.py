"""In-process metrics registry with Prometheus text exposition.

The serve path needs live scrapeable counters/gauges/histograms without
adding a dependency, so this is the minimal client: a registry of typed
metrics, optional label dimensions (children keyed by label values), and
:meth:`MetricsRegistry.render` producing the Prometheus text format
(``# HELP`` / ``# TYPE`` / samples, histogram ``_bucket``/``_sum``/
``_count`` with cumulative ``le`` buckets).

Conventions enforced at registration (and statically by the graftlint
``telemetry-unregistered-kind`` rule): every metric name matches
``rmd_<subsystem>_<name>`` — lower-snake, at least three segments, the
``rmd_`` prefix namespacing the project the way ``RMD_*`` does knobs.
Counters additionally end in ``_total`` per Prometheus practice.

Thread-safe; increments are a lock + float add, cheap enough for the
scheduler hot path.
"""

import re
import threading

NAME_RE = re.compile(r"^rmd_[a-z0-9]+(?:_[a-z0-9]+)+$")
LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# latency-oriented default buckets (seconds), 1ms .. 10s
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _escape(value):
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(value):
    if value == int(value):
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Shared parent bookkeeping: labeled children or a single bare
    child, rendered under one HELP/TYPE header."""

    typ = "untyped"

    def __init__(self, name, doc, labelnames=()):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match rmd_<subsystem>_<name> "
                f"(lower-snake, rmd_ prefix, >= 3 segments)")
        for ln in labelnames:
            if not LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} for {name}")
        self.name = name
        self.doc = doc
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}
        if not self.labelnames:
            self._children[()] = self._child()

    def labels(self, **labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._child()
        return child

    def _bare(self):
        if self.labelnames:
            raise ValueError(f"{self.name} needs .labels(...)")
        return self._children[()]

    def _samples(self):
        """Yield (suffix, labelpairs, value) for every sample line."""
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            pairs = list(zip(self.labelnames, key))
            yield from child.samples(pairs)

    def render(self):
        lines = [f"# HELP {self.name} {_escape(self.doc)}",
                 f"# TYPE {self.name} {self.typ}"]
        for suffix, pairs, value in self._samples():
            label_s = ""
            if pairs:
                label_s = "{" + ",".join(
                    f'{k}="{_escape(v)}"' for k, v in pairs) + "}"
            lines.append(f"{self.name}{suffix}{label_s} {_fmt(value)}")
        return lines


class _CounterChild:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def samples(self, pairs):
        yield "", pairs, self.value


class Counter(_Metric):
    typ = "counter"

    def __init__(self, name, doc, labelnames=()):
        if not name.endswith("_total"):
            raise ValueError(f"counter {name!r} must end in _total")
        super().__init__(name, doc, labelnames)

    def _child(self):
        return _CounterChild()

    def inc(self, amount=1.0):
        self._bare().inc(amount)

    @property
    def value(self):
        return self._bare().value


class _GaugeChild:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        with self._lock:
            return self._value

    def samples(self, pairs):
        yield "", pairs, self.value


class Gauge(_Metric):
    typ = "gauge"

    def _child(self):
        return _GaugeChild()

    def set(self, value):
        self._bare().set(value)

    def inc(self, amount=1.0):
        self._bare().inc(amount)

    def dec(self, amount=1.0):
        self._bare().dec(amount)

    @property
    def value(self):
        return self._bare().value


class _HistogramChild:
    def __init__(self, buckets):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self._buckets):
                if value <= bound:
                    self._counts[i] += 1
                    break  # per-bucket counts; render cumulates

    def samples(self, pairs):
        with self._lock:
            counts, total, s = list(self._counts), self._count, self._sum
        cum = 0
        for bound, n in zip(self._buckets, counts):
            cum += n
            yield "_bucket", pairs + [("le", _fmt(bound))], cum
        yield "_bucket", pairs + [("le", "+Inf")], total
        yield "_sum", pairs, s
        yield "_count", pairs, total


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, doc, labelnames=(), buckets=DEFAULT_BUCKETS):
        self._buckets = tuple(sorted(float(b) for b in buckets))
        if not self._buckets:
            raise ValueError(f"histogram {name!r} needs buckets")
        super().__init__(name, doc, labelnames)

    def _child(self):
        return _HistogramChild(self._buckets)

    def observe(self, value):
        self._bare().observe(value)


class MetricsRegistry:
    """Name-keyed collection of metrics; re-registering an existing
    name with the same type returns the existing metric (instrumentation
    points don't coordinate creation order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _register(self, cls, name, doc, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or \
                        existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}"
                        f"{existing.labelnames}")
                return existing
            metric = cls(name, doc, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name, doc, labelnames=()):
        return self._register(Counter, name, doc, labelnames)

    def gauge(self, name, doc, labelnames=()):
        return self._register(Gauge, name, doc, labelnames)

    def histogram(self, name, doc, labelnames=(), buckets=DEFAULT_BUCKETS):
        return self._register(Histogram, name, doc, labelnames,
                              buckets=buckets)

    def get_metric(self, name):
        with self._lock:
            return self._metrics.get(name)

    def render(self):
        """The full Prometheus text exposition (text/plain; version
        0.0.4), metrics in name order."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")


_default = MetricsRegistry()


def registry():
    """The process-default registry (serve instrumentation target)."""
    return _default


def reset():
    """Replace the process-default registry (test isolation)."""
    global _default
    _default = MetricsRegistry()
    return _default


def parse_text(text):
    """Parse Prometheus text exposition into ``{name: {labelset: value}}``
    where ``labelset`` is a sorted tuple of ``(label, value)`` pairs.

    Not a general-purpose parser — just enough for tests and the obs
    smoke check to assert a scrape round-trips.
    """
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{(.*)\})?\s+(\S+)$", line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels_s, value_s = m.groups()
        pairs = []
        if labels_s:
            for part in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]'
                                   r'|\\.)*)"', labels_s):
                pairs.append(part)
        out.setdefault(name, {})[tuple(sorted(pairs))] = float(value_s)
    return out
