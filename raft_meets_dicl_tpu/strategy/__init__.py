"""Training strategy layer: specs, runtime, checkpoints, inspector."""

from . import checkpoint, config, inspector, spec, training
from .checkpoint import (
    Checkpoint, CheckpointCorrupt, CheckpointManager, find_auto_resume,
)
from .config import load, load_stage
from .inspector import Inspector
from .spec import Stage, Strategy
from .training import NonFinitePolicy, TrainingContext

__all__ = [
    "checkpoint", "config", "inspector", "spec", "training",
    "Checkpoint", "CheckpointCorrupt", "CheckpointManager", "Inspector",
    "NonFinitePolicy", "Stage", "Strategy", "TrainingContext",
    "find_auto_resume", "load", "load_stage",
]
