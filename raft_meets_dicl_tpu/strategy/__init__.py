"""Training strategy layer: specs, runtime, checkpoints, inspector."""

from . import checkpoint, config, inspector, spec, training
from .checkpoint import Checkpoint, CheckpointManager
from .config import load, load_stage
from .inspector import Inspector
from .spec import Stage, Strategy
from .training import TrainingContext

__all__ = [
    "checkpoint", "config", "inspector", "spec", "training",
    "Checkpoint", "CheckpointManager", "Inspector", "Stage", "Strategy",
    "TrainingContext", "load", "load_stage",
]
