"""Training strategy specification: stages, optimizers, schedulers, gradients.

Config-compatible with the reference (src/strategy/spec.py) — same YAML
surface (``adam``/``adam-w``/``sgd`` with torch-style parameter names,
``one-cycle``/``multi-step`` schedulers with expression-evaluated
parameters, gradient accumulate/clip/scaler) — but built on optax:

- the optimizer spec builds an optax gradient-transform chain
  (torch ``Adam(weight_decay=...)``'s L2-into-grad semantics map to
  ``add_decayed_weights`` *before* ``scale_by_adam``; ``adam-w`` maps to
  decay *after*),
- gradient clipping is a transform in that chain,
- gradient accumulation wraps the chain in ``optax.MultiSteps``,
- learning-rate schedulers are small host-side stateful objects (their
  state checkpoints like torch schedulers); the current LR is injected
  into the jitted step through ``optax.inject_hyperparams``,
- the AMP ``GradScaler`` spec is kept for config parity but builds a no-op
  state: bf16 on TPU needs no loss scaling.
"""

from typing import List, Optional

import numpy as np
import optax

from .. import data, utils


class DataSpec:
    @classmethod
    def from_config(cls, path, cfg):
        return cls(
            source=data.load(path, cfg["source"]),
            epochs=int(cfg.get("epochs", 1)),
            batch_size=int(cfg.get("batch-size", 1)),
            drop_last=bool(cfg.get("drop-last", True)),
            shuffle=bool(cfg.get("shuffle", True)),
        )

    def __init__(self, source, epochs, batch_size, drop_last=True, shuffle=True):
        self.source = source
        self.epochs = epochs
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def get_config(self):
        return {
            "source": self.source.get_config(),
            "epochs": self.epochs,
            "batch-size": self.batch_size,
            "drop-last": self.drop_last,
            "shuffle": self.shuffle,
        }


class ValidationSpec:
    @classmethod
    def from_config(cls, path, cfg):
        if cfg is None:
            return None

        return cls(
            name=cfg.get("name", "default"),
            source=data.load(path, cfg["source"]),
            batch_size=int(cfg.get("batch-size", 1)),
            images=set(cfg.get("images", {})),
        )

    def __init__(self, name, source, batch_size, images):
        self.name = name
        self.source = source
        self.batch_size = batch_size
        self.images = images

    def get_config(self):
        return {
            "name": self.name,
            "source": self.source.get_config(),
            "batch-size": self.batch_size,
            "images": list(self.images),
        }


class OptimizerSpec:
    """torch-style optimizer config → optax transform chain.

    Parameter-name translation (lr, betas, eps, weight_decay, momentum)
    happens here so reference configs work verbatim.
    """

    def __init__(self, type, parameters={}):
        self.type = type
        self.parameters = dict(parameters)

    @classmethod
    def from_config(cls, cfg):
        return cls(cfg["type"], cfg.get("parameters", {}))

    def get_config(self):
        return {"type": self.type, "parameters": self.parameters}

    def build_transform(self):
        """The core optimizer as an optax transform WITHOUT the lr scale.

        Returns ``(transform, base_lr)``. The train step multiplies the
        produced updates by ``-lr`` itself, so host-side stateful schedulers
        can drive the rate without rebuilding the optimizer state (the
        resumable analog of torch schedulers mutating ``optimizer.lr``).
        """
        p = dict(self.parameters)
        lr = float(p.pop("lr", 1e-3))

        if self.type == "adam":
            b1, b2 = p.pop("betas", (0.9, 0.999))
            eps = float(p.pop("eps", 1e-8))
            wd = float(p.pop("weight_decay", 0.0))

            steps = []
            if wd:
                # torch Adam folds L2 into the gradient before moments
                steps.append(optax.add_decayed_weights(wd))
            steps.append(optax.scale_by_adam(b1=b1, b2=b2, eps=eps))
            tx = optax.chain(*steps)

        elif self.type == "adam-w":
            b1, b2 = p.pop("betas", (0.9, 0.999))
            eps = float(p.pop("eps", 1e-8))
            wd = float(p.pop("weight_decay", 1e-2))

            tx = optax.chain(
                optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
                optax.add_decayed_weights(wd),
            )

        elif self.type == "sgd":
            momentum = float(p.pop("momentum", 0.0))
            wd = float(p.pop("weight_decay", 0.0))
            nesterov = bool(p.pop("nesterov", False))

            steps = []
            if wd:
                steps.append(optax.add_decayed_weights(wd))
            if momentum:
                steps.append(optax.trace(decay=momentum, nesterov=nesterov))
            tx = optax.chain(*steps) if steps else optax.identity()

        else:
            raise ValueError(f"unknown optimizer type '{self.type}'")

        if p:
            raise ValueError(f"unsupported optimizer parameters: {sorted(p)}")

        return tx, lr

    def build(self, gradient=None):
        """Full per-stage transform: clip → optimizer core (→ MultiSteps).

        Returns ``(tx, base_lr)``; ``gradient`` is the stage GradientSpec.
        """
        core, lr = self.build_transform()

        steps = []
        if gradient is not None and gradient.clip is not None:
            steps.append(gradient.clip.build_transform())
        steps.append(core)
        tx = optax.chain(*steps)

        if gradient is not None and gradient.accumulate > 1:
            tx = optax.MultiSteps(tx, every_k_schedule=gradient.accumulate)

        return tx, lr


class ClipGradient:
    type = None

    @classmethod
    def from_config(cls, cfg):
        if cfg is None:
            return None

        types = {c.type: c for c in (ClipGradientNorm, ClipGradientValue)}
        return types[cfg["type"]]._from_config(cfg)

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid gradient clip type '{cfg['type']}', expected '{cls.type}'"
            )

    def get_config(self):
        raise NotImplementedError

    def build_transform(self):
        raise NotImplementedError


class ClipGradientNorm(ClipGradient):
    """Clip by global gradient norm (any ord; l2 uses the optax builtin)."""

    type = "norm"

    @classmethod
    def _from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg["value"], float(cfg.get("ord", 2)))

    def __init__(self, value, ord=2.0):
        self.value = value
        self.ord = ord

    def get_config(self):
        ord_ = self.ord if self.ord not in (np.inf, -np.inf) else str(self.ord)
        return {"type": self.type, "value": self.value, "ord": ord_}

    def build_transform(self):
        if self.ord == 2.0:
            return optax.clip_by_global_norm(self.value)

        value, ord_ = self.value, self.ord

        def clip_by_ord(updates, state, params=None):
            import jax
            import jax.numpy as jnp

            flat = jnp.concatenate(
                [jnp.abs(x).ravel() for x in jax.tree.leaves(updates)]
            )
            norm = jnp.linalg.norm(flat, ord=ord_)
            scale = jnp.minimum(1.0, value / jnp.maximum(norm, 1e-12))
            return jax.tree.map(lambda x: x * scale, updates), state

        return optax.GradientTransformation(lambda params: optax.EmptyState(), clip_by_ord)


class ClipGradientValue(ClipGradient):
    type = "value"

    @classmethod
    def _from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(float(cfg["value"]))

    def __init__(self, value):
        self.value = value

    def get_config(self):
        return {"type": self.type, "value": self.value}

    def build_transform(self):
        return optax.clip(self.value)


class GradientScalerSpec:
    """AMP GradScaler config, kept for parity; a no-op on TPU (bf16)."""

    @classmethod
    def from_config(cls, cfg):
        if cfg is None:
            return cls(enabled=False)

        return cls(
            enabled=bool(cfg.get("enabled", True)),
            init_scale=float(cfg.get("init-scale", 65536.0)),
            growth_factor=float(cfg.get("growth-factor", 2.0)),
            backoff_factor=float(cfg.get("backoff-factor", 0.5)),
            growth_interval=int(cfg.get("growth-interval", 2000)),
        )

    def __init__(self, enabled=False, init_scale=65536.0, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000):
        self.enabled = enabled
        self.init_scale = init_scale
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval

    def get_config(self):
        return {
            "enabled": self.enabled,
            "init-scale": self.init_scale,
            "growth-factor": self.growth_factor,
            "backoff-factor": self.backoff_factor,
            "growth-interval": self.growth_interval,
        }

    def build(self):
        # state kept so checkpoints round-trip the scaler slot like the
        # reference; no loss scaling happens on TPU
        return {"enabled": self.enabled, "scale": self.init_scale}


class GradientSpec:
    @classmethod
    def from_config(cls, cfg):
        return cls(
            accumulate=int(cfg.get("accumulate", 1)),
            clip=ClipGradient.from_config(cfg.get("clip")),
            scaler=GradientScalerSpec.from_config(cfg.get("scaler")),
        )

    def __init__(self, accumulate=1, clip=None, scaler=None):
        if accumulate < 1:
            raise ValueError(f"invalid value for GradientSpec.accumulate: {accumulate}")

        self.accumulate = accumulate
        self.clip = clip
        self.scaler = scaler if scaler is not None else GradientScalerSpec()

    def get_config(self):
        return {
            "accumulate": self.accumulate,
            "clip": self.clip.get_config() if self.clip is not None else None,
            "scaler": self.scaler.get_config(),
        }


# -- learning-rate schedulers ----------------------------------------------


class LrScheduler:
    """Host-side stateful scheduler with torch-like step semantics.

    ``lr()`` returns the rate for the *next* optimizer update; ``step()``
    advances. State round-trips via ``state_dict``/``load_state_dict`` for
    checkpointing.
    """

    def __init__(self, base_lr):
        self.base_lr = base_lr
        self.last_step = 0

    def lr(self):
        raise NotImplementedError

    def step(self):
        self.last_step += 1

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, state):
        self.last_step = int(state["last_step"])


class OneCycleLr(LrScheduler):
    """torch OneCycleLR: warmup to max_lr, anneal to max_lr/div/final_div."""

    def __init__(self, base_lr, max_lr, total_steps, pct_start=0.3,
                 anneal_strategy="cos", div_factor=25.0, final_div_factor=1e4,
                 cycle_momentum=True, base_momentum=0.85, max_momentum=0.95,
                 three_phase=False):
        super().__init__(base_lr)

        if three_phase:
            raise NotImplementedError("three_phase one-cycle is not supported")

        self.max_lr = float(max_lr)
        self.total_steps = int(total_steps)
        self.pct_start = float(pct_start)
        self.anneal_strategy = anneal_strategy
        self.div_factor = float(div_factor)
        self.final_div_factor = float(final_div_factor)
        # momentum cycling is accepted for config parity but not applied
        self.cycle_momentum = cycle_momentum

        self.initial_lr = self.max_lr / self.div_factor
        self.min_lr = self.initial_lr / self.final_div_factor

    def _anneal(self, start, end, pct):
        if self.anneal_strategy == "linear":
            return start + (end - start) * pct
        # 'cos'
        return end + (start - end) / 2.0 * (1.0 + np.cos(np.pi * pct))

    def lr(self):
        up_steps = float(self.pct_start * self.total_steps) - 1.0
        down_steps = float(self.total_steps - up_steps) - 1.0

        step = min(self.last_step, self.total_steps - 1)
        if step <= up_steps:
            return self._anneal(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        return self._anneal(
            self.max_lr, self.min_lr, (step - up_steps) / max(down_steps, 1)
        )


class MultiStepLr(LrScheduler):
    """torch MultiStepLR: multiply by gamma at each milestone."""

    def __init__(self, base_lr, milestones, gamma=0.1):
        super().__init__(base_lr)
        self.milestones = sorted(int(m) for m in milestones)
        self.gamma = float(gamma)

    def lr(self):
        passed = sum(1 for m in self.milestones if m <= self.last_step)
        return self.base_lr * self.gamma**passed


class SchedulerSpec:
    """Typed scheduler config with expression-evaluated parameters.

    Expressions may reference ``n_samples``, ``n_batches``, ``n_epochs``,
    ``n_accum``, ``batch_size`` (reference src/strategy/training.py:158-164).
    """

    _TYPES = {"one-cycle": OneCycleLr, "multi-step": MultiStepLr}

    @classmethod
    def from_config(cls, cfg):
        return cls(cfg["type"], cfg.get("parameters", {}))

    def __init__(self, type, parameters={}):
        if type not in self._TYPES:
            raise ValueError(f"unknown scheduler type '{type}'")
        self.type = type
        self.parameters = dict(parameters)

    def get_config(self):
        return {"type": self.type, "parameters": self.parameters}

    def _eval_param(self, value, vars):
        if isinstance(value, dict):
            return {k: self._eval_param(v, vars) for k, v in value.items()}
        if isinstance(value, (tuple, list)):
            return [self._eval_param(v, vars) for v in value]
        if not isinstance(value, str):
            return value
        try:
            return utils.expr.eval_math_expr(value, vars)
        except (TypeError, ValueError, KeyError, IndexError):
            # not an expression (e.g. 'linear', 'cos') — pass through
            return value

    def build(self, base_lr, variables):
        params = {k: self._eval_param(v, variables) for k, v in self.parameters.items()}

        if self.type == "one-cycle":
            max_lr = params.pop("max_lr", base_lr)
            return OneCycleLr(base_lr, max_lr, **params)
        return MultiStepLr(base_lr, **params)


class MultiSchedulerSpec:
    """Instance-level (per optimizer update) + epoch-level scheduler lists."""

    @classmethod
    def from_config(cls, cfg):
        return cls(
            instance=[SchedulerSpec.from_config(c) for c in cfg.get("instance", [])],
            epoch=[SchedulerSpec.from_config(c) for c in cfg.get("epoch", [])],
        )

    def __init__(self, instance=[], epoch=[]):
        self.instance = list(instance)
        self.epoch = list(epoch)

    def get_config(self):
        return {
            "instance": [s.get_config() for s in self.instance],
            "epoch": [s.get_config() for s in self.epoch],
        }

    def build(self, base_lr, variables):
        return (
            [s.build(base_lr, variables) for s in self.instance],
            [s.build(base_lr, variables) for s in self.epoch],
        )


# -- stage / strategy -------------------------------------------------------


class Stage:
    @classmethod
    def from_config(cls, path, cfg):
        valid = cfg.get("validation", [])
        if isinstance(valid, dict):
            valid = [valid]

        return cls(
            name=cfg["name"],
            id=cfg["id"],
            data=DataSpec.from_config(path, cfg["data"]),
            validation=[ValidationSpec.from_config(path, v) for v in valid],
            optimizer=OptimizerSpec.from_config(cfg["optimizer"]),
            model_args=cfg.get("model", {}).get("arguments", {}),
            model_on_epoch_args=cfg.get("model", {}).get("on-epoch", {}),
            model_on_stage_args=cfg.get("model", {}).get("on-stage", {}),
            loss_args=cfg.get("loss", {}).get("arguments", {}),
            gradient=GradientSpec.from_config(cfg.get("gradient", {})),
            scheduler=MultiSchedulerSpec.from_config(cfg.get("lr-scheduler", {})),
            loader_args=cfg.get("loader", {}),
        )

    def __init__(self, name, id, data, validation, optimizer, model_args={},
                 model_on_epoch_args={}, model_on_stage_args={}, loss_args={},
                 gradient=None, scheduler=None, loader_args={}):
        self.name = name
        self.id = id
        self.data = data
        self.validation = validation
        self.optimizer = optimizer
        self.model_args = dict(model_args)
        self.model_on_epoch_args = dict(model_on_epoch_args)
        self.model_on_stage_args = dict(model_on_stage_args)
        self.loss_args = dict(loss_args)
        self.gradient = gradient if gradient is not None else GradientSpec()
        self.scheduler = scheduler if scheduler is not None else MultiSchedulerSpec()
        self.loader_args = dict(loader_args)
        self.index = 0  # set by the training loop

    def get_config(self):
        return {
            "name": self.name,
            "id": self.id,
            "data": self.data.get_config(),
            "validation": [v.get_config() for v in self.validation],
            "optimizer": self.optimizer.get_config(),
            "model": {
                "arguments": self.model_args,
                "on-epoch": self.model_on_epoch_args,
                "on-stage": self.model_on_stage_args,
            },
            "loss": {"arguments": self.loss_args},
            "gradient": self.gradient.get_config(),
            "lr-scheduler": self.scheduler.get_config(),
            "loader": self.loader_args,
        }


class Strategy:
    """mode ``best`` restores the best checkpoint of the previous stage at
    each stage start; ``continuous`` keeps training the live weights."""

    mode: str
    stages: List[Stage]

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as strategy_config

        mode = cfg.get("mode", "best")
        if mode not in ("best", "continuous"):
            raise ValueError("invalid value for mode, expected one of ['best', 'continuous']")

        stages = [strategy_config.load_stage(path, c) for c in cfg["stages"]]
        return cls(mode, stages)

    def __init__(self, mode, stages):
        self.mode = mode
        self.stages = stages

    def get_config(self):
        return {"mode": self.mode, "stages": [s.get_config() for s in self.stages]}
