"""Inspector callback protocol — the trainer is observable, observability
lives elsewhere (reference src/strategy/inspector.py:1-30)."""


class Inspector:
    def setup(self, log, ctx):
        pass

    def wants_host_images(self, step):
        """Whether ``on_batch``/hooks will consume pixel values at this
        step. Under a wire-format input pipeline the trainer only decodes
        host images to normalized f32 when this returns True."""
        return False

    def on_step_start(self, log, ctx, stage, epoch, i):
        pass

    def on_step_end(self, log, ctx, stage, epoch, i):
        pass

    def on_batch_start(self, log, ctx, stage, epoch, i, img1, img2, target,
                       valid, meta):
        pass

    def on_batch(self, log, ctx, stage, epoch, i, img1, img2, target, valid,
                 meta, result, loss):
        pass

    def on_epoch_start(self, log, ctx, stage, epoch):
        pass

    def on_epoch(self, log, ctx, stage, epoch):
        pass

    def on_stage_start(self, log, ctx, stage):
        pass

    def on_stage(self, log, ctx, stage):
        pass
