"""Checkpointing: single-file msgpack checkpoints + retention manager.

Same logical schema as the reference (src/strategy/checkpoint.py:38-121):
``{model, iteration{stage,epoch,step}, metrics, state{model, optimizer,
scaler, lr-scheduler{instance,epoch}}, metadata}`` — serialized with flax
msgpack instead of torch.save. ``state.model`` holds the flax variables
``{params, batch_stats}``; ``state.optimizer`` holds the optax state as a
flax state-dict (restored against a freshly built optimizer's structure).

Retention (name-templated paths with metric values, best-by-expression and
keep-latest trimming) matches the reference manager exactly.

Integrity: v2 files (``RMDT2``) carry a CRC32 of the payload right after
the magic, verified on every load — a bit flip or truncation raises
:class:`CheckpointCorrupt` instead of a msgpack error deep in restore.
Corrupt files are quarantined (renamed ``*.corrupt``) by the recovery
paths (``CheckpointManager.load_valid``, :func:`find_auto_resume`) which
fall back to the next-newest valid entry. v1 files (``RMDT1``, no
checksum) still load.
"""

import concurrent.futures
import os
import re
import struct
import zlib
from collections import defaultdict
from dataclasses import dataclass
from datetime import datetime
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np
from flax import serialization

from .. import utils
from ..testing import faults

_MAGIC_V1 = b"RMDT1\n"   # legacy: no checksum
_MAGIC = b"RMDT2\n"      # current: 4-byte LE CRC32 of payload after magic
_CRC_LEN = 4


class CheckpointCorrupt(ValueError):
    """A checkpoint file failed integrity verification (bad magic, CRC
    mismatch, truncation, or msgpack decode failure)."""


def quarantine(path):
    """Rename a corrupt checkpoint out of the discovery namespace.

    ``foo.ckpt`` becomes ``foo.ckpt.corrupt`` (numbered if that exists)
    so retention scans and auto-resume stop considering it while the
    bytes stay on disk for a post-mortem. Emits a ``quarantine``
    telemetry event; returns the new path (or None if the rename lost a
    race with another process)."""
    from .. import telemetry

    path = Path(path)
    dst = path.with_name(path.name + ".corrupt")
    n = 1
    while dst.exists():
        dst = path.with_name(f"{path.name}.corrupt{n}")
        n += 1
    try:
        os.replace(path, dst)
    except OSError:
        return None
    telemetry.get().emit("quarantine", path=str(path), moved_to=str(dst))
    return dst

# single background writer shared by all managers: serializing two
# checkpoints concurrently would just thrash memory, and one ordered lane
# keeps writes in creation order. Threads are non-daemon, so a clean
# interpreter exit waits for in-flight writes instead of truncating them.
_WRITER: Optional[concurrent.futures.ThreadPoolExecutor] = None

# process-wide checkpoint-save ordinal, consumed by the
# ``corrupt_checkpoint@nth=K`` fault directive (testing.faults)
_SAVES = 0


def _writer():
    global _WRITER
    if _WRITER is None:
        _WRITER = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="chkpt-write")
    return _WRITER


def _write_atomic(path, payload):
    """Write via tmp file + rename so a reader (or a crash mid-write)
    never sees a truncated checkpoint."""
    path = Path(path)
    tmp = path.with_name(f".{path.name}.tmp{os.getpid()}")
    tmp.write_bytes(payload)
    os.replace(tmp, path)


@dataclass
class Iteration:
    stage: int
    epoch: Optional[int]
    step: int

    @classmethod
    def from_dict(cls, cfg):
        return cls(stage=cfg["stage"], epoch=cfg.get("epoch"), step=cfg["step"])

    def to_dict(self):
        return {"stage": self.stage, "epoch": self.epoch, "step": self.step}


@dataclass
class State:
    model: Any          # {'params': ..., 'batch_stats': ...}
    optimizer: Any      # optax state as flax state-dict
    scaler: Any
    lr_sched_inst: List[Any]
    lr_sched_epoch: List[Any]

    @classmethod
    def from_dict(cls, cfg):
        return cls(
            model=cfg["model"],
            optimizer=cfg["optimizer"],
            scaler=cfg["scaler"],
            lr_sched_inst=cfg["lr-scheduler"]["instance"],
            lr_sched_epoch=cfg["lr-scheduler"]["epoch"],
        )

    def to_dict(self):
        return {
            "model": self.model,
            "optimizer": self.optimizer,
            "scaler": self.scaler,
            "lr-scheduler": {
                "instance": self.lr_sched_inst,
                "epoch": self.lr_sched_epoch,
            },
        }


def _remap_legacy_model_state(target, state):
    """Migrate pre-round-5 ``raft/fs`` checkpoints at load time.

    Round 5 hoisted ``Up8Network`` out of the GRU scan body
    (models/impls/raft_fs.py), moving its params from the scanned step
    subtree (``ScanCheckpoint_FsStep_0``, or ``Scan_FsStep_0`` with
    ``remat: false``) to top-level ``Up8Network_0``. Old checkpoints keep
    the scan-body layout and would fail ``from_state_dict`` against the
    new structure. The rule fires only when the structures prove the
    migration applies: the restore *target* expects a top-level
    ``Up8Network_0`` the stored state lacks, and the stored scan body has
    one to give. Everything else passes through untouched.
    """
    from collections.abc import Mapping

    if not isinstance(target, Mapping) or not isinstance(state, Mapping):
        return state
    params_t = target.get("params")
    params_s = state.get("params")
    if not isinstance(params_t, Mapping) or not isinstance(params_s, Mapping):
        return state
    if "Up8Network_0" not in params_t or "Up8Network_0" in params_s:
        return state

    for scan_body in ("ScanCheckpoint_FsStep_0", "Scan_FsStep_0"):
        if (isinstance(params_s.get(scan_body), Mapping)
                and "Up8Network_0" in params_s[scan_body]):
            body = dict(params_s[scan_body])
            params_s = dict(params_s)
            params_s["Up8Network_0"] = body.pop("Up8Network_0")
            params_s[scan_body] = body
            return dict(state) | {"params": params_s}

    return state


def _to_host(tree):
    """Device arrays → numpy for serialization.

    Only array leaves are converted: flax msgpack can restore numpy numeric
    arrays but chokes on numpy-ified str/None leaves (``np.str_`` round-trips
    as dtype ``str256``), so non-array leaves pass through untouched.
    """
    import jax

    def conv(leaf):
        if isinstance(leaf, (np.str_, np.bytes_)):
            return leaf.item()
        if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
            return np.asarray(leaf)
        return leaf

    return jax.tree.map(conv, tree)


@dataclass
class Checkpoint:
    model: str
    iteration: Iteration
    metrics: Optional[Dict[str, float]]
    state: State
    metadata: Dict[str, Any]

    @classmethod
    def from_dict(cls, cfg):
        return cls(
            model=cfg["model"],
            iteration=Iteration.from_dict(cfg["iteration"]),
            metrics=cfg["metrics"],
            state=State.from_dict(cfg["state"]),
            metadata=cfg.get("metadata", {}),
        )

    @classmethod
    def load(cls, path, strip_prefix=None):
        raw = Path(path).read_bytes()
        if raw.startswith(_MAGIC):
            header_len = len(_MAGIC) + _CRC_LEN
            if len(raw) < header_len:
                raise CheckpointCorrupt(f"truncated checkpoint: {path}")
            (crc,) = struct.unpack("<I", raw[len(_MAGIC):header_len])
            payload = raw[header_len:]
            if zlib.crc32(payload) != crc:
                raise CheckpointCorrupt(
                    f"checkpoint checksum mismatch (bit rot or truncated "
                    f"write): {path}")
        elif raw.startswith(_MAGIC_V1):
            payload = raw[len(_MAGIC_V1):]  # legacy, no checksum
        else:
            raise CheckpointCorrupt(f"not a checkpoint file: {path}")

        try:
            cfg = serialization.msgpack_restore(payload)
        except Exception as e:  # noqa: BLE001 - decoder errors vary by impl
            raise CheckpointCorrupt(
                f"checkpoint payload undecodable: {path} ({e})") from e

        if strip_prefix:
            # pytree-key analog of the reference's module.-prefix stripping
            cfg["state"]["model"] = {
                k.removeprefix(strip_prefix): v
                for k, v in cfg["state"]["model"].items()
            }

        return cls.from_dict(cfg)

    def to_dict(self):
        return {
            "model": self.model,
            "iteration": self.iteration.to_dict(),
            "metrics": self.metrics,
            "state": self.state.to_dict(),
            "metadata": self.metadata,
        }

    def to_entry(self, path):
        return CheckpointEntry(
            self.model,
            self.iteration.stage,
            self.iteration.epoch,
            self.iteration.step,
            self.metrics,
            path,
        )

    def save(self, path, background=False):
        """Serialize to ``path`` (atomically, via tmp file + rename).

        ``background=True`` splits the work at the host boundary: the
        device→host snapshot (``_to_host`` — the part that must see a
        consistent state) runs synchronously, then the msgpack encode and
        file write happen on the shared background writer thread, and a
        ``concurrent.futures.Future`` (resolving to the seconds the
        background half took) is returned — training no longer stalls for
        the full serialize+write. Synchronous saves return None.
        """
        state = _to_host(self.to_dict())

        def write():
            import time

            t0 = time.perf_counter()
            payload = serialization.msgpack_serialize(state)
            crc = struct.pack("<I", zlib.crc32(payload))
            _write_atomic(path, _MAGIC + crc + payload)
            if faults.active():
                global _SAVES
                _SAVES += 1
                if faults.fire("corrupt_checkpoint", nth=_SAVES) is not None:
                    faults.corrupt_file(path)
            return time.perf_counter() - t0

        if not background:
            write()
            return None
        return _writer().submit(write)

    def apply(self, variables=None, opt_state=None, scaler=None,
              lr_sched_inst=(), lr_sched_epoch=()):
        """Restore state in place-of: returns (variables, opt_state, scaler).

        ``variables``/``opt_state`` act as structure targets (flax
        ``from_state_dict``); schedulers are restored in place. Pass None to
        skip a slot.
        """
        out_vars, out_opt, out_scaler = variables, opt_state, scaler

        if variables is not None:
            model_state = _remap_legacy_model_state(variables, self.state.model)
            out_vars = serialization.from_state_dict(variables, model_state)
        if opt_state is not None:
            out_opt = serialization.from_state_dict(opt_state, self.state.optimizer)
        if scaler is not None:
            out_scaler = dict(self.state.scaler)

        for sched, state in zip(lr_sched_inst, self.state.lr_sched_inst):
            sched.load_state_dict(state)
        for sched, state in zip(lr_sched_epoch, self.state.lr_sched_epoch):
            sched.load_state_dict(state)

        return out_vars, out_opt, out_scaler


@dataclass
class CheckpointEntry:
    model: str
    idx_stage: int
    idx_epoch: Optional[int]
    idx_step: int
    metrics: Optional[Dict[str, float]]
    path: Optional[Path]
    # in-flight background write (strategy.checkpoint.Checkpoint.save with
    # background=True); load() and deletion join it first
    pending: Optional[Any] = None
    # background write raised: the file is absent or unusable, retention
    # and recovery must not treat this entry as a real checkpoint
    failed: bool = False

    def wait(self):
        """Block until any in-flight background write has finished.

        A write that failed on the background thread re-raises here (and
        marks the entry ``failed``) — the error must surface at the next
        synchronization point instead of dying with the writer thread."""
        if self.pending is not None:
            pending, self.pending = self.pending, None
            try:
                pending.result()
            except BaseException as e:
                self.failed = True
                raise RuntimeError(
                    f"background checkpoint write failed: '{self.path}' "
                    f"({type(e).__name__}: {e})") from e

    def write_failed(self):
        """Non-blocking: True once a finished background write is known
        to have raised (marks the entry failed, keeps the exception for
        ``wait()`` to re-raise)."""
        if self.failed:
            return True
        if self.pending is not None and self.pending.done():
            if self.pending.exception() is not None:
                self.failed = True
        return self.failed

    def load(self, **kwargs) -> Checkpoint:
        self.wait()
        return Checkpoint.load(self.path, **kwargs)

    def __hash__(self):
        return hash((self.model, self.idx_stage, self.idx_epoch, self.idx_step,
                     self.path))

    def __eq__(self, o):
        if not isinstance(o, CheckpointEntry):
            return NotImplemented
        return (
            self.model == o.model
            and self.idx_stage == o.idx_stage
            and self.idx_epoch == o.idx_epoch
            and self.idx_step == o.idx_step
            and self.path == o.path
        )


class CheckpointManager:
    """Name-templated checkpoint store with best/latest retention.

    ``compare`` is a list of metric expressions (e.g.
    ``'{m_EndPointError_mean}'``) evaluated over a checkpoint's metrics;
    lexicographically smallest wins.
    """

    def __init__(self, model_id, path, name, compare, keep_latest=None,
                 keep_best=None):
        self.model_id = model_id
        self.path = Path(path)
        self.name = name
        self.compare = list(compare)
        self.checkpoints: List[CheckpointEntry] = []
        self.keep_latest = keep_latest
        self.keep_best = keep_best

    def _metric_args(self, entry):
        sanitize = re.compile(r"[\./\\\?!:-]")
        metrics = entry.metrics or {}
        return {"m_" + sanitize.sub("_", k): v for k, v in metrics.items()}

    def _iter_args(self, entry):
        return {
            "id_model": entry.model,
            "n_stage": entry.idx_stage,
            "n_epoch": entry.idx_epoch,
            "n_steps": entry.idx_step,
        }

    def _args(self, entry):
        return self._iter_args(entry) | self._metric_args(entry)

    def _sort_key_best(self, entry):
        args = self._args(entry)
        return [utils.expr.eval_math_expr(c, args) for c in self.compare]

    @staticmethod
    def _sort_key_latest(entry):
        return entry.idx_stage, entry.idx_epoch, entry.idx_step

    def _filtered(self, stage, epoch):
        # entries whose background write is known to have failed have no
        # usable file behind them — queries must never hand them out
        chkpts = [c for c in self.checkpoints if not c.write_failed()]
        if stage is not None and epoch is not None:
            return [c for c in chkpts if c.idx_stage == stage and c.idx_epoch == epoch]
        if stage is not None:
            return [c for c in chkpts if c.idx_stage == stage]
        if epoch is not None:
            raise ValueError("epoch can only be set if stage is set")
        return chkpts

    def get_best(self, stage=None, epoch=None) -> Optional[CheckpointEntry]:
        return min(self._filtered(stage, epoch), key=self._sort_key_best, default=None)

    def get_latest(self, stage=None, epoch=None) -> Optional[CheckpointEntry]:
        return max(self._filtered(stage, epoch), key=self._sort_key_latest,
                   default=None)

    def load_valid(self, sort="latest", stage=None, log=None):
        """Load the best/latest checkpoint that actually verifies.

        Entries are tried in ``sort`` order ("latest" or "best"); a
        corrupt file is quarantined (renamed ``*.corrupt``), dropped
        from the manager, and the next entry is tried — the recovery
        discipline for rollback and stage-boundary restores. Returns
        ``(entry, Checkpoint)`` or None when nothing valid remains.
        """
        key = (self._sort_key_best if sort == "best"
               else self._sort_key_latest)
        ordered = sorted(self._filtered(stage, None), key=key,
                         reverse=sort != "best")
        for entry in ordered:
            try:
                return entry, entry.load()
            except CheckpointCorrupt as e:
                if log is not None:
                    log.error(f"quarantining corrupt checkpoint: {e}")
                quarantine(entry.path)
                self.checkpoints = [c for c in self.checkpoints
                                    if c is not entry]
            except (RuntimeError, OSError) as e:
                # failed background write / missing file: drop, move on
                if log is not None:
                    log.error(f"skipping unusable checkpoint "
                              f"'{entry.path}': {e}")
                self.checkpoints = [c for c in self.checkpoints
                                    if c is not entry]
        return None

    def trim(self, n_best=1, n_latest=1, delete=True):
        if n_best is None and n_latest is None:
            return

        keep, remove = set(), set()
        for s in {c.idx_stage for c in self.checkpoints}:
            chkpts = [c for c in self.checkpoints if c.idx_stage == s]

            if n_best is not None:
                best = sorted(chkpts, key=self._sort_key_best)
                keep |= set(best[:n_best])
                remove |= set(best[n_best:])

            if n_latest is not None:
                latest = sorted(chkpts, key=self._sort_key_latest, reverse=True)
                keep |= set(latest[:n_latest])
                remove |= set(latest[n_latest:])

        self.checkpoints = sorted(keep, key=self._sort_key_latest)

        if delete:
            for entry in remove - keep:
                # a checkpoint whose background write is still in flight
                # must finish before the unlink (else the write recreates
                # the file after deletion)
                entry.wait()
                entry.path.unlink(missing_ok=True)

    def create(self, log, ctx, stage, epoch, step, metrics):
        """Save a checkpoint from the live training context and trim.

        Multi-host: only the primary process publishes (secondary
        processes compute the same replicated state — serializing it N
        times would just fill the workers' disks)."""
        import jax

        if jax.process_index() != 0:
            return

        # surface background-write failures at the next create(): a
        # writer-thread exception must not stay buried in a Future nobody
        # joins. The failed entry is dropped (its file is unusable), then
        # the error re-raises here.
        for entry in list(self.checkpoints):
            if entry.write_failed():
                self.checkpoints = [c for c in self.checkpoints
                                    if c is not entry]
                entry.wait()  # re-raises the writer's exception

        epoch_int = epoch if epoch is not None else stage.data.epochs
        entry = CheckpointEntry(self.model_id, stage.index, epoch_int, step,
                                metrics, None)

        args = self._args(entry) | {"id_stage": stage.id}
        args["id_model"] = args["id_model"].replace("/", "_").replace("-", ".")
        args["id_stage"] = args["id_stage"].replace("/", "_").replace("-", ".")

        entry.path = self.path / self.name.format_map(args)
        entry.path.parent.mkdir(parents=True, exist_ok=True)

        log.debug(f"saving checkpoint to '{entry.path}'")

        import time

        from .. import telemetry

        # timed from state assembly: the device->host fetch of the full
        # param/opt tree is the unavoidable step stall a checkpoint causes.
        # The msgpack encode + file write then run on a background thread
        # (RMD_ASYNC_CHECKPOINT=0 restores the fully synchronous save), so
        # training resumes after the snapshot instead of the full
        # serialize+write.
        t0 = time.perf_counter()
        chkpt = Checkpoint(
            model=self.model_id,
            iteration=Iteration(stage.index, epoch, step),
            metrics=metrics,
            state=State(
                model=serialization.to_state_dict(_to_host(ctx.train_variables())),
                optimizer=serialization.to_state_dict(_to_host(ctx.opt_state())),
                scaler=dict(ctx.scaler or {}),
                lr_sched_inst=[s.state_dict() for s in ctx.lr_sched_inst or []],
                lr_sched_epoch=[s.state_dict() for s in ctx.lr_sched_epoch or []],
            ),
            metadata={
                "timestamp": datetime.now().isoformat(),
                "source": "training",
            },
        )

        background = utils.env.get_bool("RMD_ASYNC_CHECKPOINT")
        tele = telemetry.get()

        def emit(blocking, bg):
            # `blocking` is the stall create() imposed on the train loop
            # (state snapshot [+ full write when synchronous]), `bg` the
            # serialize+write seconds that ran off the loop
            tele.emit(
                "checkpoint", path=str(entry.path), step=step,
                seconds=round(blocking + bg, 4),
                blocking_ms=round(blocking * 1e3, 1),
                background_ms=round(bg * 1e3, 1),
            )

        if background:
            write = chkpt.save(entry.path, background=True)
            blocking = time.perf_counter() - t0

            def finish():
                bg = write.result()
                emit(blocking, bg)
                return bg

            # same single-lane writer: runs after the write, so waiting
            # on entry.pending implies both the file and the telemetry
            # event exist
            entry.pending = _writer().submit(finish)
        else:
            chkpt.save(entry.path, background=False)
            emit(time.perf_counter() - t0, 0.0)

        self.checkpoints.append(entry)
        self.trim(n_best=self.keep_best, n_latest=self.keep_latest)


def find_auto_resume(path, model=None, quarantine_corrupt=True, log=None):
    """Discover the newest valid checkpoint under a directory tree.

    The ``--resume auto`` engine: scans ``path`` recursively for
    ``*.ckpt`` files (run directories, their ``checkpoints/`` subdirs,
    emergency saves — anything), verifies each candidate's integrity,
    and returns ``(file, Checkpoint)`` for the one furthest along by
    ``(stage, epoch, step)`` (file mtime breaks ties). Corrupt files
    are quarantined so the next scan doesn't re-read them; ``model``
    restricts the search to checkpoints of one model id. Returns None
    when nothing valid exists.
    """
    path = Path(path)
    if not path.exists():
        return None

    candidates = [f for f in path.rglob("*.ckpt")
                  if f.is_file() and not f.name.startswith(".")
                  # non-finite post-mortem dumps hold poisoned state by
                  # definition — never resume from one
                  and f.name != "failed.ckpt"]
    candidates.sort(key=lambda f: f.stat().st_mtime, reverse=True)

    best = None
    best_key = None
    for file in candidates:
        try:
            chkpt = Checkpoint.load(file)
        except CheckpointCorrupt as e:
            if log is not None:
                log.error(f"auto-resume: quarantining corrupt checkpoint: {e}")
            if quarantine_corrupt:
                quarantine(file)
            continue
        except (KeyError, TypeError, OSError):
            continue  # some other .ckpt-named file; not ours
        if model is not None and chkpt.model != model:
            continue
        it = chkpt.iteration
        key = (it.stage, it.epoch if it.epoch is not None else -1, it.step,
               file.stat().st_mtime)
        if best_key is None or key > best_key:
            best, best_key = (file, chkpt), key
    return best


def load_directory(path, compare) -> List[CheckpointManager]:
    """Scan a directory into per-model CheckpointManagers."""
    name = "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt"
    path = Path(path)

    checkpoints = defaultdict(list)
    for file in sorted(path.iterdir()):
        if not file.is_file():
            continue
        try:
            entry = Checkpoint.load(file).to_entry(file)
        except (ValueError, KeyError):
            continue
        checkpoints[entry.model].append(entry)

    mgrs = []
    for model in sorted(checkpoints):
        mgr = CheckpointManager(model, path, name, compare)
        mgr.checkpoints = checkpoints[model]
        mgrs.append(mgr)

    return mgrs
