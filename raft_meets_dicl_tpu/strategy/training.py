"""The training loop: stages → epochs → instances, on a jitted SPMD step.

Control-flow parity with the reference TrainingContext
(src/strategy/training.py:17-325): resume arithmetic, ``mode='best'``
cross-stage checkpoint promotion, per-stage optimizer/scheduler rebuilds
(checkpoints restore weights-only at stage boundaries, full state
mid-stage), invalid-batch skipping, result validation with a ``failed``
checkpoint dump, and the 9-callback Inspector protocol.

The hot path is different by design: instead of eager torch ops, each
instance calls one jitted train step (parallel.make_train_step) that holds
the whole forward/backward/update program; gradient accumulation and
clipping live inside it as optax transforms. Per-instance host work is just
the scheduler tick, callbacks, and a scalar fetch (loss + finiteness).
"""

import time
from collections import deque
from datetime import datetime
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from .. import telemetry, utils
from ..data import device_augment
from ..telemetry import blackbox, goodput
from ..telemetry import steptrace as steptrace_mod
from ..parallel import (
    Partitioner, TrainState, batch_nbytes, make_train_step, shard_batch,
)
from ..testing import faults
from .checkpoint import Checkpoint, Iteration, State
from .spec import Stage, Strategy


class NonFinitePolicy:
    """What to do when a training step produces non-finite values.

    ``raise`` (default) preserves the historical behavior: dump a
    ``failed.ckpt`` and abort the run. ``skip`` compiles the
    skip-step discipline of dynamic loss scaling (Micikevicius et al.,
    *Mixed Precision Training*, 2018) into the train step: the poisoned
    optimizer update is dropped on device (params/opt state carry
    forward bit-identically) and training continues. ``rollback`` skips
    like ``skip`` but restores the last valid checkpoint once trips
    persist. Both escalate — ``max_consecutive`` consecutive tripped
    steps, or more than ``max_consecutive`` trips within a trailing
    ``window`` of steps, trigger the rollback (or, under ``skip`` /
    when no checkpoint survives, the abort), and ``max_rollbacks``
    bounds how often a rollback may fire before the run gives up.
    """

    POLICIES = ("raise", "skip", "rollback")

    def __init__(self, policy="raise", max_consecutive=3, window=50,
                 max_rollbacks=3):
        if policy not in self.POLICIES:
            raise ValueError(
                f"invalid non-finite policy '{policy}', expected one of "
                f"{list(self.POLICIES)}")
        self.policy = policy
        self.max_consecutive = max(1, int(max_consecutive))
        self.window = max(1, int(window))
        self.max_rollbacks = max(0, int(max_rollbacks))

    @classmethod
    def from_config(cls, cfg):
        """``None`` | policy name | mapping with ``policy`` /
        ``max-consecutive`` / ``window`` / ``max-rollbacks`` keys."""
        if cfg is None:
            return cls()
        if isinstance(cfg, str):
            return cls(cfg)
        if isinstance(cfg, cls):
            return cfg
        return cls(
            cfg.get("policy", "raise"),
            cfg.get("max-consecutive", cfg.get("max_consecutive", 3)),
            cfg.get("window", 50),
            cfg.get("max-rollbacks", cfg.get("max_rollbacks", 3)),
        )

    def get_config(self):
        return {
            "policy": self.policy,
            "max-consecutive": self.max_consecutive,
            "window": self.window,
            "max-rollbacks": self.max_rollbacks,
        }


def _device_prefetch(samples, put, depth=2, tele=None):
    """Double-buffered host→device prefetch: pipeline batches onto the
    device ahead of consumption.

    On a remote/tunneled backend the per-step host->device input
    transfer (tens of MB per batch) otherwise serializes with compute —
    measured as the dominant step cost on the axon tunnel. A background
    thread loads and ``put``s up to ``depth`` batches ahead (default 2:
    batch N+1 transfers while step N executes); the main loop receives
    (host_batch, device_batch, meta) with transfers already in flight.
    Loader exceptions re-raise at the consumption point.

    ``RMD_PREFETCH=0`` swaps in :func:`_sync_transfer` (identical batch
    stream, transfer left on the critical path — the A/B baseline);
    ``RMD_PREFETCH_DEPTH`` tunes the buffer count.

    ``tele`` gets two phase streams: ``device_put`` (the worker's
    transfer-initiation time, attributed up to ``depth`` batches ahead of
    the consuming step — the aggregate breakdown is what matters) and
    ``data_wait`` (time the consumer blocks on the queue, i.e. the input
    pipeline failing to keep ahead of the device).
    """
    import queue
    import threading

    q = queue.Queue(maxsize=depth)
    _END = object()
    tele = tele if tele is not None else telemetry.get()

    def worker():
        try:
            for img1, img2, flow, valid, meta in samples:
                host = (img1, img2, flow, valid)
                t0 = time.perf_counter()
                dev = put(host)
                tele.add_phase("device_put", time.perf_counter() - t0)
                q.put((host, dev, meta))
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            q.put((_END, e, None))
            return
        q.put((_END, None, None))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        t0 = time.perf_counter()
        host, dev, meta = q.get()
        tele.add_phase("data_wait", time.perf_counter() - t0)
        if host is _END:
            if dev is not None:
                raise dev
            return
        yield host, dev, meta


def _sync_transfer(samples, put, tele=None):
    """RMD_PREFETCH=0: the same (host, dev, meta) stream as
    :func:`_device_prefetch` with the transfer kept synchronous on the
    critical path — the bit-identical A/B baseline for the prefetch
    overlap, and an escape hatch for backends whose background-thread
    ``device_put`` misbehaves. The ``device_put`` phase then lands on
    the consuming step's wall time instead of overlapping it."""
    tele = tele if tele is not None else telemetry.get()
    it = iter(samples)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        tele.add_phase("data_wait", time.perf_counter() - t0)
        img1, img2, flow, valid, meta = item
        host = (img1, img2, flow, valid)
        with tele.span("device_put"):
            dev = put(host)
        yield host, dev, meta


class _StepResult:
    """Minimal Result view over the train step's aux outputs."""

    def __init__(self, aux):
        self.aux = aux

    def final(self):
        return self.aux["final"]

    def output(self, batch_index=None):
        return self.aux["final"]

    def intermediate_flow(self):
        return [self.aux["final"]]


def _make_put(base_put, wire, tele):
    """Wrap the device-placement callable with wire encoding + accounting.

    With ``wire`` the batch's flow/valid are compressed here (images come
    wire-encoded from the adapter already) before ``base_put``; either way
    the actual transfer volume is recorded as the per-step ``wire_bytes``
    counter, so compression (or its absence) is visible in events.jsonl.
    """

    def put(batch):
        if wire is not None:
            batch = wire.encode_batch(batch)
        tele.add_count("wire_bytes", batch_nbytes(batch))
        return base_put(batch)

    return put


class TrainingContext:
    def __init__(self, log, path, strategy, model_id, model, model_adapter,
                 loss, input, inspector, checkpoints, mesh=None,
                 step_limit=None, loader_args={}, wire=None,
                 eval_buckets=None, nonfinite=None, partitioner=None,
                 accumulate=1, augment=None):
        self.root_log = log
        self.log = log
        self.path = Path(path)
        self.strategy = strategy
        self.model_id = model_id
        self.model = model
        self.model_adapter = model_adapter
        self.loss = loss
        self.input = input
        self.inspector = inspector
        self.checkpoints = checkpoints
        self.mesh = mesh
        # the partitioner maps params/optimizer state onto the mesh
        # (parallel.partition): replicated on the 1-D data mesh, sharded
        # over 'model' on a 2-D mesh. Everything that places or annotates
        # state asks it, so a layout change propagates everywhere at once.
        self.partitioner = (partitioner if partitioner is not None
                            else Partitioner(mesh) if mesh is not None
                            else None)
        # in-step gradient accumulation factor (make_train_step
        # accumulate=k): the loader batches k·B samples, the step scans k
        # microbatches of B and applies ONE optimizer update — k× the
        # effective batch at one microbatch's activation HBM. Orthogonal
        # to the per-stage optax.MultiSteps accumulation, which spreads
        # microbatches over k host steps instead.
        self.accumulate = max(1, int(accumulate))
        self.loader_args = dict(loader_args)
        # wire format (models.wire.WireFormat) for the host→device batch
        # transfer; bound to the input spec's clip/range per stage. None =
        # legacy host-normalized f32 batches.
        self.wire = (wire.bound(input.clip, input.range)
                     if wire is not None else None)
        # on-device augmentation (data.device_augment.DeviceAugment):
        # compiled into the train step as a ProgramKey flag variant, keyed
        # per (sample_id, epoch). Bound to the input spec's value range so
        # photometric math happens on [0, 1]. None = host-side (or no)
        # augmentation, historical step signature and program identity.
        self.augment = (augment.bound(tuple(input.range))
                        if augment is not None else None)
        # shape buckets for the validation passes (models.input.ShapeBuckets):
        # mixed-resolution validation sets batch per bucket and compile at
        # most one val-step program per bucket
        self.eval_buckets = eval_buckets

        # non-finite step recovery policy (NonFinitePolicy); counters are
        # reset per stage in run_stage
        self.nonfinite = NonFinitePolicy.from_config(nonfinite)
        self._nf_last_count = 0
        self._nf_consecutive = 0
        self._nf_window = deque()
        self._nf_rollbacks = 0
        # sample ids of recently dispatched batches — attached to
        # nonfinite events so a trip is reproducible offline even though
        # detection is amortized (up to _finite_every-1 steps late)
        self._recent_samples = deque(maxlen=32)

        # graceful-stop flag: set by the SIGTERM/SIGINT handlers (or
        # request_stop); the loop finishes the in-flight step, writes an
        # emergency checkpoint, and returns cleanly
        self._stop = None
        self._prev_handlers = {}

        self.validate = True

        self.step = 0
        self.step_limit = step_limit

        # observability plane (telemetry.sidecar.TrainObserver reads
        # these; all host-side, refreshed at the finite-check cadence)
        self.steptraces = steptrace_mod.StepTraceSummary()
        self.steps_completed = 0     # readiness = first step completed
        self._heartbeat_t = None     # step-loop liveness stamp
        self.last_norms = None       # (grad_norm, update_norm) floats
        self._pending_norms = None   # staged device scalars, unfetched
        self.last_memory = None      # latest memory_snapshot fields
        self.last_checkpoint = None  # (path, step) of the newest save

        # executed micro-batches within the current stage; drives the
        # accumulation boundary in lockstep with optax.MultiSteps (which
        # counts tx.update calls) so an invalid-batch skip costs one
        # micro-batch instead of desyncing host and device counters
        self._accum = 0
        self._in_step = False

        # per-run / per-stage state
        self.variables = None       # model variables when no stage is active
        self.state: Optional[TrainState] = None
        self.tx = None
        self.scaler = None
        self.lr_sched_inst = None
        self.lr_sched_epoch = None
        self.data = None
        self.step_fn = None
        self.base_lr = 0.0
        self.current_stage = None
        self.current_epoch = None
        self.last_lr = 0.0

    # -- state accessors (used by CheckpointManager.create) ----------------

    def train_variables(self):
        if self.state is not None:
            return {"params": self.state.params,
                    "batch_stats": self.state.batch_stats}
        return self.variables

    def opt_state(self):
        return self.state.opt_state if self.state is not None else {}

    # -- preemption / graceful stop ----------------------------------------

    def install_signal_handlers(self):
        """Route SIGTERM/SIGINT into a graceful stop: the loop finishes
        the in-flight step, writes an emergency checkpoint, and returns
        cleanly (``--resume auto`` picks the run back up). The first
        signal arms the stop and restores the previous handler, so a
        second signal still kills a wedged run the hard way. Returns
        False when handlers can't be installed (non-main thread)."""
        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)
            except ValueError:
                self._prev_handlers.clear()
                return False
        return True

    def _on_signal(self, signum, frame):
        import signal as _signal

        self.request_stop(_signal.Signals(signum).name)
        prev = self._prev_handlers.pop(signum, None)
        if prev is not None:
            _signal.signal(signum, prev)

    def request_stop(self, reason="request"):
        """Arm the graceful stop (signal-handler and test entry point)."""
        self._stop = reason

    def heartbeat_age(self):
        """Seconds since the step loop last went around (sidecar
        liveness); 0.0 before the first instance starts."""
        if self._heartbeat_t is None:
            return 0.0
        return time.perf_counter() - self._heartbeat_t

    def _emergency_stop(self, log):
        """Write the preemption checkpoint and log how to resume."""
        reason = self._stop
        tele = telemetry.get()
        tele.emit("preempt", signal=str(reason), step=self.step,
                  stage=getattr(self.current_stage, "index", None),
                  epoch=self.current_epoch)

        if jax.process_count() > 1 and jax.process_index() != 0:
            log.warn(f"stop requested ({reason}): exiting (secondary process)")
            return None

        if self.train_variables() is None or self.current_stage is None:
            log.warn(f"stop requested ({reason}) before training started: "
                     "nothing to checkpoint")
            return None

        stage = self.current_stage
        epoch = self.current_epoch if self.current_epoch is not None else 0
        path_dir = Path(getattr(self.checkpoints, "path", None) or self.path)
        path_dir.mkdir(parents=True, exist_ok=True)
        path = path_dir / f"emergency-s{stage.index}_e{epoch}_b{self.step}.ckpt"

        log.warn(f"stop requested ({reason}): writing emergency checkpoint "
                 f"to '{path}'")
        t0 = time.perf_counter()
        self._snapshot_checkpoint(stage, epoch, source="emergency").save(path)
        tele.emit("checkpoint", path=str(path), step=self.step,
                  seconds=round(time.perf_counter() - t0, 4),
                  source="emergency")
        self.last_checkpoint = (path, self.step)
        # flight recorder: the ring survived the signal path (the handler
        # only sets _stop; the loop broke out normally), so the bundle
        # holds the last N steps exactly as the loop saw them
        blackbox.get().dump(path_dir, f"preempt-{reason}", tele=tele,
                            checkpoint=str(path), step=self.step)
        log.warn("emergency checkpoint written; resume with '--resume auto'")
        return path

    # -- initialization ----------------------------------------------------

    def _ensure_variables(self, stage):
        """Initialize model variables from the first stage's sample shape."""
        if self.variables is not None:
            return

        self.log.info("initializing model parameters")
        img1, img2, *_ = self.input.apply(stage.data.source).jax()[0]

        seed = int(np.random.randint(0, 2**31 - 1))
        if jax.process_count() > 1:
            # every process must initialize identical parameters (replicate
            # trusts but never verifies same-value-per-process): broadcast
            # process 0's seed
            from jax.experimental import multihost_utils

            seed = int(multihost_utils.broadcast_one_to_all(np.int32(seed)))
        rng = jax.random.PRNGKey(seed)
        init_args = dict(self.model.arguments)
        # keep tracing cheap: recurrent iteration counts don't affect params
        if "iterations" in init_args:
            init_args["iterations"] = (
                1 if isinstance(init_args["iterations"], int)
                else tuple(1 for _ in init_args["iterations"])
            )

        self.variables = self.model.init(
            rng, img1[:1], img2[:1], **init_args
        )

    # -- main loop ----------------------------------------------------------

    def run(self, start_stage=None, start_epoch=None, checkpoint=None):
        n_stages = len(self.strategy.stages)

        if start_stage is None and checkpoint is not None:
            start_stage = checkpoint.iteration.stage
        if start_stage is None:
            start_stage = 0

        assert 0 <= start_stage < n_stages

        if start_epoch is None and checkpoint is not None:
            start_epoch = checkpoint.iteration.epoch + 1
        if start_epoch is None:
            start_epoch = 0

        if checkpoint is not None:
            self.step = checkpoint.iteration.step

        backend = jax.default_backend()
        self.log.info(
            f"start training: running {n_stages} stages on backend "
            f"'{backend}' ({jax.device_count()} devices)"
        )

        self._ensure_variables(self.strategy.stages[start_stage])
        self.inspector.setup(self.log, self)

        for i, stage in list(enumerate(self.strategy.stages))[start_stage:]:
            # checkpoint created at end of a stage: skip to the next
            if start_epoch >= stage.data.epochs:
                start_epoch = 0
                continue

            self.log = self.root_log.new(f"stage {i + 1}/{n_stages}")
            self.log.info(
                f"starting new stage '{stage.name}' ({stage.id}) at step {self.step}"
            )

            stage.index = i
            self.run_stage(self.log, stage, start_epoch, checkpoint)

            start_epoch = 0
            checkpoint = None

            if self._stop:
                break
            if self.step_limit is not None and self.step >= self.step_limit:
                break

        self.log = self.root_log
        if self._stop:
            self._emergency_stop(self.log)
            self.log.info(
                f"training interrupted ({self._stop}) at step {self.step:,}; "
                "state saved for auto-resume"
            )
            return
        self.log.info(
            f"training loop complete, ran {self.step:,} steps over {n_stages} stages"
        )

    def prepare_stage(self, log, stage: Stage):
        if self.strategy.mode != "best":
            return

        # load_valid: a corrupt best checkpoint is quarantined and the
        # next-best valid one used instead of aborting the stage handoff
        found = self.checkpoints.load_valid(sort="best",
                                            stage=stage.index - 1, log=log)
        if found is None:
            return

        entry, chkpt = found
        log.info(f"loading best checkpoint from previous stage, file='{entry.path}'")
        self.variables, _, _ = chkpt.apply(variables=self.variables)

    def run_stage(self, log, stage: Stage, start_epoch=0, checkpoint=None):
        assert 0 <= start_epoch < stage.data.epochs

        self.current_stage = stage
        self.prepare_stage(log, stage)

        # data
        log.info(f"loading dataset: {stage.data.source.description()}")
        loader_args = self.loader_args | stage.loader_args

        # multi-host: the configured batch size is GLOBAL; each process
        # loads its slice (same-seed epoch order, strided shard) and the
        # global batch is assembled in parallel.shard_batch
        n_proc = jax.process_count()
        batch_size = stage.data.batch_size
        if self.mesh is not None and batch_size % self.mesh.devices.size:
            # fail with a config-level message before the sharded step
            # rejects the global array with a partitioner traceback
            raise ValueError(
                f"global batch size {batch_size} must be a multiple of the "
                f"mesh device count ({self.mesh.devices.size})"
            )
        # in-step accumulation: the loader hands the step k microbatches
        # at once; each step call is one optimizer update over k·B
        batch_size *= self.accumulate
        if n_proc > 1:
            if batch_size % n_proc:
                raise ValueError(
                    f"global batch size {batch_size} does not divide over "
                    f"{n_proc} processes"
                )
            batch_size //= n_proc
            loader_args.setdefault("shard", (jax.process_index(), n_proc))
            if "seed" not in loader_args:
                # all processes must draw the same epoch order; broadcast a
                # seed from process 0's (run-seeded) RNG so --reproduce
                # still governs data order
                from jax.experimental import multihost_utils

                seed = int(np.random.randint(0, 2**31 - 1))
                loader_args["seed"] = int(
                    multihost_utils.broadcast_one_to_all(np.int32(seed)))

        if self.wire is not None:
            log.info(f"wire format: {self.wire.describe()} "
                     "(device-side normalization)")
        input = self.input.apply(
            stage.data.source, normalize=self.wire is None,
        ).jax(wire=self.wire)
        self.data = input.loader(
            batch_size=batch_size,
            shuffle=stage.data.shuffle,
            drop_last=stage.data.drop_last,
            **loader_args,
        )
        log.info(
            f"dataset loaded: have {len(self.data)} batches over {len(input)} samples"
        )
        if len(input) == 0:
            # combinators tolerate empty sources so bare specs can load
            # without mounted data; actually training on nothing is a
            # config error and must fail fast
            raise ValueError(
                "dataset resolved to zero samples: "
                f"{stage.data.source.description()}"
            )

        # optimizer (fresh per stage, like the reference)
        log.info("setting up optimizer")
        self.tx, self.base_lr = stage.optimizer.build(stage.gradient)
        self.scaler = stage.gradient.scaler.build()

        sched_vars = {
            "n_samples": len(input),
            "n_batches": len(self.data),
            "n_epochs": stage.data.epochs,
            "n_accum": stage.gradient.accumulate,
            "batch_size": stage.data.batch_size,
        }
        self.lr_sched_inst, self.lr_sched_epoch = stage.scheduler.build(
            self.base_lr, sched_vars
        )

        # state: fresh optimizer, current weights
        self.state = TrainState.create(self.variables, self.tx)

        # restore checkpoint state: stage boundary (epoch 0) restores weights
        # only — optimizer/schedulers belong to the previous stage
        if checkpoint is not None:
            log.info("restoring data from checkpoint")
            if start_epoch == 0:
                variables, _, _ = checkpoint.apply(
                    variables=self.train_variables()
                )
                self.state = TrainState.create(variables, self.tx)
            else:
                variables, opt_state, self.scaler = checkpoint.apply(
                    variables=self.train_variables(),
                    opt_state=self.state.opt_state,
                    scaler=self.scaler,
                    lr_sched_inst=self.lr_sched_inst,
                    lr_sched_epoch=self.lr_sched_epoch,
                )
                self.state = self.state.replace(
                    params=variables["params"],
                    batch_stats=variables["batch_stats"],
                    opt_state=opt_state,
                )

        state_sharding = None
        if self.mesh is not None:
            # place the fresh state per the partition rules (replicated on
            # the 1-D mesh, params/moments sharded over 'model' on a 2-D
            # one) and publish the per-chip HBM accounting
            self.state = self.partitioner.shard_state(self.state)
            state_sharding = self.partitioner.state_shardings(self.state)
            telemetry.get().emit(
                "sharding", step=self.step, stage=stage.index,
                **self.partitioner.report(self.state))

        # stage hooks before building the step: freeze_batchnorm etc. are
        # baked into the compiled program
        self.model_adapter.on_stage(stage, **stage.model_on_stage_args)

        # gradients enter the step's aux output only if observability asks
        # (gradient metrics/hooks) — they cost a params-sized live buffer
        with_grads = bool(getattr(self.inspector, "wants_gradients", False))

        self.step_fn = make_train_step(
            self.model, self.loss, self.tx, mesh=self.mesh,
            loss_args=stage.loss_args, model_args=stage.model_args,
            external_lr=True, donate=True, with_grads=with_grads,
            wire=self.wire, state_sharding=state_sharding,
            accumulate=self.accumulate,
            # skip/rollback compile the on-device skip guard into the
            # step; raise keeps the unguarded update (NaNs absorbing)
            nonfinite="skip" if self.nonfinite.policy != "raise" else None,
            # stable program identity: registry dedupe across rebuilds
            # (resume/rollback in-process) and AOT artifact addressing —
            # a repeat boot of the same stage config starts stepping
            # without a single compile when the program store is warm
            key=self._train_step_key(stage, with_grads),
            augment=self.augment,
        )

        self._accum = 0
        self._in_step = False
        self._pending_finite = None
        # non-finite recovery bookkeeping: the device counter restarts at
        # zero with the fresh TrainState, host mirrors follow
        self._nf_last_count = 0
        self._nf_consecutive = 0
        self._nf_window.clear()
        # finite-check cadence (steps); 1 restores the check-every-step
        # behavior for debugging
        self._finite_every = max(
            1, utils.env.get_int("RMD_FINITE_CHECK_EVERY"))

        # device-sync sampling bookkeeping: device step time is measured
        # at the finite-fetch cadence (the fetch is already a pipeline
        # drain), never per step — a per-step sync is the serialization
        # round 5 removed
        self._dispatched = 0
        self._last_sync_dispatched = 0
        self._last_sync_t = time.perf_counter()
        self._pending_norms = None

        self.inspector.on_stage_start(log, self, stage)
        telemetry.get().emit(
            "stage_start", stage=stage.index, step=self.step,
            id=stage.id, name=stage.name, epochs=stage.data.epochs,
            batch_size=stage.data.batch_size,
        )

        log.info(f"running {stage.data.epochs} epochs")
        for epoch in range(start_epoch, stage.data.epochs):
            log_ = log.new(f"epoch {epoch + 1}/{stage.data.epochs}", sep=", ")
            log_.info(f"starting new epoch at step {self.step}")
            self.log = log_

            self.run_epoch(log_, stage, epoch)

            if self._stop:
                break
            if self.step_limit is not None and self.step >= self.step_limit:
                break

        self.log = log

        # sync live variables out of the stage state
        self.variables = self.train_variables()

        if self._stop:
            # preemption: skip the stage-end validation sweep — the
            # emergency checkpoint is the only artifact that matters now
            telemetry.get().emit("stage_end", stage=stage.index,
                                 step=self.step, interrupted=True)
            goodput.get().emit_event(telemetry.get(), stage=stage.index,
                                     step=self.step)
            return

        self.inspector.on_stage(log, self, stage)
        telemetry.get().emit("stage_end", stage=stage.index, step=self.step)
        goodput.get().emit_event(telemetry.get(), stage=stage.index,
                                 step=self.step)

    def _train_step_key(self, stage, with_grads):
        """Stable ``compile.ProgramKey`` for this stage's train step.

        Everything baked into the traced program is part of the identity:
        the full stage config (model/loss args, optimizer, gradient spec —
        hashed, the repr is long), wire format, mesh layout, the
        non-finite guard, accumulation, and the aux-gradients flag.
        Returns None when the stage config has no exact serialization
        (synthetic test sources): the step then registers anonymously —
        compile-counted but never deduped or AOT'd.
        """
        import hashlib

        from .. import compile as programs

        try:
            stage_cfg = repr(stage.get_config())
        except Exception:  # noqa: BLE001 - unserializable test stubs
            return None
        mesh_key = None
        if self.mesh is not None:
            mesh_key = (tuple(self.mesh.shape.items()),
                        tuple(d.id for d in self.mesh.devices.flat))
        # the augment flag exists only on the augmented variant: with
        # device augmentation off, the key (and thus program identity,
        # AOT artifact, and budget pin) stays byte-identical to before
        aflags = {}
        if self.augment is not None:
            aflags["augment"] = self.augment.describe()
        return programs.ProgramKey(
            kind="train_step", model=self.model_id,
            flags=programs.flag_items(
                stage=stage.id,
                config=hashlib.sha256(stage_cfg.encode()).hexdigest()[:16],
                wire=None if self.wire is None else self.wire.describe(),
                mesh=mesh_key,
                nonfinite=("skip" if self.nonfinite.policy != "raise"
                           else None),
                accumulate=self.accumulate,
                with_grads=with_grads,
                **aflags,
            ))

    def run_epoch(self, log, stage, epoch):
        self.current_epoch = epoch
        tele = telemetry.get()
        tele.emit("epoch_start", stage=stage.index, epoch=epoch,
                  step=self.step)

        desc = (
            f"stage {stage.index + 1}/{len(self.strategy.stages)}, "
            f"epoch {epoch + 1}/{stage.data.epochs}"
        )
        samples = utils.logging.progress(self.data, unit="batch", leave=False,
                                         desc=desc)

        self.model_adapter.on_epoch(stage, epoch, **stage.model_on_epoch_args)
        self.inspector.on_epoch_start(log, self, stage, epoch)

        # advance epoch-seeded host augmentation BEFORE the loader starts
        # iterating (decode workers fork per iteration, so they capture
        # the value); keyed per (sample_id, epoch) like the device path
        src = getattr(stage.data, "source", None)
        if src is not None and hasattr(src, "set_epoch"):
            src.set_epoch(epoch)

        base_put = ((lambda b: shard_batch(b, self.mesh))
                    if self.mesh is not None else jax.device_put)
        if not utils.env.get_bool("RMD_PREFETCH_PUT"):
            # host-only prefetch: overlap decode but let jit do the
            # implicit arg transfer (fallback for backends whose explicit
            # device_put path misbehaves)
            base_put = lambda b: b  # noqa: E731

        if (self.wire is None
                and getattr(getattr(self.model, "module", None),
                            "mixed_precision", False)
                and utils.env.get_bool("RMD_WIRE_BF16")):
            # legacy lightweight compression (pre-wire-format): the model
            # computes its encoders in bf16 anyway, so transferring the
            # host-normalized images as bf16 halves the dominant bytes
            # without changing effective numerics; flow/valid stay exact.
            # The full wire layer (--wire-format) subsumes this path.
            import jax.numpy as jnp

            def put(b, _base=base_put):
                img1, img2, flow, valid = b
                b = (np.asarray(img1, jnp.bfloat16),
                     np.asarray(img2, jnp.bfloat16), flow, valid)
                tele.add_count("wire_bytes", batch_nbytes(b))
                return _base(b)
        else:
            put = _make_put(base_put, self.wire, tele)

        # double-buffered prefetch (default): batch N+1's device_put runs
        # on a background thread while step N executes, so the transfer
        # never sits on the step critical path. RMD_PREFETCH=0 restores
        # the synchronous put (bit-identical results, for A/B and as an
        # escape hatch); RMD_PREFETCH_DEPTH tunes how far ahead.
        if not utils.env.get_bool("RMD_PREFETCH"):
            batches = _sync_transfer(samples, put, tele=tele)
        else:
            depth = max(1, utils.env.get_int("RMD_PREFETCH_DEPTH"))
            batches = _device_prefetch(samples, put, depth=depth, tele=tele)

        it = enumerate(batches)
        while True:
            # per-step trace: one perf_counter clock whose marks bracket
            # the queue pull, so data_wait lands on the step that paid it
            strace = steptrace_mod.StepTrace(step=self.step)
            strace.mark("start")
            nxt = next(it, None)
            if nxt is None:
                break
            i, (host, dev, meta) = nxt
            strace.mark("data")

            log_ = log.new(f"step {self.step}", sep=", ")
            self.log = log_

            self.run_instance(log_, stage, epoch, i, host, dev, meta,
                              strace=strace)

            if self._stop:
                break
            if self.step_limit is not None and self.step >= self.step_limit:
                break

        self.log = log
        self._flush_finite_check(log)

        # memory watermarks: RMD_DEBUG_MEM's ad-hoc print, promoted to a
        # structured per-epoch event (snapshot cost is one procfs read +
        # a live-array census — epoch-boundary cheap)
        if tele.enabled or utils.env.get_bool("RMD_DEBUG_MEM"):
            snap = telemetry.memory_snapshot()
            self.last_memory = snap
            tele.emit("memory", stage=stage.index, epoch=epoch,
                      step=self.step, **snap)
            if utils.env.get_bool("RMD_DEBUG_MEM"):
                log.info(f"mem: rss {snap['host_rss_gib']:.2f} GiB, "
                         f"live jax arrays {snap['live_arrays']}")

        if self._stop:
            # mid-epoch preemption: the epoch didn't complete, so neither
            # the epoch schedulers nor the epoch-end validation sweep run
            tele.emit("epoch_end", stage=stage.index, epoch=epoch,
                      step=self.step, interrupted=True)
            return

        for s in self.lr_sched_epoch:
            s.step()

        self.inspector.on_epoch(log, self, stage, epoch)
        tele.emit("epoch_end", stage=stage.index, epoch=epoch,
                  step=self.step)

    def _flush_finite_check(self, log):
        """Resolve the deferred finite flag of the epoch's last step
        before validation/checkpointing can observe a poisoned state."""
        prev, self._pending_finite = self._pending_finite, None
        if prev is not None:
            self._sample_norms()
            self._resolve_finite(log, prev,
                                 "non-finite flow values detected")

    def _sample_norms(self):
        """Fetch the staged grad/update norm scalars for the gauges.

        Called only at the amortized finite-fetch cadence, where the
        pipeline is already drained by the finite flag — the two extra
        scalar fetches ride the same sync, never adding one.
        """
        pending, self._pending_norms = self._pending_norms, None
        if pending is None:
            return
        g, u = pending
        try:
            self.last_norms = (
                None if g is None else float(g),  # graftlint: disable=host-sync -- rides the amortized finite fetch, pipeline already drained
                None if u is None else float(u))  # graftlint: disable=host-sync -- rides the amortized finite fetch, pipeline already drained
        except Exception:  # noqa: BLE001 - gauges must never kill a step
            self.last_norms = None

    def _resolve_finite(self, log, prev, msg):
        """Apply the non-finite policy to one resolved finite fetch.

        ``prev`` is ``(finite_flag, stage, epoch, nonfinite_count)`` as
        staged by run_instance. Under ``raise`` this is the historical
        dump-and-abort. Under ``skip``/``rollback`` the poisoned updates
        were already dropped on device; here the host reads the
        cumulative skip counter, emits the telemetry trail, and
        escalates when trips persist (see NonFinitePolicy).
        """
        finite, stage, epoch, count = prev

        if self.nonfinite.policy == "raise":
            if not bool(finite):
                self._dump_failed(log, stage, epoch)
                raise RuntimeError(msg)
            return

        finite = bool(finite)
        count = int(count) if count is not None else 0
        trips = count - self._nf_last_count
        self._nf_last_count = count

        if trips <= 0:
            self._nf_consecutive = 0
            return

        # consecutive estimate: exact at RMD_FINITE_CHECK_EVERY=1; at a
        # larger cadence the latest step's flag decides whether the trip
        # streak is still live
        self._nf_consecutive = (self._nf_consecutive + trips if not finite
                                else 0)
        self._nf_window.append((self.step, trips))
        horizon = self.step - self.nonfinite.window
        while self._nf_window and self._nf_window[0][0] < horizon:
            self._nf_window.popleft()
        in_window = sum(t for _, t in self._nf_window)

        samples = [{"step": s, "samples": ids}
                   for s, ids in self._recent_samples]
        telemetry.get().emit(
            "nonfinite", step=self.step, stage=stage.index, epoch=epoch,
            action="skip", trips=trips, consecutive=self._nf_consecutive,
            window_trips=in_window, samples=samples,
        )
        log.warn(
            f"non-finite step: dropped {trips} optimizer update(s) "
            f"(policy '{self.nonfinite.policy}'; {in_window} trips in the "
            f"last {self.nonfinite.window} steps)")

        if (self._nf_consecutive < self.nonfinite.max_consecutive
                and in_window <= self.nonfinite.max_consecutive):
            return

        if self.nonfinite.policy == "rollback":
            self._rollback(log, stage, epoch)
            return

        self._dump_failed(log, stage, epoch)
        raise RuntimeError(
            f"non-finite steps persist under policy 'skip' "
            f"({self._nf_consecutive} consecutive, {in_window} within "
            f"{self.nonfinite.window} steps): aborting ({msg})")

    def _rollback(self, log, stage, epoch):
        """Restore the last valid checkpoint after persistent trips."""
        self._nf_rollbacks += 1
        if self._nf_rollbacks > self.nonfinite.max_rollbacks:
            self._dump_failed(log, stage, epoch)
            raise RuntimeError(
                f"non-finite steps persist after "
                f"{self.nonfinite.max_rollbacks} rollbacks: aborting")

        found = (self.checkpoints.load_valid(sort="latest", log=log)
                 if self.checkpoints is not None else None)
        if found is None:
            self._dump_failed(log, stage, epoch)
            raise RuntimeError(
                "non-finite steps persist and no valid checkpoint exists "
                "to roll back to")

        entry, chkpt = found
        from_step = self.step
        log.error(
            f"non-finite steps persist: rolling back to '{entry.path}' "
            f"(step {chkpt.iteration.step})")

        try:
            variables, opt_state, self.scaler = chkpt.apply(
                variables=self.train_variables(),
                opt_state=self.state.opt_state,
                scaler=self.scaler,
                lr_sched_inst=self.lr_sched_inst,
                lr_sched_epoch=self.lr_sched_epoch,
            )
        except (KeyError, TypeError, ValueError):
            # optimizer structure mismatch (checkpoint from another
            # stage): weights-only restore, optimizer restarts fresh
            log.warn("rollback checkpoint has incompatible optimizer "
                     "state: restoring weights only")
            variables, _, _ = chkpt.apply(variables=self.train_variables())
            opt_state = self.tx.init(variables["params"])

        self.state = self.state.replace(
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            opt_state=opt_state,
        )
        if self.mesh is not None:
            self.state = self.partitioner.shard_state(self.state)
        self.step = chkpt.iteration.step

        self._nf_consecutive = 0
        self._nf_window.clear()
        telemetry.get().emit(
            "nonfinite", step=self.step, stage=stage.index, epoch=epoch,
            action="rollback", path=str(entry.path), from_step=from_step,
            to_step=chkpt.iteration.step, rollbacks=self._nf_rollbacks,
        )

    def run_instance(self, log, stage, epoch, i, host, dev, meta,
                     strace=None):
        accumulate = stage.gradient.accumulate
        img1, img2, flow, valid = host

        self._heartbeat_t = time.perf_counter()
        if strace is None:
            # direct callers (tests) skip the run_epoch pull bracket:
            # start the clock here with an empty data_wait phase
            strace = steptrace_mod.StepTrace(step=self.step)
            strace.mark("start")
            strace.mark("data")

        # wire mode: host images are un-normalized wire dtype. Observers
        # that consume pixel values (TB image dumps, intermediates
        # capture) expect the normalized f32 contract — decode on the
        # steps where the inspector says it will actually look, so the
        # hot path never pays the second f32 copy
        if self.wire is not None and self._wants_host_images():
            img1 = self.wire.decode_images_host(img1)
            img2 = self.wire.decode_images_host(img2)

        if not self._in_step:
            self.inspector.on_step_start(log, self, stage, epoch, i)
            self._in_step = True

        # check for degeneracies in samples and warn/skip — the boundary is
        # driven by executed micro-batches, so a skip shifts the step by one
        # batch (like the reference's zero-grad-on-boundary) instead of
        # desyncing against the in-step MultiSteps counter
        if not all(m.valid for m in meta):
            log.warn("skipping batch due to invalid data")
            return

        # learning rate from the instance schedulers (last one wins, like
        # chained torch schedulers); epoch schedulers compose the base
        lr = self.base_lr
        for s in self.lr_sched_epoch:
            lr = s.lr()
        for s in self.lr_sched_inst:
            lr = s.lr()
        self.last_lr = lr

        if faults.active():
            if faults.fire("sigterm", step=self.step) is not None:
                import os as _os
                import signal as _signal

                log.warn(f"fault injection: SIGTERM at step {self.step}")
                _os.kill(_os.getpid(), _signal.SIGTERM)
            if faults.fire("nan_update", step=self.step) is not None:
                # NaN lr -> NaN update tree: the same poison a NaN
                # gradient produces after the optimizer, without
                # depending on model internals
                log.warn(f"fault injection: NaN update at step {self.step}")
                lr = float("nan")

        self._recent_samples.append(
            (self.step,
             [f"{m.dataset_id}/{m.sample_id}" for m in meta]))

        self.inspector.on_batch_start(log, self, stage, epoch, i, img1, img2,
                                      flow, valid, meta)

        # host prep done; the transfer itself was staged by the prefetch
        # worker (its cost is the worker-attributed device_put phase), so
        # the consumer-side device_put mark lands immediately
        strace.mark("prep")
        strace.mark("put")

        tele = telemetry.get()
        with tele.span("dispatch"):
            if self.augment is not None:
                # device augmentation: per-sample ids + the epoch scalar
                # key the on-device draws; ids derive from the metadata
                # so they are independent of shuffle order and resume
                ids = device_augment.sample_id_array(meta)
                self.state, aux = self.step_fn(
                    self.state, lr, *dev, ids, np.int32(epoch))
            else:
                self.state, aux = self.step_fn(self.state, lr, *dev)
        self._dispatched += 1
        strace.mark("dispatched")

        # validate output, check for non-finite numbers — DEFERRED and
        # AMORTIZED: bool(finite) is a device->host fetch, and fetching
        # every freshly-dispatched step would serialize the loop on the
        # backend's round-trip latency (on the tunneled TPU that latency,
        # not compute, dominated the epoch). Only the latest step's flag
        # is fetched, every _finite_every steps; NaNs/infs are absorbing
        # through the optimizer state (NaN grads -> NaN clip scale ->
        # NaN params), so a poisoned step always trips a later check —
        # detection just fires up to _finite_every-1 steps late, and
        # _flush_finite_check resolves the epoch's last step before
        # validation or checkpointing can observe the state.
        self._pending_norms = (aux.get("grad_norm"),
                               aux.get("update_norm"))
        if self.validate:
            self._pending_finite = (aux["finite"], stage, epoch,
                                    aux.get("nonfinite_count"))
            if (i + 1) % self._finite_every == 0:
                prev, self._pending_finite = self._pending_finite, None
                t0 = time.perf_counter()
                finite = bool(prev[0])
                self._emit_device_sync(tele, time.perf_counter() - t0)
                self._sample_norms()
                self._resolve_finite(
                    log, (finite,) + prev[1:],
                    "non-finite flow values detected (flagged on a "
                    "later step than the producing one; the state "
                    "dump includes the poisoned updates)")
        elif tele.enabled and (i + 1) % self._finite_every == 0:
            # validation disabled: the finite fetch (our usual free sync
            # point) never happens, so sample the pipeline drain
            # explicitly at the same amortized cadence
            t0 = time.perf_counter()
            jax.block_until_ready(aux["loss"])
            self._emit_device_sync(tele, time.perf_counter() - t0)
            self._sample_norms()
        # device phase = how long the fetch above blocked (zero on the
        # amortized steps in between) — never an extra sync
        strace.mark("synced")

        loss = aux["loss"]

        # multi-process: aux["final"] is the GLOBAL batch array, but
        # host-side metrics compare against this process's local targets —
        # reassemble the local slice from the addressable shards (ordered
        # by their global offset; each process owns one contiguous stripe)
        with tele.span("host"):
            if self.mesh is not None and jax.process_count() > 1:
                # dedupe by batch offset: on a 2-D mesh a batch range can
                # be materialized on more than one local device (model
                # axis), and each copy must contribute exactly once
                parts = {}
                for s in aux["final"].addressable_shards:
                    parts.setdefault(s.index[0].start or 0,
                                     np.asarray(s.data))
                aux = aux | {"final": np.concatenate(
                    [parts[k] for k in sorted(parts)])}

            result = _StepResult(aux)

            self.inspector.on_batch(log, self, stage, epoch, i, img1, img2,
                                    flow, valid, meta, result, loss)

        self._accum += 1
        if self._accum % accumulate == 0:
            # the optimizer update itself happened inside the jitted step
            # (optax.MultiSteps applies on every accumulate-th call)
            for s in self.lr_sched_inst:
                s.step()

            # step event precedes on_step_end so the inspector can mirror
            # this step's phases to the TB scalars under the same step
            tele.step_event(self.step, stage=stage.index, epoch=epoch,
                            batch=stage.data.batch_size)
            self.inspector.on_step_end(log, self, stage, epoch, i)
            self.step += 1
            self.steps_completed += 1
            self._in_step = False

        # close the trace: every phase is a perf_counter diff on one
        # clock, so the record telescopes exactly to the step total
        strace.mark("done")
        rec = self.steptraces.add(strace)
        blackbox.get().record_step(rec)
        if tele.enabled and (i + 1) % self._finite_every == 0:
            ev = self.steptraces.event(self.step)
            if ev is not None:
                tele.emit("steptrace", **ev)

    def _wants_host_images(self):
        """Whether the inspector will consume pixel values this step.

        Inspectors declare via ``wants_host_images(step)``; inspectors
        that predate the wire layer get decoded images on every step
        (correct, just not free).
        """
        fn = getattr(self.inspector, "wants_host_images", None)
        return bool(fn(self.step)) if callable(fn) else True

    def _emit_device_sync(self, tele, drain):
        """Record one pipeline-drain sample: ``seconds`` is the time the
        host blocked to resolve the newest step's output (≈0 means the
        host, not the device, is the bottleneck), ``wall``/``steps`` give
        the true device pipeline rate over the sampled window."""
        if not tele.enabled:
            return
        now = time.perf_counter()
        steps = self._dispatched - self._last_sync_dispatched
        wall = now - self._last_sync_t
        self._last_sync_dispatched = self._dispatched
        self._last_sync_t = now
        tele.emit("device_sync", step=self.step, seconds=round(drain, 6),
                  steps=steps, wall=round(wall, 6))

    def _snapshot_checkpoint(self, stage, epoch, source="training"):
        """Full-state Checkpoint of the live context (host-side copy)."""
        from flax import serialization

        return Checkpoint(
            model=self.model_id,
            iteration=Iteration(stage.index, epoch, self.step),
            metrics=None,
            state=State(
                model=serialization.to_state_dict(
                    jax.tree.map(np.asarray, self.train_variables())
                ),
                optimizer=serialization.to_state_dict(
                    jax.tree.map(np.asarray, self.opt_state())
                ),
                scaler=dict(self.scaler or {}),
                lr_sched_inst=[s.state_dict()
                               for s in self.lr_sched_inst or []],
                lr_sched_epoch=[s.state_dict()
                                for s in self.lr_sched_epoch or []],
            ),
            metadata={
                "timestamp": datetime.now().isoformat(),
                "source": source,
            },
        )

    def _dump_failed(self, log, stage, epoch):
        log.error("detected non-finite values in final flow field")
        # auto-flushes the sink (nonfinite is a boundary event): the run
        # is about to die and the JSONL must survive for the post-mortem.
        # The recent sample-id window makes the trip reproducible offline
        # even though detection is amortized (the producing batch is one
        # of the listed ones, at most _finite_every-1 steps back).
        telemetry.get().emit(
            "nonfinite", step=self.step, stage=stage.index, epoch=epoch,
            action="raise",
            samples=[{"step": s, "samples": ids}
                     for s, ids in self._recent_samples],
        )

        failed = self.path / "failed.ckpt"
        self._snapshot_checkpoint(stage, epoch).save(failed)
        self.last_checkpoint = (failed, self.step)
        blackbox.get().dump(self.path, "nonfinite", tele=telemetry.get(),
                            checkpoint=str(failed), step=self.step)
