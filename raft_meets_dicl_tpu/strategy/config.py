"""Strategy/stage config loading with file-relative resolution
(reference src/strategy/config.py)."""

from pathlib import Path

from ..utils import config
from . import spec


def load_stage(path, cfg=None):
    path = Path(path)

    if cfg is None:
        return spec.Stage.from_config(path.parent, config.load(path))
    if not isinstance(cfg, dict):
        return spec.Stage.from_config((path / cfg).parent, config.load(path / cfg))
    return spec.Stage.from_config(path, cfg)


def load(path, cfg=None):
    path = Path(path)

    if cfg is None:
        return spec.Strategy.from_config(path.parent, config.load(path))
    if not isinstance(cfg, dict):
        return spec.Strategy.from_config((path / cfg).parent, config.load(path / cfg))
    return spec.Strategy.from_config(path, cfg)
