"""The ``evaluate`` subcommand: per-sample metrics, reports, flow images.

Capability parity with the reference command (src/cmd/eval.py:112-303): the
same metric/collector pipeline, per-sample logging, JSON/YAML report, and
the ten flow-image output formats. The forward passes run through the
jitted evaluation generator (evaluation.evaluate).
"""

import logging
from pathlib import Path

import cv2
import numpy as np

from .. import data, evaluation, metrics, models, strategy, utils, visual

_DEFAULT_METRICS = Path(__file__).parent.parent.parent / "cfg" / "eval" / "default.yaml"

FLOW_FORMATS = (
    "flow:flo", "flow:kitti", "visual:epe", "visual:bp-fl", "visual:flow",
    "visual:flow:dark", "visual:flow:gt", "visual:i1",
    "visual:warp:backwards", "visual:intermediate:flow",
    "visual:occlusion", "visual:confidence",
)

# formats derived from the forwards-backwards pass (--fwbw)
_FWBW_FORMATS = ("visual:occlusion", "visual:confidence")


def evaluate(args):
    utils.logging.setup()

    # fail fast on a bad format — before model load and jit compile
    if args.flow and args.flow_format not in FLOW_FORMATS:
        raise ValueError(
            f"unknown flow format '{args.flow_format}'; "
            f"choose one of {', '.join(FLOW_FORMATS)}"
        )

    fwbw = bool(getattr(args, "fwbw", False))
    if args.flow and args.flow_format in _FWBW_FORMATS and not fwbw:
        raise ValueError(
            f"flow format '{args.flow_format}' derives from the "
            f"forwards-backwards pass; add --fwbw")

    # telemetry (opt-in for eval: --telemetry PATH): the sweep's eval
    # event, compile attribution, and the AOT hit/miss trail
    from .. import compile as programs, telemetry
    from ..utils import compcache

    tele = telemetry.get()
    if getattr(args, "telemetry", None):
        tele = telemetry.activate(telemetry.create(Path(args.telemetry)))
        if tele.path:
            logging.info(f"writing telemetry events to '{tele.path}'")
    tele.emit(
        "boot",
        compile_cache=compcache.effective_dir(),
        aot_dir=str(programs.programs_dir()) if programs.aot_enabled()
        else None,
        aot=programs.aot_enabled(),
    )

    # device selection (mirrors the train command)
    import jax

    from .train import select_devices

    devices = select_devices(args.device, args.device_ids)
    jax.config.update("jax_default_device", devices[0])

    # multi-device selection shards the eval batch over a data mesh (the
    # reference wraps eval in nn.DataParallel, src/cmd/eval.py:144-145);
    # the mesh comes from the parallel layer so eval and train agree on
    # device order and axis names
    mesh = None
    if len(devices) > 1:
        from .. import parallel

        mesh = parallel.data_mesh(devices=devices)
        logging.info(f"evaluating data-parallel over {len(devices)} devices")

    # model (a full training config's model section is accepted too)
    logging.info(f"loading model specification, file='{args.model}'")
    model_cfg = utils.config.load(args.model)
    if "strategy" in model_cfg:
        model_cfg = model_cfg["model"]

    spec = models.load(model_cfg)
    model, loss, input = spec.model, spec.loss, spec.input
    model_adapter = model.get_adapter()

    logging.info(f"loading checkpoint, file='{args.checkpoint}'")
    chkpt = strategy.Checkpoint.load(args.checkpoint)

    # metrics
    metrics_path = args.metrics if args.metrics else _DEFAULT_METRICS
    logging.info(f"loading metrics specification, file='{metrics_path}'")

    metrics_cfg = utils.config.load(metrics_path)
    mtx = metrics.Metrics.from_config(metrics_cfg["metrics"])
    collectors = metrics.Collectors.from_config(metrics_cfg["summary"])

    # data
    logging.info(f"loading data specification, file='{args.data}'")
    compute_metrics = not args.flow_only

    # wire format: images cross host->device compact and un-normalized,
    # normalization runs inside the jitted eval step
    from ..models.wire import WireFormat

    wire = WireFormat.from_config(getattr(args, "wire_format", None))
    if wire is not None:
        wire = wire.bound(input.clip, input.range)
        logging.info(f"input wire format: {wire.describe()}")

    if fwbw and wire is not None:
        # the backwards dispatch re-enters the eval program with the
        # yielded (already host-decoded) images; a wire session would
        # need them re-encoded — keep the product path f32-only
        raise ValueError("--fwbw needs the plain f32 input path "
                         "(drop --wire-format)")

    # shape buckets: quantize mixed per-image resolutions onto a small
    # canonical set and batch same-bucket samples — a KITTI-like sweep
    # then compiles at most n_buckets programs instead of one per
    # distinct padded shape, and batches stay full
    from ..models.input import ShapeBuckets

    buckets_spec = (getattr(args, "buckets", None)
                    or utils.env.raw("RMD_EVAL_BUCKETS"))
    buckets = ShapeBuckets.from_config(buckets_spec)
    if buckets is not None:
        logging.info(f"shape buckets: {buckets.describe()}")

    dataset = data.load(args.data)
    loader = input.apply(dataset, normalize=wire is None, buckets=buckets).jax(
        compute_metrics, wire=wire,
    ).loader(batch_size=args.batch_size, shuffle=False, drop_last=False,
             group_by_shape=buckets is not None)

    # variables from the checkpoint (structure target from a sample init;
    # init wants the normalized f32 contract, not the wire dtype)
    img1, img2, *_ = loader.source[0]
    if wire is not None:
        img1 = wire.decode_images_host(img1)
        img2 = wire.decode_images_host(img2)
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])
    variables, _, _ = chkpt.apply(variables=variables)

    path_out = Path(args.output) if args.output else None
    if path_out is not None:
        path_out.parent.mkdir(parents=True, exist_ok=True)

    path_flow = Path(args.flow) if args.flow else None

    # visual-format argument plumbing (src/cmd/eval.py:177-204)
    visual_args = {}
    if args.flow_mrm:
        visual_args["mrm"] = float(args.flow_mrm)
    if args.flow_gamma:
        visual_args["gamma"] = float(args.flow_gamma)

    visual_dark_args = dict(visual_args)
    if args.flow_transform:
        visual_dark_args["transform"] = args.flow_transform

    epe_args = {}
    if args.epe_cmap is not None:
        epe_args["cmap"] = args.epe_cmap
    if args.epe_max is not None:
        epe_args["vmax"] = float(args.epe_max)

    logging.info(f"evaluating {len(loader.source)} samples")

    # partial per-bucket batches (epoch-end remainders) are padded up to
    # the full batch size so they reuse the bucket's compiled program
    pad_to = args.batch_size if buckets is not None else None
    stats = evaluation.EvalRunStats(name="evaluate")

    # recurrence-budget override: CLI --iterations > RMD_ITERATIONS >
    # the model config's default (0/unset means no override). The
    # program key hashes the effective merged arguments, so overridden
    # sweeps never collide with the default program or its AOT artifact
    from ..utils import env

    iterations = getattr(args, "iterations", None)
    if iterations is None:
        iterations = env.get_int("RMD_ITERATIONS") or None
    model_args = {"iterations": int(iterations)} if iterations else None
    if iterations:
        logging.info(f"iteration override: {iterations}")

    # stable model id: the program dedupes with any other builder of the
    # same (model, bucket, wire) triple in this process (e.g. a training
    # validation pass) and round-trips through the AOT store across boots
    eval_fn = evaluation.make_eval_fn(model, model_args, mesh=mesh,
                                      wire=wire, model_id=spec.id)
    if getattr(args, "precompile", False):
        if buckets is None or not buckets.sizes:
            raise ValueError(
                "--precompile needs explicit bucket sizes (--buckets HxW,...)")
        warm_batch = args.batch_size
        if mesh is not None:
            n = mesh.devices.size
            warm_batch = -(-warm_batch // n) * n
        logging.info(f"precompiling {len(buckets.sizes)} bucket shapes "
                     f"at batch {warm_batch}")
        evaluation.warmup_eval_fn(eval_fn, variables, buckets.sizes,
                                  warm_batch, wire=wire, stats=stats)

    # incremental per-sample JSONL: one line per evaluated sample, flushed
    # as it is computed — a crash mid-sweep keeps everything up to the
    # crash instead of losing the whole report
    inc_path = None
    if not getattr(args, "no_incremental", False):
        if getattr(args, "incremental", None):
            inc_path = Path(args.incremental)
        elif path_out is not None and compute_metrics:
            inc_path = path_out.parent / (path_out.stem + ".samples.jsonl")
    inc_fd = None
    if inc_path is not None and compute_metrics:
        inc_path.parent.mkdir(parents=True, exist_ok=True)
        inc_fd = open(inc_path, "w")
        logging.info(f"appending per-sample metrics to '{inc_path}'")

    import json

    if fwbw:
        from ..video.products import fw_bw_products

    output = []
    ctx_m = metrics.MetricContext()

    for sample in evaluation.evaluate(model, variables, loader, mesh=mesh,
                                      wire=wire, eval_fn=eval_fn,
                                      pad_to=pad_to, stats=stats):
        target = sample.target[None] if sample.target is not None else None
        valid = sample.valid[None] if sample.valid is not None else None
        est = sample.final[None]
        out = model_adapter.wrap_result(sample.output, None)

        occlusion = confidence = None
        if fwbw:
            # reversed pair through the same compiled eval program
            # (batch 1 — a second shape next to a batched sweep, but
            # one compile per bucket, and products stay per-sample)
            _, flow_bw = eval_fn(variables, sample.img2[None],
                                 sample.img1[None])
            flow_bw = np.asarray(jax.device_get(flow_bw))[0]
            occlusion, confidence = fw_bw_products(sample.final, flow_bw)

        if target is not None and compute_metrics:
            sample_loss = float(np.asarray(
                loss(model, out.output(), target, valid)
            ))
            sample_metrs = mtx(ctx_m, est, target, valid, sample_loss)

            record = {"id": str(sample.meta.sample_id), "metrics": sample_metrs}
            if occlusion is not None:
                record["fwbw"] = {
                    "occlusion_ratio": round(float(occlusion.mean()), 5),
                    "confidence_mean": round(float(confidence.mean()), 5),
                }
            output.append(record)
            collectors.collect(sample_metrs)
            if inc_fd is not None:
                inc_fd.write(json.dumps(record) + "\n")
                inc_fd.flush()

            info = [f"{k}: {v:.04f}" for k, v in sample_metrs.items()]
            logging.info(f"sample: {sample.meta.sample_id}, {', '.join(info)}")
        else:
            logging.info(f"sample: {sample.meta.sample_id}")

        if path_flow is not None:
            img1 = (sample.img1 + 1) / 2
            img2 = (sample.img2 + 1) / 2
            save_flow_image(
                path_flow, args.flow_format, sample.meta.sample_id, img1, img2,
                sample.target, sample.valid, sample.final, out,
                sample.meta.original_extents, visual_args, visual_dark_args,
                epe_args, occlusion=occlusion, confidence=confidence,
            )

    if inc_fd is not None:
        inc_fd.close()

    logging.info(
        f"evaluation sweep: {stats.samples} samples in {stats.batches} "
        f"batches ({stats.samples_per_sec():.2f} samples/s, "
        f"{stats.compiles} compiled shapes, "
        f"pad waste {stats.pad_waste_ratio() * 100:.1f}%)")
    stats.emit()

    if compute_metrics:
        logging.info("summary:")
        for collector in collectors.collectors:
            info = [f"{k}: {v:.04f}" for k, v in collector.result().items()]
            logging.info(f"  {collector.type}: {', '.join(info)}")

        if path_out is not None:
            utils.config.store(path_out, {
                "samples": output,
                "summary": collectors.results(),
            })

    if getattr(args, "telemetry", None):
        # flush + close the opt-in sink so the JSONL is complete on exit
        telemetry.deactivate()


def save_flow_image(dir, format, sample_id, img1, img2, target, valid, flow,
                    out, size, visual_args, visual_dark_args, epe_args,
                    batch_index=0, occlusion=None, confidence=None):
    """One sample's output in the requested format (src/cmd/eval.py:274-303).

    ``batch_index`` selects the sample within ``out``'s batch dimension
    for the intermediates dump — the evaluation generator yields
    per-sample (batch-1) outputs, so the default 0 addresses that sample;
    callers holding a full-batch result pass the real index.
    ``occlusion``/``confidence`` are the forwards-backwards products
    (``--fwbw``), required by the ``visual:occlusion`` and
    ``visual:confidence`` formats.
    """
    (h0, h1), (w0, w1) = size
    flow = flow[h0:h1, w0:w1]
    img1 = img1[h0:h1, w0:w1]
    img2 = img2[h0:h1, w0:w1]
    if target is not None:
        target = target[h0:h1, w0:w1]
    if valid is not None:
        valid = np.asarray(valid[h0:h1, w0:w1], bool)
    if occlusion is not None:
        occlusion = occlusion[h0:h1, w0:w1]
    if confidence is not None:
        confidence = confidence[h0:h1, w0:w1]

    formats = {
        "flow:flo": (data.io.write_flow_mb, [flow], {}, "flo"),
        "flow:kitti": (data.io.write_flow_kitti, [flow], {}, "png"),
        "visual:epe": (save_flow_visual_epe, [flow, target, valid], epe_args, "png"),
        "visual:bp-fl": (save_flow_visual_fl_error, [flow, target, valid], {}, "png"),
        "visual:flow": (save_flow_visual, [flow], visual_args, "png"),
        "visual:flow:dark": (save_flow_visual_dark, [flow], visual_dark_args, "png"),
        "visual:flow:gt": (save_flow_visual, [target], visual_args, "png"),
        "visual:i1": (save_image, [img1], {}, "png"),
        "visual:warp:backwards": (save_flow_visual_warp_backwards, [img2, flow], {}, "png"),
        "visual:intermediate:flow": (save_intermediate_flow_visual,
                                     [out, batch_index], visual_args, "png"),
        "visual:occlusion": (save_occlusion_visual, [img1, occlusion],
                             {}, "png"),
        "visual:confidence": (save_confidence_visual, [confidence],
                              {}, "png"),
    }

    write, wargs, kwargs, ext = formats[format]

    path = Path(dir) / f"{sample_id}.{ext}"
    path.parent.mkdir(parents=True, exist_ok=True)
    write(path, *wargs, **kwargs)


def _to_u8(img):
    return (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)


def save_image(path, img, **kwargs):
    cv2.imwrite(str(path), _to_u8(img[:, :, ::-1]))


def save_flow_visual(path, uv, **kwargs):
    rgba = visual.flow_to_rgba(uv, **kwargs)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_flow_visual_dark(path, uv, **kwargs):
    rgba = visual.flow_to_rgba_dark(uv, **kwargs)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_flow_visual_epe(path, uv, uv_target, mask, cmap="gray", **kwargs):
    if cmap == "absflow":
        rgba = visual.end_point_error_abs(uv, uv_target, mask)
    else:
        rgba = visual.end_point_error(uv, uv_target, mask, cmap=cmap, **kwargs)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_flow_visual_fl_error(path, uv, uv_target, mask):
    rgba = visual.fl_error(uv, uv_target, mask)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_flow_visual_warp_backwards(path, img2, flow):
    cv2.imwrite(str(path), _to_u8(visual.warp_backwards(img2, flow)[:, :, ::-1]))


def save_occlusion_visual(path, img1, occlusion, **kwargs):
    rgba = visual.occlusion_overlay(img1, occlusion, **kwargs)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_confidence_visual(path, confidence, **kwargs):
    rgba = visual.confidence_to_rgba(confidence, **kwargs)
    cv2.imwrite(str(path), _to_u8(visual.utils.rgba_to_bgra(rgba)))


def save_intermediate_flow_visual(path, output, batch_index=0, mrm=None,
                                  **kwargs):
    """Dump every intermediate flow, magnitude-normalized across levels by
    width ratio (src/cmd/eval.py:338-383).

    ``batch_index`` picks the sample out of each node's leading batch
    dimension, so a batched result dumps the requested sample's
    intermediates instead of silently always writing sample 0.
    """
    inter = output.intermediate_flow()

    flat = {}

    def unpack(node, key=""):
        if isinstance(node, (list, tuple)):
            for i, x in enumerate(node):
                unpack(x, f"{key}.{i}")
        elif isinstance(node, dict):
            for k, x in node.items():
                unpack(x, f"{key}.{k}")
        else:
            flat[key] = np.asarray(node)[batch_index]

    unpack(inter)

    ref_width = max(uv.shape[1] for uv in flat.values())

    if mrm is None:
        mrm = 1e-5
        for uv in flat.values():
            level_max = float(np.max(np.linalg.norm(uv, ord=2, axis=-1)))
            mrm = max(mrm, level_max * ref_width / uv.shape[1])

    path = Path(path)
    for k, uv in flat.items():
        p = path.parent / f"{path.stem}{k}{path.suffix}"
        rgba = visual.flow_to_rgba(uv, mrm=mrm * uv.shape[1] / ref_width, **kwargs)
        cv2.imwrite(str(p), _to_u8(visual.utils.rgba_to_bgra(rgba)))
