"""The ``checkpoint`` subcommand: info and trim over checkpoint stores.

Capability parity with the reference (src/cmd/checkpoint.py:7-77).
"""

from pathlib import Path

from ..strategy import Checkpoint
from ..strategy.checkpoint import load_directory


def checkpoint(args):
    commands = {"info": info, "trim": trim}
    if args.subcommand not in commands:
        print("usage: checkpoint {info, trim} ... (see --help)")
        return
    commands[args.subcommand](args)


def _split_exprs(exprs):
    return [e.strip() for e in exprs.split(",")]


def _entry_info(entry):
    info = [
        f"stage: {entry.idx_stage}",
        f"epoch: {entry.idx_epoch}",
        f"step: {entry.idx_step}",
    ]
    info += [f"{k}: {v:.04f}" for k, v in (entry.metrics or {}).items()]
    return ", ".join(info)


def info(args):
    compare = _split_exprs(args.sort or "{n_stage}, {n_epoch}, {n_steps}")

    for path in args.file:
        path = Path(path)

        if path.is_file():
            entry = Checkpoint.load(path).to_entry(path)
            print()
            print(f"File: '{path}', Model: {entry.model}")
            print(f"  {_entry_info(entry)}")
        else:
            for mgr in load_directory(path, compare):
                print()
                print(f"Directory: '{path}', Model: {mgr.model_id}")
                for entry in sorted(mgr.checkpoints, key=mgr._sort_key_best):
                    print(f"  {_entry_info(entry)}")


def trim(args):
    if args.keep_best and not args.compare:
        raise ValueError(
            "option --compare must be specified when --keep-best is specified"
        )
    if not args.keep_best and not args.keep_latest:
        raise ValueError(
            "need to specify --keep-best or --keep-latest (or both)"
        )

    compare = _split_exprs(args.compare or "{n_stage}, {n_epoch}, {n_steps}")

    for path in args.directory:
        for mgr in load_directory(path, compare):
            mgr.trim(args.keep_best, args.keep_latest)
