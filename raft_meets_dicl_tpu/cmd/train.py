"""The ``train`` subcommand: run-dir setup, config assembly, training loop.

Capability parity with the reference command (src/cmd/train.py:45-226),
TPU-native where the reference is CUDA-native:

- device selection picks the jax platform / device subset and (for more
  than one device) builds the SPMD data mesh — the reference's
  ``nn.DataParallel`` wrap (src/cmd/train.py:183-184) has no runtime object
  here, sharding is part of the compiled step,
- ``--detect-anomaly`` flips ``jax_debug_nans`` (the jax analog of
  ``torch.autograd.set_detect_anomaly``),
- the env config carries loader args plus an ``xla`` section instead of
  cudnn switches.
"""

import datetime
import logging
import re
from pathlib import Path

from .. import inspect as inspect_
from .. import models, parallel, strategy, telemetry, utils
from ..strategy.training import TrainingContext

_DEFAULT_ENV = Path(__file__).parent.parent.parent / "cfg" / "env" / "default.yaml"
_DEFAULT_INSPECT = Path(__file__).parent.parent.parent / "cfg" / "inspect" / "default.yaml"


class Environment:
    """Loader arguments + wire format + backend flags (reference
    Environment, src/cmd/train.py:18-42; cudnn switches become jax/XLA
    ones, plus the host→device wire-format section)."""

    @classmethod
    def load(cls, cfg):
        if isinstance(cfg, (Path, str)):
            cfg = utils.config.load(cfg)

        return cls(
            loader_args=cfg.get("loader", {}),
            wire=cfg.get("wire"),
            eval=cfg.get("eval", {}),
            nonfinite=cfg.get("nonfinite"),
            parallel=cfg.get("parallel", {}),
            compile=cfg.get("compile", {}),
            augment=cfg.get("augment"),
            debug_nans=cfg.get("jax", {}).get("debug-nans", False),
            deterministic=cfg.get("jax", {}).get("deterministic", False),
        )

    def __init__(self, loader_args={}, wire=None, eval={}, nonfinite=None,
                 parallel={}, compile={}, augment=None, debug_nans=False,
                 deterministic=False):
        self.loader_args = dict(loader_args)
        # wire config: preset name ('f32'/'bf16'/'u8') or mapping with
        # images/flow/pack-valid keys (models.wire.WireFormat.from_config)
        self.wire = wire
        # eval section: shape buckets for the validation/evaluation passes
        # ({'buckets': 'HxW,...' | 'group' | {sizes, mode}}); the
        # RMD_EVAL_BUCKETS env var overrides it
        self.eval = dict(eval or {})
        # nonfinite section: non-finite step recovery policy — a policy
        # name or {policy, max-consecutive, window, max-rollbacks}
        # (strategy.training.NonFinitePolicy); --nonfinite and
        # RMD_NONFINITE override it
        self.nonfinite = nonfinite
        # parallel section: SPMD scale-out — {mesh: 'D,M' | {data, model},
        # accumulate: k}. --mesh/--accumulate and RMD_MESH/RMD_ACCUMULATE
        # override it (parallel.parse_mesh_spec documents the mesh forms).
        self.parallel = dict(parallel or {})
        # compile section: compiled-program cold-start knobs — {cache:
        # DIR} repoints the persistent XLA compile cache, {aot: false}
        # disables the AOT program store, {aot: DIR} relocates it.
        # --compile-cache / RMD_COMPILE_CACHE / RMD_AOT* override it.
        self.compile = dict(compile or {})
        # augment section: on-device augmentation parameters
        # (data.device_augment.DeviceAugment.from_config); its presence
        # with enabled: true turns the device path on, --device-aug and
        # RMD_DEVICE_AUG force it on with these (or default) parameters.
        self.augment = augment
        self.debug_nans = debug_nans
        self.deterministic = deterministic

    def get_config(self):
        return {
            "loader": self.loader_args,
            "wire": self.wire,
            "eval": self.eval,
            "nonfinite": self.nonfinite,
            "parallel": self.parallel,
            "compile": self.compile,
            "augment": self.augment,
            "jax": {
                "debug-nans": self.debug_nans,
                "deterministic": self.deterministic,
            },
        }

    def apply(self):
        import jax

        # compile-cache / AOT-store config (lowest precedence: the CLI
        # flag and RMD_* env vars were already applied at entry; only
        # fill in what they left at the default). Runs before any
        # backend use, like every other env flag here.
        cache = self.compile.get("cache")
        if (cache and not utils.env.raw("RMD_COMPILE_CACHE")
                and not utils.env.raw("RMD_COMPILE_CACHE_DIR")):
            from ..utils.compcache import enable_persistent_cache

            enable_persistent_cache(str(cache))
        aot = self.compile.get("aot")
        if aot is not None and not utils.env.raw("RMD_AOT_DIR"):
            from .. import compile as programs

            if aot is False:
                programs.disable_aot()
            elif programs.aot_enabled():
                programs.enable_aot(
                    None if aot is True else str(aot))

        if self.debug_nans:
            jax.config.update("jax_debug_nans", True)
        if self.deterministic:
            import os

            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_gpu_deterministic_ops" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_gpu_deterministic_ops=true"
                ).strip()


def select_devices(device=None, device_ids=None):
    """Resolve --device/--device-ids to a jax device list.

    ``device`` filters by platform name ('tpu', 'cpu'); ``device_ids`` is a
    comma-separated index list into that platform's devices. Returns the
    selected devices (all of the default backend if unspecified).
    """
    import jax

    if device:
        # make the requested platform the jax default too — site
        # configuration may pin a different platform, and only a
        # pre-backend-init config update lets e.g. `--device cpu` on an
        # accelerator host pick up XLA_FLAGS like
        # --xla_force_host_platform_device_count
        try:
            jax.config.update("jax_platforms", device)
        except RuntimeError:
            pass  # backend already initialized; fall through to filtering

    if device:
        try:
            devices = jax.devices(device)
        except RuntimeError as e:
            # surface an unknown/unavailable platform as a config-level
            # message: the config update above is a global side effect,
            # and backend init otherwise fails later with a confusing
            # error
            raise ValueError(
                f"--device '{device}': no such jax platform available "
                f"({e})"
            ) from e
    else:
        devices = jax.devices()

    if device_ids:
        ids = [int(i.strip()) for i in device_ids.split(",")]
        devices = [devices[i] for i in ids]

    return devices


def load_config_parts(args):
    """Assemble seed/env/model/strategy/inspect configs from --config plus
    individual overrides (reference src/cmd/train.py:69-137)."""
    cfg_seeds = cfg_env = cfg_model = cfg_strat = cfg_inspc = None
    base_path = "./"

    if getattr(args, "config", None) is not None:
        logging.info(f"loading configuration: file='{args.config}'")
        config = utils.config.load(args.config)

        cfg_seeds = config.get("seeds")
        cfg_model = config.get("model")
        cfg_strat = config.get("strategy")
        cfg_inspc = config.get("inspect")
        cfg_env = config.get("environment")
        base_path = Path(args.config).parent

    if getattr(args, "seeds", None):
        cfg_seeds = utils.config.load(args.seeds)

    if getattr(args, "env", None):
        cfg_env = args.env
    if cfg_env is None:
        cfg_env = _DEFAULT_ENV

    if getattr(args, "model", None) is not None:
        cfg_model = args.model
    if getattr(args, "data", None) is not None:
        cfg_strat = args.data
        base_path = "./"
    if getattr(args, "inspect", None) is not None:
        cfg_inspc = args.inspect
    if cfg_inspc is None:
        cfg_inspc = _DEFAULT_INSPECT

    return cfg_seeds, cfg_env, cfg_model, cfg_strat, cfg_inspc, base_path


def _train(args):
    timestamp = datetime.datetime.now()

    cfg_seeds, cfg_env, cfg_model, cfg_strat, cfg_inspc, base_path = \
        load_config_parts(args)

    # env flags must land before anything touches jax (XLA parses flags at
    # backend init — and the distributed handshake below brings the
    # backend up); seeds.apply() creates the first PRNG key
    env = Environment.load(cfg_env)
    env.apply()

    # multi-host: join the process group before any other backend use;
    # only the primary process owns the run directory, logs, and
    # checkpoints (SURVEY §5.8 — the pod-scale replacement for the
    # reference's single-host nn.DataParallel, src/cmd/train.py:183-184)
    primary = True
    if getattr(args, "distributed", False):
        parallel.initialize(
            coordinator=args.dist_coordinator,
            num_processes=args.dist_num_processes,
            process_id=args.dist_process_id,
        )
        primary = parallel.is_primary()

    suffix = ""
    if args.suffix:
        suffix = args.suffix if re.match(r"^[./_-].*$", args.suffix) else f"-{args.suffix}"

    if primary:
        path_out = Path(args.output) / (timestamp.strftime("%G.%m.%dT%H.%M.%S") + suffix)
        path_out.mkdir(parents=True)
        utils.logging.setup(path_out / "main.log")
    else:
        # secondary processes compute, they don't publish: artifacts go
        # to a scratch dir (checkpoint writes themselves are gated to the
        # primary in CheckpointManager.create), logging stays on console.
        # The scratch dir is removed when the process exits — worker hosts
        # otherwise accumulate one per run.
        import atexit
        import shutil
        import tempfile

        scratch = tempfile.mkdtemp(prefix="train-secondary-")
        atexit.register(shutil.rmtree, scratch, ignore_errors=True)
        path_out = Path(scratch)
        utils.logging.setup()
    logging.info(f"starting: time is {timestamp}, writing to '{path_out}'")
    logging.info(f"description: {args.comment if args.comment else '<not available>'}")

    # telemetry: structured run events (events.jsonl) — primary-only, like
    # every other run artifact. --no-telemetry / RMD_TELEMETRY=0 disable;
    # render the sink with scripts/telemetry_report.py afterwards.
    if getattr(args, "no_telemetry", False) or not primary:
        tele = telemetry.activate(telemetry.NullTelemetry())
    else:
        tele_path = getattr(args, "telemetry", None)
        tele = telemetry.activate(telemetry.create(
            Path(tele_path) if tele_path else path_out / "events.jsonl"))
        if tele.path:
            logging.info(f"writing telemetry events to '{tele.path}'")

    # goodput ledger + flight recorder ride the event stream (taps in
    # Telemetry.emit), so they activate right after the sink: the resume
    # event below must reach the ledger for replay accounting
    from ..telemetry import blackbox, goodput

    if tele.enabled and utils.env.get_bool("RMD_GOODPUT"):
        goodput.activate()
    if tele.enabled:
        blackbox.activate(
            capacity=max(1, utils.env.get_int("RMD_BLACKBOX_STEPS")),
            registry=telemetry.metrics.registry())

    # boot configuration event: the effective compile-cache and AOT
    # program directories (instead of silently defaulting) plus the
    # prefetch knob — the first thing a cold-start post-mortem needs
    from .. import compile as programs
    from ..utils import compcache

    tele.emit(
        "boot",
        compile_cache=compcache.effective_dir(),
        aot_dir=str(programs.programs_dir()) if programs.aot_enabled()
        else None,
        aot=programs.aot_enabled(),
        prefetch=utils.env.get_bool("RMD_PREFETCH"),
    )
    if compcache.effective_dir():
        logging.info(
            f"persistent compile cache: '{compcache.effective_dir()}'")
    if programs.aot_enabled():
        logging.info(f"AOT program store: '{programs.programs_dir()}'")

    # seeds (apply() seeds host RNGs and yields the root jax key)
    if args.reproduce or args.seeds:
        if cfg_seeds is None:
            raise ValueError("set --reproduce but no seeds specified")
        logging.info("seeding: using seeds from config")
        seeds = utils.seeds.from_config(cfg_seeds)
    else:
        seeds = utils.seeds.random_seeds()
    seeds.apply()

    # model
    if cfg_model is None:
        raise ValueError("no model configuration specified")
    if isinstance(cfg_model, str):
        logging.info(f"loading model configuration: file='{cfg_model}'")
    model = models.load(cfg_model)

    # strategy
    if cfg_strat is None:
        raise ValueError("no strategy/data configuration specified")
    if isinstance(cfg_strat, str):
        logging.info(f"loading strategy configuration: file='{cfg_strat}'")
        strat = strategy.load(cfg_strat)
    else:
        strat = strategy.load(base_path, cfg_strat)

    # inspector
    if isinstance(cfg_inspc, (str, Path)):
        logging.info(f"loading metrics/inspection configuration: file='{cfg_inspc}'")
    inspc = inspect_.load(cfg_inspc)

    # reproducibility dump
    path_config = path_out / "config.json"
    logging.info(f"writing full configuration to '{path_config}'")

    with open(path_out / "model.txt", "w") as fd:
        fd.write(repr(model.model.module))

    run_config = {
        "timestamp": timestamp.isoformat(),
        "commit": utils.vcs.get_git_head_hash(),
        "comment": args.comment if args.comment else "",
        "cwd": str(Path.cwd()),
        "args": {k: v for k, v in vars(args).items() if k != "comment"},
        "seeds": seeds.get_config(),
        "model": model.get_config(),
        "strategy": strat.get_config(),
        "inspect": inspc.get_config(),
        "environment": env.get_config(),
    }
    utils.config.store(path_config, run_config)
    blackbox.get().config = run_config

    # devices / mesh: --mesh > RMD_MESH > env 'parallel' section. Default
    # is the 1-D data mesh over every selected device (pure batch
    # parallelism, replicated params — the historical layout); 'D,M'
    # builds the 2-D (data × model) mesh whose 'model' axis shards
    # param/optimizer storage per parallel.partition's rules.
    import jax

    devices = select_devices(args.device, args.device_ids)
    mesh_cfg = (getattr(args, "mesh", None)
                or utils.env.raw("RMD_MESH")
                or env.parallel.get("mesh"))
    mesh_spec = parallel.parse_mesh_spec(mesh_cfg)
    if len(devices) > 1 or (mesh_spec is not None
                            and mesh_spec[0] * mesh_spec[1] > 1):
        mesh = parallel.make_mesh(mesh_spec, devices=devices)
        if parallel.process_count() > 1 and "model" in mesh.axis_names:
            raise ValueError(
                "--mesh with a model axis is single-process only for now "
                "(sharded state save/restore is process-local)")
    else:
        # pin single-device runs to the selected device — without this the
        # jitted step would fall back to the default backend's device 0
        mesh = None
        jax.config.update("jax_default_device", devices[0])
    if mesh is not None:
        shape = ", ".join(f"{n}={mesh.shape[n]}" for n in mesh.axis_names)
        logging.info(
            f"devices: {len(devices)}× {devices[0].platform} "
            f"(SPMD mesh: {shape})")
    else:
        logging.info(
            f"devices: {len(devices)}× {devices[0].platform} "
            "(single device)")

    # in-step gradient accumulation: --accumulate > RMD_ACCUMULATE > env
    # 'parallel' section; k microbatches per optimizer step inside the
    # jitted train step (k× effective batch, one microbatch's HBM)
    accumulate = int(getattr(args, "accumulate", None)
                     or utils.env.raw("RMD_ACCUMULATE")
                     or env.parallel.get("accumulate", 1) or 1)
    if accumulate > 1:
        logging.info(f"gradient accumulation: {accumulate} microbatches "
                     "per optimizer step (in-step lax.scan)")

    # build inspector and checkpoint manager
    inspector, chkptm = inspc.build(model.id, path_out)

    model_id = model.id
    model_spec, loss, input = model.model, model.loss, model.input
    model_adapter = model_spec.get_adapter()

    # checkpoint / resume
    chkpt = None
    if args.checkpoint and args.resume:
        raise ValueError("cannot set both --checkpoint and --resume")

    if args.checkpoint or args.resume:
        logging.warning(
            "saved config not sufficient for reproducibility due to checkpoint data"
        )

    # wire format: CLI flag > RMD_WIRE_FORMAT > env config. None keeps the
    # legacy host-normalized f32 batches.
    from ..models.wire import WireFormat

    wire_cfg = (getattr(args, "wire_format", None)
                or utils.env.raw("RMD_WIRE_FORMAT")
                or env.wire)
    wire = WireFormat.from_config(wire_cfg)
    if wire is not None:
        logging.info(f"input wire format: {wire.describe()}")

    loader_args = dict(env.loader_args)
    if getattr(args, "loader_procs", None) is not None:
        loader_args["procs"] = args.loader_procs

    # eval shape buckets: RMD_EVAL_BUCKETS > env config 'eval' section.
    # The validation passes group same-bucket samples into full batches
    # and compile at most one program per bucket (models.input.ShapeBuckets)
    from ..models.input import ShapeBuckets

    eval_buckets = ShapeBuckets.from_config(
        utils.env.raw("RMD_EVAL_BUCKETS") or env.eval.get("buckets"))
    if eval_buckets is not None:
        logging.info(f"validation shape buckets: {eval_buckets.describe()}")

    # non-finite step recovery policy: CLI flag > RMD_NONFINITE > env
    # config 'nonfinite' section. Default is the historical raise.
    from ..strategy.training import NonFinitePolicy

    nf_cfg = (getattr(args, "nonfinite", None)
              or utils.env.raw("RMD_NONFINITE")
              or env.nonfinite)
    nonfinite = NonFinitePolicy.from_config(nf_cfg)
    if nonfinite.policy != "raise":
        logging.info(f"non-finite step policy: {nonfinite.get_config()}")

    # on-device augmentation: --device-aug / RMD_DEVICE_AUG / the env
    # config's 'augment' section (enabled: true). The section's remaining
    # keys parameterize data.device_augment.DeviceAugment; off keeps the
    # historical host-side augmentation and registered-program identities.
    from ..data.device_augment import DeviceAugment

    aug_cfg = dict(env.augment or {})
    aug_on = bool(getattr(args, "device_aug", None)
                  or utils.env.get_bool("RMD_DEVICE_AUG")
                  or aug_cfg.pop("enabled", False))
    aug_cfg.pop("enabled", None)
    augment = DeviceAugment.from_config(aug_cfg) if aug_on else None
    if augment is not None:
        logging.info(f"on-device augmentation: {augment.describe()}")

    log = utils.logging.Logger()
    tctx = TrainingContext(
        log, path_out, strat, model_id, model_spec, model_adapter, loss, input,
        inspector, chkptm, mesh=mesh, step_limit=args.steps,
        loader_args=loader_args, wire=wire, eval_buckets=eval_buckets,
        nonfinite=nonfinite, accumulate=accumulate, augment=augment,
    )

    if args.checkpoint:
        logging.info(f"loading checkpoint '{args.checkpoint}'")
        warm = strategy.Checkpoint.load(args.checkpoint)
        tctx._ensure_variables(strat.stages[args.start_stage or 0])
        tctx.variables, _, _ = warm.apply(variables=tctx.variables)

    if args.resume == "auto":
        # preemption-safe auto-resume: find the newest valid checkpoint
        # (emergency saves included) under the output base directory —
        # corrupt files are quarantined and the next-newest one wins.
        # Stage/epoch/step reconstruct from the checkpoint's iteration.
        found = strategy.find_auto_resume(Path(args.output), model=model_id,
                                          log=log)
        if found is None:
            raise ValueError(
                f"--resume auto: no valid checkpoint for model "
                f"'{model_id}' found under '{args.output}'")
        resume_path, chkpt = found
        logging.info(
            f"auto-resume: picking up from '{resume_path}' "
            f"(stage {chkpt.iteration.stage}, epoch {chkpt.iteration.epoch}, "
            f"step {chkpt.iteration.step})")
        tele.emit("resume", path=str(resume_path), step=chkpt.iteration.step,
                  stage=chkpt.iteration.stage, epoch=chkpt.iteration.epoch)
    elif args.resume:
        logging.info(f"loading checkpoint '{args.resume}'")
        chkpt = strategy.Checkpoint.load(args.resume)
        tele.emit("resume", path=str(args.resume), step=chkpt.iteration.step)

    if args.detect_anomaly:
        log.warn("anomaly detection enabled")
        jax.config.update("jax_debug_nans", True)

    # §5.1 tracing: device-level profile of the (typically --limit-steps
    # bounded) run — the TPU analog of the reference's torch-tb-profiler
    # dev dependency
    profile_dir = getattr(args, "profile", None)
    if profile_dir:
        log.info(f"capturing jax.profiler trace to '{profile_dir}'")
        jax.profiler.start_trace(profile_dir)

    tele.emit("run_start", dir=str(path_out),
              commit=utils.vcs.get_git_head_hash(),
              comment=args.comment or "")

    # trainer observability sidecar: --metrics-port > RMD_TRAIN_METRICS_PORT;
    # serves /metrics, /healthz, /statusz, /profilez off the shared
    # telemetry.sidecar server (port 0 picks an ephemeral port)
    mport = getattr(args, "metrics_port", None)
    if mport is None and utils.env.is_set("RMD_TRAIN_METRICS_PORT"):
        mport = utils.env.get_int("RMD_TRAIN_METRICS_PORT")
    observer = None
    if mport is not None and primary:
        from ..telemetry import sidecar

        observer = sidecar.train_observer(tctx, mport, sink=tele,
                                          ledger=goodput.get())
        logging.info(f"trainer observability sidecar: {observer.url}")

    # preemption safety: SIGTERM/SIGINT finish the in-flight step, write
    # an emergency checkpoint, and return cleanly (--resume auto resumes)
    tctx.install_signal_handlers()

    try:
        tctx.run(args.start_stage, args.start_epoch, chkpt)
    except Exception:
        # crash postmortem: the nonfinite/preempt paths dump their own
        # bundle first (dump is once-per-process, first reason wins)
        blackbox.get().dump(path_out, "crash", tele=tele, step=tctx.step)
        raise
    finally:
        if profile_dir:
            jax.profiler.stop_trace()
            # graftprof attribution of the capture: advisory — never
            # let a parse failure mask the run's real exit path
            if utils.env.get_bool("RMD_PROFILE_ATTRIBUTION"):
                try:
                    from ..analysis import profile as prof

                    summary = prof.attribute_trace(profile_dir)
                    log.info("profile attribution:\n"
                             + prof.render_attribution(summary))
                except Exception as e:  # noqa: BLE001 - attribution is advisory
                    log.warn(f"profile attribution failed: "
                             f"{type(e).__name__}: {e}")
        if observer is not None:
            observer.close()
        ledger = goodput.get()
        if ledger.enabled:
            ledger.close()
            ledger.emit_event(tele, final=True, step=tctx.step)
        goodput.deactivate()
        blackbox.deactivate()
        tele.emit("run_end")
        tele.close()


def train(args):
    utils.debug.run(_train, args, debug=args.debug)
