"""The ``serve`` subcommand: online flow inference as a service.

Boots one replica (model + warm compiled-program pool), then either:

- ``--prebuild``: compile and AOT-export every (model, bucket, wire)
  triple of the serve config — with ``--ladder``, every iteration-rung
  program too — and exit: the deploy-time warm-pool builder (a replica
  booting against the exported store serves its first request with zero
  compiles);
- default: run the built-in open-loop load generator against the
  scheduler and print the SLO report (p50/p99 latency, pairs/s,
  shed/error counts; with ``--ladder``, the per-class breakdown) as
  JSON — the in-process serving harness the network frontend will
  mount.

Knob precedence everywhere: CLI flag > config file (``serve:`` section)
> ``RMD_SERVE_*`` environment knob > registered default.
"""

import json
import logging
import os
import signal
import subprocess
import sys
import threading
from pathlib import Path

from .. import models, serve as serving, utils


def _pick(cli, cfg, cfg_key, env_value):
    if cli is not None:
        return cli
    if cfg_key in cfg:
        return cfg[cfg_key]
    return env_value


def _resolve(path, cfg_path):
    """Config-file-relative path resolution (same contract as the data
    layer's spec refs): a relative path inside the serve config means
    "next to this file", not "under whatever CWD the CLI ran from"."""
    if cfg_path is None or Path(path).is_absolute():
        return path
    return str(Path(cfg_path).parent / path)


def serve(args):
    if getattr(args, "fleet", None):
        return _serve_fleet(args)

    utils.logging.setup()

    from .. import compile as programs, telemetry
    from ..utils import compcache, env

    tele = telemetry.get()
    if getattr(args, "telemetry", None):
        # serve uses the non-blocking sink: disk writes ride a bounded
        # background queue, a slow disk sheds trace events (counted)
        # instead of backpressuring the scheduler
        tele = telemetry.activate(
            telemetry.create(Path(args.telemetry), nonblocking=True))
        if tele.path:
            logging.info(f"writing telemetry events to '{tele.path}'")
    tele.emit(
        "boot",
        compile_cache=compcache.effective_dir(),
        aot_dir=str(programs.programs_dir()) if programs.aot_enabled()
        else None,
        aot=programs.aot_enabled(),
    )

    import jax

    from .train import select_devices

    devices = select_devices(args.device, args.device_ids)
    jax.config.update("jax_default_device", devices[0])

    cfg = {}
    if getattr(args, "config", None):
        cfg = utils.config.load(args.config)
        cfg = cfg.get("serve", cfg)

    model_src = args.model
    if model_src is None:
        model_src = cfg.get("model")
        if isinstance(model_src, str):
            model_src = _resolve(model_src, getattr(args, "config", None))
    if model_src is None:
        raise ValueError("serve needs a model: --model or the config's "
                         "'model' key")
    model_cfg = (utils.config.load(model_src) if isinstance(model_src, str)
                 else model_src)
    if "strategy" in model_cfg:
        model_cfg = model_cfg["model"]
    spec = models.load(model_cfg)
    logging.info(f"serving model '{spec.id}'")

    from ..models.input import ShapeBuckets
    from ..models.wire import WireFormat

    buckets_spec = _pick(args.buckets, cfg, "buckets",
                         env.raw("RMD_SERVE_BUCKETS"))
    buckets = ShapeBuckets.from_config(buckets_spec)
    if buckets is None or not buckets.sizes:
        raise ValueError(
            "serve needs explicit bucket sizes: --buckets 'HxW,...', the "
            "config's 'buckets' key, or RMD_SERVE_BUCKETS")
    logging.info(f"shape buckets: {buckets.describe()}")

    wire_cfg = _pick(getattr(args, "wire_format", None), cfg, "wire-format",
                     env.get_str("RMD_WIRE_FORMAT"))
    wire = WireFormat.from_config(wire_cfg)
    if wire is not None:
        logging.info(f"request wire format: {wire.describe()}")

    batch_size = int(_pick(args.batch_size, cfg, "batch-size",
                           env.get_int("RMD_SERVE_BATCH")))
    checkpoint = args.checkpoint
    if checkpoint is None and cfg.get("checkpoint") is not None:
        checkpoint = _resolve(cfg["checkpoint"],
                              getattr(args, "config", None))

    ladder_spec = _pick(getattr(args, "ladder", None), cfg, "ladder", None)
    ladder = None
    if ladder_spec:
        ladder = serving.LadderSpec.from_config(
            ladder_spec, threshold=_pick(
                getattr(args, "ladder_threshold", None), cfg,
                "ladder-threshold", None))
        logging.info(f"iteration ladder: {ladder.describe()}")

    video = bool(_pick(getattr(args, "video", None) or None, cfg,
                       "video", None))
    if video:
        logging.info("video sessions enabled: warm-start programs + "
                     "sticky per-client carry cache")

    quant = _pick(getattr(args, "quant", None), cfg, "quant",
                  env.get_str("RMD_QUANT"))
    if quant:
        logging.info(f"quantized matching tier: {quant} (fast class + "
                     "video warm frames)")

    session = serving.ServeSession(
        spec, buckets, wire=wire, checkpoint=checkpoint,
        batch_size=batch_size, ladder=ladder, video=video, quant=quant)

    aot_store = getattr(args, "aot_store", None)
    if aot_store and not getattr(args, "prebuild", False) \
            and programs.aot_enabled():
        fetched = programs.fetch(aot_store)
        logging.info(
            f"AOT store '{aot_store}': fetched {fetched['copied']} "
            f"programs ({fetched['present']} already local)")

    outcomes = session.warm_pool()
    for o in outcomes:
        rung = f" rung {o['rung']}" if "rung" in o else ""
        logging.info(
            f"warm pool: {o['model']} bucket {o['bucket']} batch "
            f"{o['batch']}{rung} [{o['wire']}] — {o['compiles']} compiles, "
            f"{o['aot_hits']} AOT hits, {o['aot_saves']} AOT saves "
            f"({o['seconds']:.2f} s)")

    if getattr(args, "prebuild", False):
        published = None
        if aot_store and programs.aot_enabled():
            published = programs.publish(aot_store)
            logging.info(
                f"AOT store '{aot_store}': published "
                f"{published['copied']} programs "
                f"({published['present']} already there)")
        print(json.dumps({"prebuild": outcomes, "published": published}))
        if getattr(args, "telemetry", None):
            telemetry.deactivate()
        return

    max_wait_ms = float(_pick(args.max_wait_ms, cfg, "max-wait-ms",
                              env.get_float("RMD_SERVE_MAX_WAIT_MS")))
    queue_limit = int(_pick(args.queue_limit, cfg, "queue-limit",
                            env.get_int("RMD_SERVE_QUEUE")))

    scheduler = serving.Scheduler(
        session, batch_size=batch_size, max_wait_ms=max_wait_ms,
        queue_limit=queue_limit).start()

    if getattr(args, "listen_port", None) is not None:
        _serve_replica_blocking(args, session, scheduler, tele)
        if getattr(args, "telemetry", None):
            telemetry.deactivate()
        return

    metrics_port = int(_pick(getattr(args, "metrics_port", None), cfg,
                             "metrics-port",
                             env.get_int("RMD_METRICS_PORT")) or 0)
    observer = None
    if metrics_port:
        observer = serving.serve_observer(
            session, scheduler, metrics_port, sink=tele)
        logging.info(
            f"observability plane at {observer.url}: /metrics /healthz "
            f"/statusz /profilez")

    # built-in open-loop client: every bucket size plus an off-bucket
    # variant of each (exercises quantization + partial batches)
    shapes = []
    for h, w in session.buckets.sizes:
        shapes.append((h, w))
        if h > 8 and w > 8:
            shapes.append((h - 8, w - 8))

    requests = int(_pick(args.requests, cfg, "requests", 32))
    rate = float(_pick(args.rate, cfg, "rate", 50.0))
    classes = list(serving.CLASSES) if ladder is not None else None
    if video:
        # sticky streams force the fast rung; class cycling is moot
        classes = None
    logging.info(f"open-loop load: {requests} requests at {rate}/s over "
                 f"{len(shapes)} shapes"
                 + (f", classes {'/'.join(classes)}" if classes else "")
                 + (", sticky video streams" if video else ""))

    report = serving.loadgen.run_open_loop(
        scheduler, shapes, requests=requests, rate_hz=rate, classes=classes,
        sequence=video)
    if scheduler.slo:
        report["slo"] = scheduler.slo.snapshot()
    tail = scheduler.trace_summary.tail()
    if tail is not None:
        report["tail"] = tail
    scheduler.stop(drain=True)

    logging.info(
        f"served {report['completed']}/{report['requests']} requests: "
        f"p50 {report['p50_ms']:.1f} ms, p99 {report['p99_ms']:.1f} ms, "
        f"{report['pairs_per_sec']:.2f} pairs/s")
    print(json.dumps(report))

    if observer is not None:
        observer.close()
    if getattr(args, "telemetry", None):
        telemetry.deactivate()


def _serve_replica_blocking(args, session, scheduler, tele):
    """Replica mode: bind the fleet API, write the port-file rendezvous,
    block until SIGTERM/SIGINT, then drain and exit cleanly."""
    from .. import fleet

    index = int(getattr(args, "replica_index", 0) or 0)
    observer = serving.Observer(session, scheduler, sink=tele)
    server = fleet.serve_replica(
        session, scheduler, observer, int(args.listen_port), index=index)
    logging.info(
        f"replica {index} serving at {server.url}: /v1/flow /sessionz "
        f"/drainz + /metrics /healthz /statusz /profilez")
    port_file = getattr(args, "port_file", None)
    if port_file:
        # atomic write: the supervisor polls this file and must never
        # read a torn port number
        tmp = f"{port_file}.tmp"
        Path(tmp).write_text(f"{server.port}\n")
        os.replace(tmp, port_file)

    stop = threading.Event()

    def _terminate(signum, frame):
        logging.info(f"replica {index}: signal {signum}, draining")
        observer.begin_drain()
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    while not stop.wait(1.0):
        pass
    scheduler.stop(drain=True)
    server.close()
    logging.info(f"replica {index}: drained and stopped")


def _child_argv(extra):
    """The replica child's command line: this CLI re-entered with the
    parent's serve flags minus the fleet-harness-only ones."""
    strip_valued = {"--fleet", "--telemetry", "--metrics-port",
                    "--listen-port", "--port-file", "--replica-index"}
    strip_flags = {"--drill", "--prebuild"}
    argv, skip = [], False
    for a in sys.argv[1:]:
        if skip:
            skip = False
            continue
        opt = a.split("=", 1)[0]
        if opt in strip_flags:
            continue
        if opt in strip_valued:
            skip = "=" not in a
            continue
        argv.append(a)
    head = [sys.executable]
    script = sys.argv[0]
    if script and script.endswith(".py") and Path(script).exists():
        head.append(script)
    else:
        head += ["-c",
                 "from raft_meets_dicl_tpu.main import main; main()"]
    return head + argv + extra


def _serve_fleet(args):
    """Fleet mode: supervise N replica processes behind the router,
    then drive them (open-loop load or the kill/rejoin drill)."""
    utils.logging.setup()

    from .. import fleet, telemetry
    from ..models.input import ShapeBuckets
    from ..models.wire import WireFormat
    from ..utils import env

    tele = telemetry.get()
    if getattr(args, "telemetry", None):
        tele = telemetry.activate(
            telemetry.create(Path(args.telemetry), nonblocking=True))
        if tele.path:
            logging.info(f"writing telemetry events to '{tele.path}'")

    cfg = {}
    if getattr(args, "config", None):
        cfg = utils.config.load(args.config)
        cfg = cfg.get("serve", cfg)
    buckets = ShapeBuckets.from_config(
        _pick(args.buckets, cfg, "buckets", env.raw("RMD_SERVE_BUCKETS")))
    if buckets is None or not buckets.sizes:
        raise ValueError(
            "fleet mode needs explicit bucket sizes: --buckets 'HxW,...', "
            "the config's 'buckets' key, or RMD_SERVE_BUCKETS")
    wire = WireFormat.from_config(
        _pick(getattr(args, "wire_format", None), cfg, "wire-format",
              env.get_str("RMD_WIRE_FORMAT")))
    ladder_spec = _pick(getattr(args, "ladder", None), cfg, "ladder", None)
    video = bool(_pick(getattr(args, "video", None) or None, cfg,
                       "video", None))

    n = int(args.fleet) if int(args.fleet) > 0 \
        else env.get_int("RMD_FLEET_REPLICAS")
    logging.info(f"fleet: {n} replicas, buckets {buckets.describe()}"
                 + (f", wire {wire.describe()}" if wire else ""))

    def spawn(index, port_file):
        argv = _child_argv(["--listen-port", "0",
                            "--port-file", port_file,
                            "--replica-index", str(index)])
        return subprocess.Popen(argv, env=os.environ.copy())

    codec = fleet.EdgeCodec(buckets, wire=wire)
    router = fleet.Router(codec).start()
    sup = fleet.Supervisor(
        spawn, n,
        on_up=lambda i, url: router.add_replica(f"replica-{i}", url),
        on_down=lambda i: router.mark_down(f"replica-{i}"))
    router.on_recycle = lambda name: sup.recycle(
        int(name.rsplit("-", 1)[1]))  # graftlint: disable=host-sync -- parses a replica name, not a device value

    frontend = None
    report = {}
    try:
        sup.start(wait_ready=True)
        for slot in sup.slots:
            if slot.url:
                router.add_replica(slot.name, slot.url)
        ready = sum(1 for s in router.replicas().values() if s.eligible())
        if ready == 0:
            raise RuntimeError("fleet: no replica came up healthy")
        logging.info(f"fleet: {ready}/{n} replicas ready")

        metrics_port = int(_pick(getattr(args, "metrics_port", None), cfg,
                                 "metrics-port",
                                 env.get_int("RMD_METRICS_PORT")) or 0)
        if metrics_port:
            frontend = fleet.serve_frontend(router, metrics_port)
            logging.info(f"fleet front-end at {frontend.url}: /v1/flow "
                         f"/fleetz /healthz")

        shapes = []
        for h, w in buckets.sizes:
            shapes.append((h, w))
            if h > 8 and w > 8:
                shapes.append((h - 8, w - 8))
        classes = list(serving.CLASSES) if ladder_spec else None
        if video:
            classes = None

        if getattr(args, "drill", False):
            def kill(owner):
                index = int(owner.rsplit("-", 1)[1]) if owner else 0  # graftlint: disable=host-sync -- parses a replica name, not a device value
                logging.info(f"drill: hard-killing replica-{index}")
                sup.kill(index)
                return f"replica-{index}"

            report = fleet.run_drill(
                router, kill, shapes,
                classes=tuple(classes) if classes else (None,),
                frames=int(_pick(args.requests, cfg, "requests", 24)))
            report = {"fleet": n, "drill": report}
        else:
            requests = int(_pick(args.requests, cfg, "requests", 32))
            rate = float(_pick(args.rate, cfg, "rate", 50.0))
            report = serving.loadgen.run_open_loop(
                router, shapes, requests=requests, rate_hz=rate,
                classes=classes, sequence=video)
            report = {"fleet": n, **report}
    finally:
        report["router"] = router.describe()
        report["supervisor"] = sup.describe()
        if frontend is not None:
            frontend.close()
        router.stop()
        sup.stop()

    print(json.dumps(report))
    if getattr(args, "telemetry", None):
        telemetry.deactivate()
