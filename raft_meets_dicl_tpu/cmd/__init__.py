from . import checkpoint as checkpoint_mod
from . import eval as eval_mod
from . import gencfg, serve as serve_mod, train as train_mod

train = train_mod.train
evaluate = eval_mod.evaluate
checkpoint = checkpoint_mod.checkpoint
generate_config = gencfg.generate_config
serve = serve_mod.serve

__all__ = ["train", "evaluate", "checkpoint", "generate_config", "serve"]
