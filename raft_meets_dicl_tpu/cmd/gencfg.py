"""The ``gencfg`` subcommand: merge config parts into one full config.

Capability parity with the reference (src/cmd/gencfg.py:14-103); the output
is the same reproducible full-config format that ``train --config`` and the
eval command's model-section extraction accept.
"""

import datetime
import logging
from pathlib import Path

from .. import inspect as inspect_
from .. import models, strategy, utils
from .train import Environment, load_config_parts


def generate_config(args):
    timestamp = datetime.datetime.now()

    utils.logging.setup()

    cfg_seeds, cfg_env, cfg_model, cfg_strat, cfg_inspc, base_path = \
        load_config_parts(args)

    if cfg_seeds is not None:
        logging.info("seeding: using seeds from config")
        seeds = utils.seeds.from_config(cfg_seeds)
    else:
        seeds = utils.seeds.random_seeds()
    seeds.apply()

    env = Environment.load(cfg_env)

    if cfg_model is None:
        raise ValueError("no model configuration specified")
    model = models.load(cfg_model)

    if cfg_strat is None:
        raise ValueError("no strategy/data configuration specified")
    if isinstance(cfg_strat, str):
        strat = strategy.load(cfg_strat)
    else:
        strat = strategy.load(base_path, cfg_strat)

    inspc = inspect_.load(cfg_inspc)

    logging.info(f"storing configuration: file='{args.output}'")
    utils.config.store(args.output, {
        "timestamp": timestamp.isoformat(),
        "commit": utils.vcs.get_git_head_hash(),
        "cwd": str(Path.cwd()),
        "args": {k: v for k, v in vars(args).items() if k != "comment"},
        "seeds": seeds.get_config(),
        "model": model.get_config(),
        "strategy": strat.get_config(),
        "inspect": inspc.get_config(),
        "environment": env.get_config(),
    })
