"""CLI argument parsing and dispatch.

Flag-surface parity with the reference (src/main.py:34-117); device flags
select the jax platform / device subset instead of CUDA ordinals, and
``--detect-anomaly`` maps to ``jax_debug_nans``.

Example usage:
- basic training
    ./main.py train --data strategy.yaml --model model.yaml
    ./main.py train --config config.json
- warm start (weights only) vs resume (full state)
    ./main.py train -d data.yaml -m model.yaml --checkpoint chkpt.ckpt
    ./main.py train --config config.json --resume chkpt.ckpt
- evaluation with report + flow images
    ./main.py evaluate -d data.yaml -m model.yaml -c chkpt.ckpt -o report.json
- checkpoint management
    ./main.py checkpoint info runs/<ts>/checkpoints --sort '{m_EndPointError_mean}'
    ./main.py checkpoint trim dir/ --compare '{m_EndPointError_mean}' --keep-best 5
- full-config generation
    ./main.py gencfg -o full.json -d strategy.yaml -m model.yaml
"""

import argparse

from . import cmd


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Optical Flow Estimation (TPU-native)", formatter_class=fmtcls
    )
    subp = parser.add_subparsers(dest="command", help="help for command")

    # subcommand: train
    train = subp.add_parser("train", aliases=["t"], formatter_class=fmtcls,
                            help="train model")
    train.add_argument("-c", "--config", help="full training configuration")
    train.add_argument("-d", "--data", help="training strategy and data")
    train.add_argument("-m", "--model", help="specification of the model")
    train.add_argument("-s", "--seeds", help="seed config for initializing RNGs")
    train.add_argument("-i", "--inspect", help="specification of metrics")
    train.add_argument("-e", "--env", "--environment", dest="env",
                       help="environment config")
    train.add_argument("-o", "--output", default="runs",
                       help="base output directory [default: %(default)s]")
    train.add_argument("--device",
                       help="jax platform to use (tpu, cpu) [default: backend default]")
    train.add_argument("--device-ids",
                       help="comma-separated device indices for the SPMD data mesh")
    train.add_argument("--checkpoint",
                       help="start with pre-trained model state from checkpoint")
    train.add_argument("--resume",
                       help="resume training from checkpoint (full state); "
                            "'auto' discovers the newest valid checkpoint "
                            "(emergency saves included) under the output "
                            "directory, quarantining corrupt files")
    train.add_argument("--nonfinite", choices=["raise", "skip", "rollback"],
                       help="non-finite step recovery policy: raise (abort, "
                            "default), skip (drop the poisoned optimizer "
                            "update on device and continue), rollback "
                            "(skip, then restore the last valid checkpoint "
                            "when trips persist). Also: RMD_NONFINITE or "
                            "the env config's 'nonfinite' section")
    train.add_argument("--start-stage", type=int,
                       help="start with specified stage and skip previous")
    train.add_argument("--start-epoch", type=int,
                       help="start with specified epoch and skip previous")
    train.add_argument("--reproduce", action="store_true", help="use seeds from config")
    train.add_argument("--debug", action="store_true", help="enter debugger on exception")
    train.add_argument("--detect-anomaly", action="store_true",
                       help="enable jax nan-debugging (jax_debug_nans)")
    train.add_argument("--suffix", "--sfx", dest="suffix",
                       help="suffix for output directory")
    train.add_argument("--comment", dest="comment", help="comment to add to config file")
    train.add_argument("--limit-steps", type=int, dest="steps",
                       help="limit to a fixed number of steps")
    train.add_argument("--distributed", action="store_true",
                       help="join the multi-process runtime "
                            "(jax.distributed.initialize; on TPU pods "
                            "coordinator/rank are auto-discovered)")
    train.add_argument("--dist-coordinator", metavar="HOST:PORT",
                       help="coordinator address for non-TPU setups")
    train.add_argument("--dist-num-processes", type=int,
                       help="total process count for non-TPU setups")
    train.add_argument("--dist-process-id", type=int,
                       help="this process's id for non-TPU setups")
    train.add_argument("--profile", metavar="DIR",
                       help="capture a jax.profiler trace of the run into DIR "
                            "(open with TensorBoard's profile plugin); "
                            "combine with --limit-steps")
    train.add_argument("--telemetry", metavar="PATH",
                       help="telemetry JSONL sink path "
                            "[default: <run-dir>/events.jsonl]")
    train.add_argument("--compile-cache", metavar="DIR",
                       help="persistent XLA compile cache directory "
                            "(also: RMD_COMPILE_CACHE; "
                            "RMD_NO_COMPILE_CACHE=1 disables) "
                            "[default: <repo>/.jax_cache]. The AOT "
                            "program store lives in DIR/programs "
                            "(RMD_AOT=0 disables, RMD_AOT_DIR relocates)")
    train.add_argument("--no-telemetry", action="store_true",
                       help="disable run telemetry "
                            "(equivalent to RMD_TELEMETRY=0)")
    train.add_argument("--metrics-port", type=int, metavar="PORT",
                       help="trainer observability HTTP port on "
                            "127.0.0.1: /metrics (Prometheus text), "
                            "/healthz, /statusz, /profilez?seconds=N; "
                            "0 picks an ephemeral port (also: "
                            "RMD_TRAIN_METRICS_PORT) [default: off]")
    train.add_argument("--wire-format", choices=["f32", "bf16", "u8"],
                       help="host->device batch wire format: compact image "
                            "dtype + on-device normalization (also: "
                            "RMD_WIRE_FORMAT or the env config's 'wire' "
                            "section) [default: host-normalized f32]")
    train.add_argument("--loader-procs", type=int, metavar="N",
                       help="decode the input pipeline in N worker "
                            "processes (shared-memory transport); 0 = "
                            "thread pool (also: RMD_LOADER_PROCS)")
    train.add_argument("--mesh", metavar="DATA,MODEL",
                       help="SPMD mesh shape: 'D,M' (e.g. '4,2') builds a "
                            "2-D data×model mesh whose model axis shards "
                            "param/optimizer storage (regex partition "
                            "rules, parallel.partition); 'data' or unset "
                            "keeps the 1-D replicated-params data mesh; "
                            "D=-1 fills the remaining devices (also: "
                            "RMD_MESH or the env config's 'parallel' "
                            "section)")
    train.add_argument("--device-aug", action="store_true", dest="device_aug",
                       help="compile the augmentation pipeline into the "
                            "train step (on-device data engine): one fused "
                            "inverse-affine warp + elementwise photometric "
                            "ops under per-sample (sample_id, epoch) keys "
                            "(also: RMD_DEVICE_AUG or the env config's "
                            "'augment' section, which tunes the parameters)")
    train.add_argument("--accumulate", type=int, metavar="K",
                       help="in-step gradient accumulation: scan K "
                            "microbatches per optimizer step inside the "
                            "jitted train step — K× effective batch at "
                            "one microbatch's activation memory (also: "
                            "RMD_ACCUMULATE or the env config's "
                            "'parallel' section)")

    # subcommand: evaluate
    eval_ = subp.add_parser("evaluate", aliases=["e", "eval"], formatter_class=fmtcls,
                            help="evaluate model")
    eval_.add_argument("-d", "--data", required=True, help="evaluation dataset")
    eval_.add_argument("-m", "--model", required=True, help="the model to use")
    eval_.add_argument("-c", "--checkpoint", required=True, help="the checkpoint to load")
    eval_.add_argument("-b", "--batch-size", type=int, default=1,
                       help="batch-size to use for evaluation")
    eval_.add_argument("--iterations", type=int,
                       help="recurrence iteration override for the "
                            "model's update loop (also: RMD_ITERATIONS) "
                            "[default: model config]")
    eval_.add_argument("-x", "--metrics",
                       help="specification of metrics to use for evaluation")
    eval_.add_argument("-o", "--output",
                       help="write detailed output to this file (json or yaml)")
    eval_.add_argument("--incremental", metavar="PATH",
                       help="append per-sample metrics to this JSONL as the "
                            "sweep runs, so a crash keeps partial results "
                            "[default: <output>.samples.jsonl when -o is "
                            "set]")
    eval_.add_argument("--no-incremental", action="store_true",
                       help="disable the incremental per-sample JSONL")
    eval_.add_argument("-f", "--flow",
                       help="compute and write flow images to specified directory")
    from .cmd.eval import FLOW_FORMATS

    eval_.add_argument("--flow-format", default="visual:flow",
                       choices=FLOW_FORMATS, metavar="FORMAT",
                       help="output format for flow images [default: %(default)s]")
    eval_.add_argument("--flow-mrm", type=float,
                       help="maximum range of motion for visual flow image output")
    eval_.add_argument("--flow-gamma", type=float,
                       help="gamma for visual:flow image output")
    eval_.add_argument("--flow-transform",
                       help="transform for visual:flow:dark image output")
    eval_.add_argument("--flow-only", action="store_true",
                       help="only compute flow images, do not evaluate metrics")
    eval_.add_argument("--fwbw", action="store_true",
                       help="also run the reversed pair per sample and "
                            "derive forwards-backwards consistency "
                            "products (occlusion masks + confidence; "
                            "enables the visual:occlusion and "
                            "visual:confidence flow formats)")
    eval_.add_argument("--epe-cmap", default="gray",
                       help="colormap for end-point-error visualization")
    eval_.add_argument("--epe-max", type=float, default=None,
                       help="maximum end point error for visualization")
    eval_.add_argument("--device",
                       help="jax platform to use (tpu, cpu) [default: backend default]")
    eval_.add_argument("--device-ids",
                       help="comma-separated device indices")
    eval_.add_argument("--wire-format", choices=["f32", "bf16", "u8"],
                       help="host->device batch wire format (compact image "
                            "dtype, on-device normalization) "
                            "[default: host-normalized f32]")
    eval_.add_argument("--buckets", metavar="SPEC",
                       help="shape buckets for mixed-resolution datasets: "
                            "'group' (batch same-shape samples) or a "
                            "comma-separated HxW list, e.g. "
                            "'384x1280,448x1024' (quantize + batch; at "
                            "most one jit compile per bucket). Also: "
                            "RMD_EVAL_BUCKETS")
    eval_.add_argument("--precompile", action="store_true",
                       help="compile every declared bucket shape before "
                            "the sweep (requires explicit --buckets sizes)")
    eval_.add_argument("--compile-cache", metavar="DIR",
                       help="persistent XLA compile cache directory "
                            "(also: RMD_COMPILE_CACHE) "
                            "[default: <repo>/.jax_cache]; AOT program "
                            "store in DIR/programs (RMD_AOT=0 disables)")
    eval_.add_argument("--telemetry", metavar="PATH",
                       help="write sweep telemetry events (eval stats, "
                            "compile attribution, AOT hits/misses) to "
                            "this JSONL file")

    # subcommand: serve
    serve = subp.add_parser("serve", formatter_class=fmtcls,
                            help="serve flow inference (continuous "
                                 "shape-bucketed batching)")
    serve.add_argument("-c", "--config",
                       help="serve configuration (yaml/json with a "
                            "'serve' section; CLI flags win)")
    serve.add_argument("-m", "--model", help="model specification to serve")
    serve.add_argument("--checkpoint", help="checkpoint to load")
    serve.add_argument("--buckets", metavar="SPEC",
                       help="canonical request shapes, comma-separated "
                            "HxW list, e.g. '384x1280,448x1024' "
                            "(required; also: RMD_SERVE_BUCKETS or the "
                            "config's 'buckets' key)")
    serve.add_argument("--wire-format", choices=["f32", "bf16", "u8"],
                       help="request wire format: compact image dtype "
                            "decoded inside the jitted program "
                            "[default: host-normalized f32]")
    serve.add_argument("-b", "--batch-size", type=int,
                       help="device batch size per dispatch (also: "
                            "RMD_SERVE_BATCH) [default: 4]")
    serve.add_argument("--max-wait-ms", type=float,
                       help="max time a partial batch waits before "
                            "dispatching padded (also: "
                            "RMD_SERVE_MAX_WAIT_MS) [default: 50]")
    serve.add_argument("--queue-limit", type=int,
                       help="per-bucket admission queue bound; overload "
                            "sheds with a typed rejection (also: "
                            "RMD_SERVE_QUEUE) [default: 64]")
    serve.add_argument("--ladder", nargs="?", const=True, metavar="RUNGS",
                       help="serve latency classes (fast/balanced/"
                            "quality) over an iteration ladder; optional "
                            "ascending rung budgets, e.g. '4,8,12' "
                            "(also: RMD_LADDER, the config's 'ladder' "
                            "key) [default: off]")
    serve.add_argument("--ladder-threshold", type=float,
                       help="flow-delta norm below which the balanced "
                            "class stops escalating (also: "
                            "RMD_LADDER_THRESHOLD) [default: 0.1]")
    serve.add_argument("--video", action="store_true",
                       help="video sessions: register the warm-start "
                            "program per bucket, cache per-client carry "
                            "state (bounded + TTL-evicted), and route "
                            "sequence requests onto it; the built-in "
                            "client then submits sticky frame streams "
                            "(also: the config's 'video' key) "
                            "[default: off]")
    serve.add_argument("--quant", nargs="?", const="u8",
                       choices=["u8", "i8", "off"], metavar="MODE",
                       help="quantized matching tier for the fast ladder "
                            "class and video warm frames: correlation "
                            "volumes stored u8/i8 and dequantized "
                            "in-register by the lookup ('u8' when given "
                            "bare; also: RMD_QUANT, the config's 'quant' "
                            "key) [default: off]")
    serve.add_argument("--prebuild", action="store_true",
                       help="compile + AOT-export every (model, bucket, "
                            "wire) program triple — with --ladder, every "
                            "rung program too — and exit (deploy-time "
                            "warm-pool build)")
    serve.add_argument("--requests", type=int,
                       help="built-in open-loop client: request count "
                            "[default: 32]")
    serve.add_argument("--rate", type=float,
                       help="built-in open-loop client: submissions/s "
                            "[default: 50]")
    serve.add_argument("--device",
                       help="jax platform to use (tpu, cpu) [default: backend default]")
    serve.add_argument("--device-ids",
                       help="comma-separated device indices")
    serve.add_argument("--compile-cache", metavar="DIR",
                       help="persistent XLA compile cache directory "
                            "(also: RMD_COMPILE_CACHE) "
                            "[default: <repo>/.jax_cache]; AOT program "
                            "store in DIR/programs (RMD_AOT=0 disables)")
    serve.add_argument("--telemetry", metavar="PATH",
                       help="write serve telemetry events (request "
                            "spans, batches, rejects, warm-pool "
                            "outcomes) to this JSONL file")
    serve.add_argument("--metrics-port", type=int, metavar="PORT",
                       help="observability HTTP port on 127.0.0.1: "
                            "/metrics (Prometheus text), /healthz, "
                            "/statusz, /profilez?seconds=N (also: "
                            "RMD_METRICS_PORT, the config's "
                            "'metrics-port' key) [default: off]")
    serve.add_argument("--fleet", type=int, metavar="N",
                       help="fault-tolerant fleet: supervise N replica "
                            "processes behind the routing front-end "
                            "(least-loaded dispatch, retry, drain, "
                            "session handoff; also: RMD_FLEET_REPLICAS) "
                            "[default: single process]")
    serve.add_argument("--drill", action="store_true",
                       help="with --fleet: run the kill/rejoin chaos "
                            "drill instead of the plain open-loop client "
                            "(hard-kills a replica mid-stream, asserts "
                            "typed sheds only, <=1 cold frame, warm "
                            "rejoin)")
    serve.add_argument("--aot-store", metavar="DIR",
                       help="published AOT program store: --prebuild "
                            "publishes built programs into DIR; a "
                            "booting replica fetches from DIR before "
                            "warming (zero-compile boot)")
    serve.add_argument("--listen-port", type=int, metavar="PORT",
                       help="replica mode: serve the fleet API "
                            "(/v1/flow /sessionz /drainz + the "
                            "observability routes) on this 127.0.0.1 "
                            "port (0 = ephemeral) and block until "
                            "SIGTERM drains")
    serve.add_argument("--port-file", metavar="PATH",
                       help="replica mode: write the bound port here "
                            "once serving (the supervisor's rendezvous)")
    serve.add_argument("--replica-index", type=int, default=0,
                       metavar="I",
                       help="replica mode: this replica's fleet slot "
                            "index (labels telemetry + chaos triggers)")

    # subcommand: checkpoint
    chkpt = subp.add_parser("checkpoint", formatter_class=fmtcls,
                            help="inspect and manage checkpoints")
    chkpt_sub = chkpt.add_subparsers(dest="subcommand", help="help for subcommand")

    chkpt_info = chkpt_sub.add_parser("info", formatter_class=fmtcls,
                                      help="show info on checkpoint(s)")
    chkpt_info.add_argument("file", nargs="+",
                            help="checkpoint file or directory to search")
    chkpt_info.add_argument("--sort",
                            help="expression(s) for sorting checkpoints (comma-separated)")

    chkpt_trim = chkpt_sub.add_parser("trim", formatter_class=fmtcls,
                                      help="remove bad and/or outdated checkpoints")
    chkpt_trim.add_argument("directory", nargs="+",
                            help="directory to search for checkpoints")
    chkpt_trim.add_argument("--compare",
                            help="expression(s) for comparing checkpoints (comma-separated)")
    chkpt_trim.add_argument("--keep-latest", type=int,
                            help="keep specified number of latest checkpoints")
    chkpt_trim.add_argument("--keep-best", type=int,
                            help="keep specified number of best checkpoints")

    # subcommand: gencfg
    gencfg = subp.add_parser("gencfg", formatter_class=fmtcls,
                             help="generate full config from parts")
    gencfg.add_argument("-o", "--output", required=True, help="output file")
    gencfg.add_argument("-c", "--config", help="full training configuration")
    gencfg.add_argument("-d", "--data", help="training strategy and data")
    gencfg.add_argument("-m", "--model", help="specification of the model")
    gencfg.add_argument("-s", "--seeds", help="seed config for initializing RNGs")
    gencfg.add_argument("-i", "--inspect", help="specification of metrics")
    gencfg.add_argument("-e", "--env", "--environment", dest="env",
                       help="environment config")

    args = parser.parse_args()

    # persistent compile cache + AOT program store: configured after
    # parsing (--compile-cache wins over RMD_COMPILE_CACHE over the
    # default) but before any backend use
    import os

    from . import compile as programs
    from .utils.compcache import enable_persistent_cache

    if getattr(args, "compile_cache", None):
        # export so lower-precedence config (the env file's 'compile'
        # section) can see the flag won
        os.environ["RMD_COMPILE_CACHE"] = args.compile_cache
    enable_persistent_cache(getattr(args, "compile_cache", None))
    programs.enable_aot()

    commands = {
        "checkpoint": cmd.checkpoint,
        "evaluate": cmd.evaluate,
        "e": cmd.evaluate,
        "eval": cmd.evaluate,
        "gencfg": cmd.generate_config,
        "serve": cmd.serve,
        "train": cmd.train,
        "t": cmd.train,
    }

    if args.command is None:
        parser.print_help()
        return

    commands[args.command](args)
