"""Forwards-backwards product visualizations.

Occlusion masks render as a tinted overlay on the first frame (occluded
pixels stand out against the image that produced them); confidence maps
go through a matplotlib colormap like the EPE visualization. Both return
(H, W, 4) float RGBA in [0, 1], the shared contract of this package.
"""

import matplotlib.cm
import matplotlib.colors
import numpy as np


def occlusion_overlay(img, occlusion, color=(1.0, 0.1, 0.1), strength=0.65):
    """Occlusion mask over ``img``: (H, W, 4) in [0, 1].

    ``img`` is (H, W, 3) in [0, 1] (or None for a plain mask render);
    ``occlusion`` (H, W) bool, True where the forwards-backwards check
    flagged the pixel. Occluded pixels blend toward ``color`` by
    ``strength``; the rest keep the (dimmed) image so the mask reads in
    context.
    """
    occlusion = np.asarray(occlusion, bool)
    rgba = np.zeros((*occlusion.shape, 4))
    rgba[..., 3] = 1.0

    if img is not None:
        rgba[..., :3] = np.clip(np.asarray(img, np.float64), 0.0, 1.0)

    tint = np.asarray(color, np.float64)
    rgba[occlusion, :3] = ((1.0 - strength) * rgba[occlusion, :3]
                           + strength * tint)
    return rgba


def confidence_to_rgba(confidence, cmap="viridis", vmin=0.0, vmax=1.0):
    """Colormapped confidence map (H, W, 4) in [0, 1].

    ``confidence`` is the (H, W) float map from the forwards-backwards
    products (1 = consistent, 0 = inconsistent/out-of-bounds); the
    default fixed [0, 1] normalization keeps frames of a sequence
    comparable.
    """
    conf = np.nan_to_num(np.asarray(confidence, np.float64))
    norm = matplotlib.colors.Normalize(vmin=vmin, vmax=vmax)
    return matplotlib.colormaps[cmap](norm(conf))
