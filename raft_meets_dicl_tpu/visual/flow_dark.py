"""Dark-background flow color coding after Bruhn (2006).

Hue encodes direction (piecewise-remapped to emphasize horizontal motion),
value encodes magnitude on black. Capability parity with reference
src/visual/flow_dark.py:9.
"""

import warnings

import numpy as np


def _hsv_to_rgb(h, s, v):
    """Vectorized HSV → RGB, all inputs/outputs in [0, 1]."""
    i = np.floor(h * 6.0).astype(np.int64) % 6
    f = h * 6.0 - np.floor(h * 6.0)
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))

    lut = np.stack([
        np.stack([v, t, p], -1),
        np.stack([q, v, p], -1),
        np.stack([p, v, t], -1),
        np.stack([p, q, v], -1),
        np.stack([t, p, v], -1),
        np.stack([v, p, q], -1),
    ], 0)
    return np.take_along_axis(lut, i[None, ..., None], axis=0)[0]


def flow_to_rgba(uv, mask=None, mrm=None, gamma=1.0, transform=None,
                 mask_color=(0, 0, 0, 1), nan_color=(0, 0, 0, 1)):
    """Color-code a flow field (H, W, 2) as RGBA on a dark background.

    ``transform`` may be 'log' or 'loglog' to compress the magnitude scale.
    """
    if transform not in (None, "log", "loglog"):
        raise ValueError("invalid value for parameter 'transform'")

    uv = np.array(uv, dtype=np.float64)
    u, v = uv[..., 0], uv[..., 1]

    if mask is not None:
        mask = np.asarray(mask, bool)
        u = np.where(mask, u, 0.0)
        v = np.where(mask, v, 0.0)

    bogus = ~(np.isfinite(u) & np.isfinite(v))
    if bogus.any():
        warnings.warn("encountered non-finite values in flow field",
                      RuntimeWarning, stacklevel=2)
        u = np.where(bogus, 0.0, u)
        v = np.where(bogus, 0.0, v)

    length = np.hypot(u, v) ** gamma
    if mrm is None:
        mrm = float(np.max(length if mask is None else length * mask)) or 1.0

    # direction → hue: [0,90)° stretches over 60 hue-degrees, [90,180) over
    # the next 60, [180,360) over the remaining 240 (Bruhn's remapping)
    deg = np.rad2deg(-np.arctan2(v, u)) % 360.0
    hue = np.where(
        deg < 90.0, deg * (60.0 / 90.0),
        np.where(deg < 180.0, (deg - 90.0) * (60.0 / 90.0) + 60.0,
                 (deg - 180.0) * (240.0 / 180.0) + 120.0),
    ) / 360.0

    value = length / mrm
    for _ in range(("log", "loglog").index(transform) + 1 if transform else 0):
        value = np.log10(9.0 * value + 1.0)
    value = np.clip(value, 0.0, 1.0)

    rgb = _hsv_to_rgb(hue, np.ones_like(hue), value)

    rgba = np.concatenate([rgb, np.ones_like(rgb[..., :1])], axis=-1)
    rgba[bogus] = np.asarray(nan_color, dtype=np.float64)
    if mask is not None:
        rgba[~mask] = np.asarray(mask_color, dtype=np.float64)

    return rgba
