"""Backwards-warp preview: resample img2 by the estimated flow.

Host-facing wrapper over the jax warp op (capability parity with reference
src/visual/warp.py:6-14, which wraps the torch warp).
"""

import numpy as np

from ..ops import warp as _warp


def warp_backwards(img2, flow, eps=1e-5):
    """Warp a single HWC image by an HW2 flow field; returns HWC numpy."""
    est, _mask = _warp.warp_backwards(
        np.asarray(img2, np.float32)[None],
        np.asarray(flow, np.float32)[None],
        eps=eps,
    )
    return np.asarray(est[0])
