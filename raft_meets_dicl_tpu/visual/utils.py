"""Small color-layout helpers (reference src/visual/utils.py)."""

import numpy as np


def rgba_to_bgra(rgba):
    """RGBA → BGRA channel swap for cv2 writers."""
    return np.ascontiguousarray(np.asarray(rgba)[..., [2, 1, 0, 3]])
