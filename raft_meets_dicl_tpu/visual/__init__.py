"""Flow visualization: Middlebury/dark color coding, EPE and Fl maps.

Capability parity with reference src/visual/__init__.py.
"""

from . import (bad_pixel, epe, flow_dark, flow_mb, imshow, occlusion, utils,
               warp)

end_point_error = epe.end_point_error
end_point_error_abs = epe.end_point_error_abs
fl_error = bad_pixel.fl_error
flow_to_rgba = flow_mb.flow_to_rgba
flow_to_rgba_dark = flow_dark.flow_to_rgba
warp_backwards = warp.warp_backwards
occlusion_overlay = occlusion.occlusion_overlay
confidence_to_rgba = occlusion.confidence_to_rgba

show_image = imshow.show_image
show_flow = imshow.show_flow
show_flow_dark = imshow.show_flow_dark

__all__ = [
    "bad_pixel", "epe", "flow_dark", "flow_mb", "imshow", "occlusion",
    "utils", "warp",
    "end_point_error", "end_point_error_abs", "fl_error", "flow_to_rgba",
    "flow_to_rgba_dark", "warp_backwards", "occlusion_overlay",
    "confidence_to_rgba", "show_image", "show_flow", "show_flow_dark",
]
