"""End-point-error visualizations.

Absolute variant uses the logarithmic threshold palette of Menze et al.,
"Object Scene Flow" (as realized in cv-stuttgart/flow_library); relative
variant maps EPE through a matplotlib colormap. Capability parity with
reference src/visual/epe.py:9,55.
"""

import matplotlib.cm
import matplotlib.colors
import numpy as np

# (upper EPE threshold, RGB) — logarithmic scale, doubling per band
_ABS_BANDS = (
    (0.1875, (49, 53, 148)),
    (0.375, (69, 116, 180)),
    (0.75, (115, 173, 209)),
    (1.5, (171, 216, 233)),
    (3.0, (223, 242, 248)),
    (6.0, (254, 223, 144)),
    (12.0, (253, 173, 96)),
    (24.0, (243, 108, 67)),
    (48.0, (215, 48, 38)),
    (np.inf, (165, 0, 38)),
)


def end_point_error_abs(uv, uv_target, mask=None, mask_color=(0, 0, 0, 1),
                        nan_color=(0, 0, 0, 1)):
    """Banded absolute-EPE map (H, W, 4) in [0, 1]."""
    epe = np.linalg.norm(np.asarray(uv_target, np.float64) - uv, axis=-1)

    bogus = ~np.isfinite(epe)
    epe = np.nan_to_num(epe)

    rgba = np.zeros((*epe.shape, 4))
    rgba[..., 3] = 1.0
    for threshold, rgb in reversed(_ABS_BANDS):
        rgba[epe < threshold, :3] = np.asarray(rgb) / 255.0

    rgba[bogus] = np.asarray(nan_color, dtype=np.float64)
    if mask is not None:
        rgba[~np.asarray(mask, bool)] = np.asarray(mask_color, dtype=np.float64)

    return rgba


def end_point_error(uv, uv_target, mask=None, ord=2, cmap="gray", vmin=0.0,
                    vmax=None, mask_color=(0, 0, 0, 1)):
    """Colormapped EPE map (H, W, 4); default grayscale, auto-scaled."""
    d = np.linalg.norm(np.asarray(uv_target, np.float64) - uv, axis=-1, ord=ord)

    if mask is not None:
        mask = np.asarray(mask, bool)
        d = d * mask

    norm = matplotlib.colors.Normalize(vmin=vmin, vmax=vmax)
    rgba = matplotlib.colormaps[cmap](norm(d))

    if mask is not None:
        rgba[~mask] = np.asarray(mask_color, dtype=np.float64)

    return rgba
