"""Middlebury optical-flow color coding.

Implements the color wheel of Baker et al., "A Database and Evaluation
Methodology for Optical Flow" (ICCV 2007) as published in the Middlebury
flow-code C++ reference (vision.middlebury.edu/flow/code/flow-code/).
Capability parity with reference src/visual/flow_mb.py:14-63.
"""

import warnings

import numpy as np

# (count, from-RGB, to-RGB) hue segments; counts follow the published
# Middlebury code (chosen there for perceptual uniformity)
_SEGMENTS = (
    (15, (1, 0, 0), (1, 1, 0)),   # red → yellow
    (6, (1, 1, 0), (0, 1, 0)),    # yellow → green
    (4, (0, 1, 0), (0, 1, 1)),    # green → cyan
    (11, (0, 1, 1), (0, 0, 1)),   # cyan → blue
    (13, (0, 0, 1), (1, 0, 1)),   # blue → magenta
    (6, (1, 0, 1), (1, 0, 0)),    # magenta → red
)

_WHEEL = None


def color_wheel():
    global _WHEEL
    if _WHEEL is None:
        parts = []
        for count, lo, hi in _SEGMENTS:
            t = np.arange(count, dtype=np.float64)[:, None] / count
            parts.append((1.0 - t) * np.asarray(lo) + t * np.asarray(hi))
        _WHEEL = np.concatenate(parts, axis=0)
    return _WHEEL


def flow_to_rgba(uv, mask=None, mrm=None, gamma=1.0, eps=1e-5,
                 mask_color=(0, 0, 0, 1), nan_color=(0, 0, 0, 1)):
    """Color-code a flow field (H, W, 2) as RGBA floats in [0, 1].

    ``mrm`` fixes the maximum range of motion used for normalization (so
    estimate and ground truth can share a scale); ``mask`` marks valid
    pixels; non-finite flow is rendered in ``nan_color`` with a warning.
    """
    uv = np.array(uv, dtype=np.float64)
    u, v = uv[..., 0], uv[..., 1]

    if mask is not None:
        mask = np.asarray(mask, bool)
        u = np.where(mask, u, 0.0)
        v = np.where(mask, v, 0.0)

    bogus = ~(np.isfinite(u) & np.isfinite(v))
    if bogus.any():
        warnings.warn("encountered non-finite values in flow field",
                      RuntimeWarning, stacklevel=2)
        u = np.where(bogus, 0.0, u)
        v = np.where(bogus, 0.0, v)

    radius = np.hypot(u, v) ** gamma
    if mrm is None:
        mrm = max(float(np.max(radius if mask is None else radius * mask)), eps)
    radius = np.clip(radius / mrm, 0.0, 1.0)

    wheel = color_wheel()
    n = wheel.shape[0]

    # angle in [-1, 1] → fractional wheel index; linear interpolation with
    # wrap-around between adjacent wheel entries
    angle = np.arctan2(-v, -u) / np.pi
    pos = (angle + 1.0) / 2.0 * (n - 1)
    lo = np.floor(pos).astype(np.int64)
    hi = (lo + 1) % n
    frac = (pos - lo)[..., None]

    rgb = (1.0 - frac) * wheel[lo] + frac * wheel[hi]

    # desaturate towards white for small motion
    rgb = 1.0 - radius[..., None] * (1.0 - rgb)

    rgba = np.concatenate([rgb, np.ones_like(rgb[..., :1])], axis=-1)
    rgba[bogus] = np.asarray(nan_color, dtype=np.float64)
    if mask is not None:
        rgba[~mask] = np.asarray(mask_color, dtype=np.float64)

    return rgba
