"""Interactive cv2 image windows with close-safe waiting.

Capability parity with reference src/visual/imshow.py:7-39.
"""

import cv2

from . import flow_dark, flow_mb


class ImageWindow:
    def __init__(self, title):
        self.title = title

    def wait(self):
        # waitKey(0) deadlocks (and eats Ctrl-C) once the window is closed
        # via its 'x' button; poll visibility instead so both closing and
        # interrupting behave
        while cv2.getWindowProperty(self.title, cv2.WND_PROP_VISIBLE) >= 1:
            if cv2.waitKey(250) != -1:
                break


def show_image(title, rgb):
    cv2.imshow(title, rgb[:, :, ::-1])  # cv2 wants BGR
    return ImageWindow(title)


def show_flow(title, flow, *args, **kwargs):
    return show_image(title, flow_mb.flow_to_rgba(flow, *args, **kwargs))


def show_flow_dark(title, flow, *args, **kwargs):
    return show_image(title, flow_dark.flow_to_rgba(flow, *args, **kwargs))
