"""KITTI Fl bad-pixel (outlier) visualization.

A pixel is an outlier when EPE ≥ 3px AND ≥ 5% of the ground-truth
magnitude (the KITTI 2015 Fl criterion). Capability parity with reference
src/visual/bad_pixel.py:7-32.
"""

import numpy as np


def fl_error(uv, uv_target, mask=None, base_color=(0.0, 1.0, 0.0, 1.0),
             bp_color=(1.0, 0.0, 0.0, 1.0), mask_color=(0, 0, 0, 1),
             nan_color=(0, 0, 0, 1)):
    """Outlier map (H, W, 4): inliers ``base_color``, outliers ``bp_color``."""
    uv = np.asarray(uv, np.float64)
    uv_target = np.asarray(uv_target, np.float64)

    epe = np.linalg.norm(uv_target - uv, axis=-1)
    magnitude = np.linalg.norm(uv_target, axis=-1)

    bogus = ~np.isfinite(epe)
    outlier = (epe >= 3.0) & (epe >= 0.05 * magnitude)

    rgba = np.empty((*epe.shape, 4))
    rgba[...] = np.asarray(base_color, dtype=np.float64)
    rgba[outlier] = np.asarray(bp_color, dtype=np.float64)
    rgba[bogus] = np.asarray(nan_color, dtype=np.float64)

    if mask is not None:
        rgba[~np.asarray(mask, bool)] = np.asarray(mask_color, dtype=np.float64)

    return rgba
