"""Iteration-ladder policy: latency classes over recurrence budgets.

The recurrent models spend most of their serving latency in the GRU
update loop, and the loop's iteration count is a pure quality/latency
dial (the paper's 12 is the quality end). The ladder makes that dial a
first-class serving concept without recompilation: every rung is a
fixed-``iterations`` compiled program (``evaluation.make_rung_fn``),
rungs chain bit-exactly through the ``(flow, hidden)`` carry the models
return, and the host reads a cheap per-sample convergence norm
(``delta``) *between* programs to decide whether the next rung is worth
its latency.

Three latency classes map onto ladder policies:

- ``fast`` — the base rung only (``rungs[0]`` iterations): minimum
  latency, no escalation;
- ``balanced`` — start at the base rung, escalate through continuation
  rungs while the batch's worst convergence norm still exceeds
  ``threshold``: adaptive latency, quality close to the full budget;
- ``quality`` — the monolithic full-budget program (``rungs[-1]``
  iterations): the paper's setting, one program, no host round-trips.

This module is host-side policy only (no jax); the device half lives in
:meth:`~.session.ServeSession.run_ladder`.
"""

from dataclasses import dataclass
from typing import Tuple

from ..utils import env

CLASSES = ("fast", "balanced", "quality")


@dataclass(frozen=True)
class LadderSpec:
    """One ladder: ascending iteration budgets plus the escalation
    threshold on the per-sample flow-delta norm (coarse-grid px)."""

    rungs: Tuple[int, ...] = (4, 8, 12)
    threshold: float = 0.1

    def __post_init__(self):
        if len(self.rungs) < 2:
            raise ValueError(
                f"a ladder needs at least two rungs, got {self.rungs!r}")
        if any(r <= 0 for r in self.rungs):
            raise ValueError(f"rung budgets must be positive: {self.rungs!r}")
        if list(self.rungs) != sorted(set(self.rungs)):
            raise ValueError(
                f"rung budgets must be strictly ascending: {self.rungs!r}")
        if self.threshold <= 0:
            raise ValueError(
                f"escalation threshold must be positive: {self.threshold!r}")

    @classmethod
    def from_config(cls, spec=None, threshold=None):
        """Parse ``'4,8,12'`` (default: the ``RMD_LADDER`` knob); the
        threshold defaults to ``RMD_LADDER_THRESHOLD``."""
        if spec is None or spec is True:
            spec = env.get_str("RMD_LADDER")
        if isinstance(spec, str):
            rungs = tuple(int(p) for p in spec.replace(" ", "").split(",")
                          if p)
        else:
            rungs = tuple(int(r) for r in spec)
        if threshold is None:
            threshold = env.get_float("RMD_LADDER_THRESHOLD")
        return cls(rungs=rungs, threshold=float(threshold))

    def increments(self):
        """Continuation budgets between consecutive rungs."""
        return tuple(b - a for a, b in zip(self.rungs, self.rungs[1:]))

    def programs(self):
        """Every ``(iterations, cont)`` program this ladder executes:
        the base rung, the monolithic full budget, and one continuation
        program per *distinct* increment — one program per rung, however
        many fill levels or classes ride it."""
        out = [(self.rungs[0], False), (self.rungs[-1], False)]
        for inc in sorted(set(self.increments())):
            out.append((inc, True))
        return out

    def describe(self):
        return (f"rungs {','.join(str(r) for r in self.rungs)} "
                f"threshold {self.threshold:g}")
