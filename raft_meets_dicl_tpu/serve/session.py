"""One serving replica: model + variables + warm compiled-program pool.

The session owns everything device-side: the model spec, its variables
(freshly initialized or checkpoint-restored), the registered eval program
(``evaluation.make_eval_fn`` with the stable model id, so the program
dedupes process-wide and round-trips the AOT store), and the warm pool —
one precompiled executable per (model, bucket, wire) triple at the serve
batch size. A replica prepared with :meth:`warm_pool` against a populated
AOT store serves its first request with zero compiles; without artifacts
it pays at most one compile per bucket, up front instead of on the first
unlucky request.
"""

import logging
import time

import numpy as np

from .. import evaluation, models, telemetry
from ..models.input import ShapeBuckets


class ServeSession:
    """Device-side half of the serving path.

    ``spec`` is a loaded ``models.ModelSpec``; ``buckets`` the canonical
    ``ShapeBuckets`` (explicit sizes required — the warm pool is built
    per bucket); ``wire`` an optional ``WireFormat`` (bound to the
    model's clip/range here). Submitted images are raw un-normalized f32;
    with a wire format they cross host→device compact and decode inside
    the jitted program, without one they are normalized on the host by
    :meth:`encode_image`.
    """

    def __init__(self, spec, buckets, wire=None, checkpoint=None,
                 batch_size=4, mesh=None, ladder=None, video=False,
                 quant=None):
        buckets = ShapeBuckets.from_config(buckets) \
            if not isinstance(buckets, ShapeBuckets) else buckets
        if buckets is None or not buckets.sizes:
            raise ValueError(
                "serving needs explicit bucket sizes ('HxW,...'): the "
                "warm program pool and admission control are per bucket")
        self.spec = spec
        self.model = spec.model
        self.input = spec.input
        buckets.check_compatible(self.input.padding)

        if wire is not None:
            wire = wire.bound(self.input.clip, self.input.range)
        self.wire = wire
        # requests pad raw pixels then encode/normalize, so bucket pad
        # constants translate into raw space (same as the wire loaders)
        self.buckets = buckets.raw_variant(self.input.clip, self.input.range)
        self.batch_size = int(batch_size)  # graftlint: disable=host-sync -- config scalar, not a device value
        self.mesh = mesh

        self.variables = self._init_variables(checkpoint)
        self.eval_fn = evaluation.make_eval_fn(
            self.model, None, mesh=mesh, wire=wire, model_id=spec.id)

        # iteration ladder (ladder.LadderSpec): one registered rung
        # program per (iterations, cont) — base rung, continuation
        # increments, monolithic full budget — all ProgramKey flag
        # variants that dedupe/AOT like the plain eval program
        self.ladder = ladder
        # readiness for /healthz: flips once warm_pool() has compiled
        # (or AOT-loaded) every bucket's program — before that a request
        # would pay a cold compile the operator thinks was prepaid
        self.ready = False
        # quantized matching tier (RMD_QUANT / --quant, ops.quant): the
        # latency-critical programs — the fast class's base rung and the
        # video warm frames — run with quantized correlation volumes.
        # Continuation increments and the monolithic full budget stay
        # full-precision, so the balanced class escalates from the quant
        # base onto full-precision rungs exactly as the ladder threshold
        # already decides, and quality is untouched.
        from ..ops import quant as quant_ops

        self.quant = quant_ops.normalize_mode(quant)
        self._rung_fns = {}
        if ladder is not None:
            for its, cont in ladder.programs():
                q = (self.quant
                     if (not cont and its == ladder.rungs[0]) else None)
                self._rung_fns[(its, cont)] = evaluation.make_rung_fn(
                    self.model, its, cont=cont, mesh=mesh, wire=wire,
                    model_id=spec.id, quant=q)

        # video sessions (PR 15): one warm-start program per bucket set —
        # the fast rung re-entered from the previous frame's carry (the
        # projection lives inside the program; see make_warm_fn) — plus
        # its plain-rung twin for cold frames. With a ladder the bottom
        # rung doubles as the twin; ladderless sessions register one at
        # RMD_VIDEO_WARM_ITERATIONS.
        self.video = bool(video)
        self._warm_fn = None
        if video:
            from ..utils import env

            self.warm_iterations = (
                ladder.rungs[0] if ladder is not None
                else env.get_int("RMD_VIDEO_WARM_ITERATIONS"))
            self._warm_fn = evaluation.make_warm_fn(
                self.model, self.warm_iterations, mesh=mesh, wire=wire,
                model_id=spec.id, quant=self.quant)
            if (self.warm_iterations, False) not in self._rung_fns:
                self._rung_fns[(self.warm_iterations, False)] = \
                    evaluation.make_rung_fn(
                        self.model, self.warm_iterations, mesh=mesh,
                        wire=wire, model_id=spec.id, quant=self.quant)

    @classmethod
    def from_config(cls, model_cfg, buckets, **kwargs):
        """Build from a model config mapping (full training configs
        accepted — their ``model`` section is used)."""
        if "strategy" in model_cfg:
            model_cfg = model_cfg["model"]
        return cls(models.load(model_cfg), buckets, **kwargs)

    def _init_variables(self, checkpoint):
        import jax

        # structure init at the smallest bucket; init wants the
        # normalized f32 contract, not the wire dtype
        h, w = self.buckets.sizes[0]
        dummy = self._normalize(np.zeros((1, h, w, 3), np.float32))
        variables = self.model.init(jax.random.PRNGKey(0), dummy, dummy)
        if checkpoint is not None:
            from .. import strategy

            logging.info(f"loading checkpoint, file='{checkpoint}'")
            chkpt = strategy.Checkpoint.load(checkpoint)
            variables, _, _ = chkpt.apply(variables=variables)
        return variables

    def _normalize(self, img):
        lo, hi = self.input.clip
        rmin, rmax = self.input.range
        x = np.clip(np.asarray(img, np.float32), lo, hi)  # graftlint: disable=host-sync -- host-side raw request pixels, never a device array
        return (rmax - rmin) * x + rmin

    # -- request encoding (host, admission path) -----------------------------

    def encode_image(self, img):
        """Raw un-normalized image → what the program's inputs expect:
        wire dtype (decode runs inside the jit) or host-normalized f32."""
        if self.wire is not None:
            return self.wire.encode_image(img)
        return self._normalize(img)

    def image_dtype(self):
        return (self.wire.image_dtype() if self.wire is not None
                else np.dtype(np.float32))

    # -- device work (dispatch thread) ---------------------------------------

    def run(self, img1, img2):
        """One batch through the eval program; returns the final flow as
        a ready device array (NHWC, f32)."""
        import jax

        _, flow = self.eval_fn(self.variables, img1, img2)
        # the dispatch span must cover device compute: the scheduler's
        # only pipeline stage is this call, there is no async overlap to
        # preserve
        jax.block_until_ready(flow)  # graftlint: disable=host-sync -- serving dispatch-span boundary
        return flow

    def run_ladder(self, img1, img2, klass):
        """One batch through the ladder policy for ``klass``; returns
        ``(flow, info)`` — the final flow as a ready device array plus
        ``{"rungs", "iterations"}`` accounting.

        ``fast`` and ``quality`` are single programs (base rung /
        monolithic full budget). ``balanced`` chains continuation rungs:
        the ``(flow, hidden)`` carry stays on device between programs,
        only the per-sample ``delta`` norm crosses to the host — the
        decision point that makes escalation recompile-free.
        """
        import jax

        lad = self.ladder
        if klass == "quality":
            flow, _ = self._rung_fns[(lad.rungs[-1], False)](
                self.variables, img1, img2)
            jax.block_until_ready(flow)  # graftlint: disable=host-sync -- serving dispatch-span boundary
            return flow, {"rungs": 1, "iterations": lad.rungs[-1]}

        flow, state = self._rung_fns[(lad.rungs[0], False)](
            self.variables, img1, img2)
        executed, rungs = lad.rungs[0], 1
        if klass == "balanced":
            for inc in lad.increments():
                worst = float(np.max(np.asarray(state["delta"])))  # graftlint: disable=host-sync -- rung decision point: the host reads the convergence norm between programs
                if worst <= lad.threshold:
                    break
                flow, state = self._rung_fns[(inc, True)](
                    self.variables, img1, img2,
                    state["flow"], state["hidden"])
                executed += inc
                rungs += 1
        jax.block_until_ready(flow)  # graftlint: disable=host-sync -- serving dispatch-span boundary
        return flow, {"rungs": rungs, "iterations": executed}

    def run_video(self, img1, img2, carry=None):
        """One video-session batch; returns ``(flow, state, info)``.

        ``carry`` is the batch's previous-frame coarse flow (stacked
        per-member rows from the scheduler's session cache) — the warm
        program forward-projects it internally. ``carry=None`` runs the
        plain rung twin: a true cold start, bit-exact with what the warm
        program produces on an all-zero carry. ``state`` stays on device
        except what the caller fetches; the scheduler stores its
        ``flow`` rows back per client.
        """
        import jax

        if not self.video:
            raise RuntimeError("run_video needs a video=True session")
        warm = carry is not None
        if warm:
            flow, state = self._warm_fn(self.variables, img1, img2, carry)
        else:
            flow, state = self._rung_fns[(self.warm_iterations, False)](
                self.variables, img1, img2)
        jax.block_until_ready(flow)  # graftlint: disable=host-sync -- serving dispatch-span boundary
        return flow, state, {"rungs": 1,
                             "iterations": self.warm_iterations,
                             "warm": warm}

    def fetch(self, flow):
        """Device flow → host numpy (the per-request ``device`` span)."""
        import jax

        return np.asarray(jax.device_get(flow))  # graftlint: disable=host-sync -- response must materialize on host

    def compiles(self):
        """Exact backend-compile count across the serve programs — the
        eval program plus every ladder rung and the video warm variant
        (registry Program counters; see
        evaluation._program_compile_counter)."""
        progs = [self.eval_fn, *self._rung_fns.values()]
        if self._warm_fn is not None:
            progs.append(self._warm_fn)
        return sum(getattr(p, "compiles", 0) for p in progs)

    # -- warm pool ------------------------------------------------------------

    def warm_pool(self):
        """Compile (or AOT-load) the program for every bucket at the
        serve batch size; returns one outcome record per (model, bucket,
        wire) triple — plus, with a ladder, one per (model, bucket,
        wire, rung): compiles / AOT hits / AOT saves / seconds.

        With a populated AOT store every record reports ``compiles=0,
        aot_hits=1``; a prebuild run (``serve --prebuild``) reports the
        saves it exported.
        """
        import jax
        import jax.numpy as jnp

        dtype = self.image_dtype()
        outcomes = []

        def _counts(step):
            return (time.perf_counter(), getattr(step, "compiles", 0),
                    getattr(step, "aot_hits", 0),
                    getattr(step, "aot_saves", 0))

        def _record(step, bucket, rung, t0, c0, h0, s0):
            outcome = {
                "model": self.spec.id,
                "bucket": bucket,
                "wire": (self.wire.describe() if self.wire is not None
                         else "f32 host-normalized"),
                "batch": self.batch_size,
                "compiles": getattr(step, "compiles", 0) - c0,
                "aot_hits": getattr(step, "aot_hits", 0) - h0,
                "aot_saves": getattr(step, "aot_saves", 0) - s0,
                "seconds": round(time.perf_counter() - t0, 4),
            }
            if rung is not None:
                outcome["rung"] = rung
            if getattr(step, "quant", None):
                outcome["quant"] = step.quant
            outcomes.append(outcome)
            telemetry.get().emit("serve", event="warmup", **outcome)

        for h, w in self.buckets.sizes:
            bucket = f"{h}x{w}"
            img = jnp.zeros((self.batch_size, h, w, 3), dtype)

            step = self.eval_fn
            t0, c0, h0, s0 = _counts(step)
            _, flow = step(self.variables, img, img)
            jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
            _record(step, bucket, None, t0, c0, h0, s0)

            carry = None
            if self.ladder is not None:
                # ladder rungs: warm the base rung first, then feed its
                # carry to every continuation increment (correct carry
                # shapes without knowing the model's hidden width), then
                # the monolithic full budget
                lad = self.ladder
                base = self._rung_fns[(lad.rungs[0], False)]
                t0, c0, h0, s0 = _counts(base)
                flow, state = base(self.variables, img, img)
                jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
                _record(base, bucket, f"base:{lad.rungs[0]}", t0, c0, h0,
                        s0)
                carry = state
                for inc in sorted(set(lad.increments())):
                    step = self._rung_fns[(inc, True)]
                    t0, c0, h0, s0 = _counts(step)
                    flow, _ = step(self.variables, img, img,
                                   state["flow"], state["hidden"])
                    jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
                    _record(step, bucket, f"cont:+{inc}", t0, c0, h0, s0)
                step = self._rung_fns[(lad.rungs[-1], False)]
                t0, c0, h0, s0 = _counts(step)
                flow, _ = step(self.variables, img, img)
                jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
                _record(step, bucket, f"full:{lad.rungs[-1]}", t0, c0, h0,
                        s0)

            if not self.video:
                continue
            # video variants: the cold plain-rung twin (with a ladder the
            # base rung above already covers it), then the warm-start
            # program fed the twin's carry (correct coarse shape without
            # knowing the model's downsampling factor)
            if carry is None:
                step = self._rung_fns[(self.warm_iterations, False)]
                t0, c0, h0, s0 = _counts(step)
                flow, carry = step(self.variables, img, img)
                jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
                _record(step, bucket, f"base:{self.warm_iterations}", t0,
                        c0, h0, s0)
            step = self._warm_fn
            t0, c0, h0, s0 = _counts(step)
            flow, _ = step(self.variables, img, img, carry["flow"])
            jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
            _record(step, bucket, f"warm:{self.warm_iterations}", t0, c0,
                    h0, s0)
        self.ready = True
        return outcomes

    def program_fingerprint(self, klass=""):
        """Stable identity of the compiled program a batch of ``klass``
        rides (registry ProgramKey canonical form) — the batch-trace
        field that lets a tail batch be tied to one executable."""
        fn = self.eval_fn
        if klass and self.ladder is not None:
            lad = self.ladder
            rung = lad.rungs[-1] if klass == "quality" else lad.rungs[0]
            fn = self._rung_fns.get((rung, False), fn)
        key = getattr(fn, "key", None)
        if key is not None:
            return key.describe()
        return getattr(fn, "telemetry_label", "eval_step")
