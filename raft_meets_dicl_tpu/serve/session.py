"""One serving replica: model + variables + warm compiled-program pool.

The session owns everything device-side: the model spec, its variables
(freshly initialized or checkpoint-restored), the registered eval program
(``evaluation.make_eval_fn`` with the stable model id, so the program
dedupes process-wide and round-trips the AOT store), and the warm pool —
one precompiled executable per (model, bucket, wire) triple at the serve
batch size. A replica prepared with :meth:`warm_pool` against a populated
AOT store serves its first request with zero compiles; without artifacts
it pays at most one compile per bucket, up front instead of on the first
unlucky request.
"""

import logging
import time

import numpy as np

from .. import evaluation, models, telemetry
from ..models.input import ShapeBuckets


class ServeSession:
    """Device-side half of the serving path.

    ``spec`` is a loaded ``models.ModelSpec``; ``buckets`` the canonical
    ``ShapeBuckets`` (explicit sizes required — the warm pool is built
    per bucket); ``wire`` an optional ``WireFormat`` (bound to the
    model's clip/range here). Submitted images are raw un-normalized f32;
    with a wire format they cross host→device compact and decode inside
    the jitted program, without one they are normalized on the host by
    :meth:`encode_image`.
    """

    def __init__(self, spec, buckets, wire=None, checkpoint=None,
                 batch_size=4, mesh=None):
        buckets = ShapeBuckets.from_config(buckets) \
            if not isinstance(buckets, ShapeBuckets) else buckets
        if buckets is None or not buckets.sizes:
            raise ValueError(
                "serving needs explicit bucket sizes ('HxW,...'): the "
                "warm program pool and admission control are per bucket")
        self.spec = spec
        self.model = spec.model
        self.input = spec.input
        buckets.check_compatible(self.input.padding)

        if wire is not None:
            wire = wire.bound(self.input.clip, self.input.range)
        self.wire = wire
        # requests pad raw pixels then encode/normalize, so bucket pad
        # constants translate into raw space (same as the wire loaders)
        self.buckets = buckets.raw_variant(self.input.clip, self.input.range)
        self.batch_size = int(batch_size)  # graftlint: disable=host-sync -- config scalar, not a device value
        self.mesh = mesh

        self.variables = self._init_variables(checkpoint)
        self.eval_fn = evaluation.make_eval_fn(
            self.model, None, mesh=mesh, wire=wire, model_id=spec.id)

    @classmethod
    def from_config(cls, model_cfg, buckets, **kwargs):
        """Build from a model config mapping (full training configs
        accepted — their ``model`` section is used)."""
        if "strategy" in model_cfg:
            model_cfg = model_cfg["model"]
        return cls(models.load(model_cfg), buckets, **kwargs)

    def _init_variables(self, checkpoint):
        import jax

        # structure init at the smallest bucket; init wants the
        # normalized f32 contract, not the wire dtype
        h, w = self.buckets.sizes[0]
        dummy = self._normalize(np.zeros((1, h, w, 3), np.float32))
        variables = self.model.init(jax.random.PRNGKey(0), dummy, dummy)
        if checkpoint is not None:
            from .. import strategy

            logging.info(f"loading checkpoint, file='{checkpoint}'")
            chkpt = strategy.Checkpoint.load(checkpoint)
            variables, _, _ = chkpt.apply(variables=variables)
        return variables

    def _normalize(self, img):
        lo, hi = self.input.clip
        rmin, rmax = self.input.range
        x = np.clip(np.asarray(img, np.float32), lo, hi)  # graftlint: disable=host-sync -- host-side raw request pixels, never a device array
        return (rmax - rmin) * x + rmin

    # -- request encoding (host, admission path) -----------------------------

    def encode_image(self, img):
        """Raw un-normalized image → what the program's inputs expect:
        wire dtype (decode runs inside the jit) or host-normalized f32."""
        if self.wire is not None:
            return self.wire.encode_image(img)
        return self._normalize(img)

    def image_dtype(self):
        return (self.wire.image_dtype() if self.wire is not None
                else np.dtype(np.float32))

    # -- device work (dispatch thread) ---------------------------------------

    def run(self, img1, img2):
        """One batch through the eval program; returns the final flow as
        a ready device array (NHWC, f32)."""
        import jax

        _, flow = self.eval_fn(self.variables, img1, img2)
        # the dispatch span must cover device compute: the scheduler's
        # only pipeline stage is this call, there is no async overlap to
        # preserve
        jax.block_until_ready(flow)  # graftlint: disable=host-sync -- serving dispatch-span boundary
        return flow

    def fetch(self, flow):
        """Device flow → host numpy (the per-request ``device`` span)."""
        import jax

        return np.asarray(jax.device_get(flow))  # graftlint: disable=host-sync -- response must materialize on host

    def compiles(self):
        """Exact backend-compile count of the serve program (registry
        Program counter; see evaluation._program_compile_counter)."""
        return getattr(self.eval_fn, "compiles", 0)

    # -- warm pool ------------------------------------------------------------

    def warm_pool(self):
        """Compile (or AOT-load) the program for every bucket at the
        serve batch size; returns one outcome record per (model, bucket,
        wire) triple: compiles / AOT hits / AOT saves / seconds.

        With a populated AOT store every triple reports ``compiles=0,
        aot_hits=1``; a prebuild run (``serve --prebuild``) reports the
        saves it exported.
        """
        import jax
        import jax.numpy as jnp

        step = self.eval_fn
        dtype = self.image_dtype()
        outcomes = []
        for h, w in self.buckets.sizes:
            t0 = time.perf_counter()
            c0 = self.compiles()
            h0 = getattr(step, "aot_hits", 0)
            s0 = getattr(step, "aot_saves", 0)
            img = jnp.zeros((self.batch_size, h, w, 3), dtype)
            _, flow = step(self.variables, img, img)
            jax.block_until_ready(flow)  # graftlint: disable=host-sync -- warm pool must finish before serving starts
            outcome = {
                "model": self.spec.id,
                "bucket": f"{h}x{w}",
                "wire": (self.wire.describe() if self.wire is not None
                         else "f32 host-normalized"),
                "batch": self.batch_size,
                "compiles": self.compiles() - c0,
                "aot_hits": getattr(step, "aot_hits", 0) - h0,
                "aot_saves": getattr(step, "aot_saves", 0) - s0,
                "seconds": round(time.perf_counter() - t0, 4),
            }
            outcomes.append(outcome)
            telemetry.get().emit("serve", event="warmup", **outcome)
        return outcomes
