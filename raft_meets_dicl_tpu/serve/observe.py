"""Live observability HTTP plane for one serve replica.

A tiny stdlib HTTP server (daemon thread, no dependency) the fleet
router and operators scrape:

- ``/metrics`` — Prometheus text exposition of the ``rmd_*`` registry
  (telemetry.metrics), with the scrape-time gauges (queue depth,
  dropped telemetry events, readiness, per-class SLO burn) refreshed
  just before render;
- ``/healthz`` — readiness (warm pool complete: every bucket's program
  compiled or AOT-loaded) and liveness (dispatch-loop heartbeat age
  under the threshold); 200 only when both hold, 503 otherwise, JSON
  body either way — the router's drain signal;
- ``/statusz`` — JSON snapshot: per-lane queue depths, shed/error
  counts, per-class p50/p99 plus the slowest-decile critical-path
  breakdown (telemetry.trace.TraceSummary), SLO windows;
- ``/profilez?seconds=N`` — on-demand ``jax.profiler`` capture to a
  fresh directory (the generalized form of the train ``--profile``
  hook), single-flight and capped so a scrape loop can't stack
  captures.

The server binds ``127.0.0.1`` (an observability sidecar, not the
serving API) and ``port=0`` picks an ephemeral port (tests).
"""

import json
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..telemetry import metrics as metrics_mod

# liveness: the dispatch loop wakes at least every second
# (scheduler._HEARTBEAT_WAKE_S); 10x that margin tolerates a loaded host
STALE_HEARTBEAT_S = 10.0
MAX_PROFILE_S = 60.0
DEFAULT_PROFILE_S = 3.0


class Observer:
    """Aggregates one replica's live state for the HTTP plane and keeps
    the scrape-time gauges fresh."""

    def __init__(self, session, scheduler, sink=None, registry=None,
                 stale_heartbeat_s=STALE_HEARTBEAT_S):
        self.session = session
        self.scheduler = scheduler
        self.sink = sink
        self.registry = registry or metrics_mod.registry()
        self.stale_heartbeat_s = float(stale_heartbeat_s)  # graftlint: disable=host-sync -- config scalar, not a device value
        self._profile_lock = threading.Lock()
        self._m_ready = self.registry.gauge(
            "rmd_serve_ready", "replica readiness (warm pool complete)")
        self._m_heartbeat = self.registry.gauge(
            "rmd_serve_heartbeat_age_seconds",
            "seconds since the dispatch loop last went around")
        self._m_dropped = self.registry.gauge(
            "rmd_telemetry_dropped_total",
            "telemetry events shed by the bounded non-blocking buffer")
        self._m_burn = self.registry.gauge(
            "rmd_slo_burn_rate",
            "per-class SLO burn rate over the rolling window", ("klass",))
        self._m_attain = self.registry.gauge(
            "rmd_slo_attainment",
            "per-class SLO attainment over the rolling window", ("klass",))

    # -- state ---------------------------------------------------------------

    def ready(self):
        return bool(getattr(self.session, "ready", False))

    def heartbeat_age(self):
        age = getattr(self.scheduler, "heartbeat_age", None)
        return age() if age else 0.0

    def live(self):
        return self.heartbeat_age() < self.stale_heartbeat_s

    def _refresh_gauges(self):
        self._m_ready.set(1.0 if self.ready() else 0.0)
        self._m_heartbeat.set(round(self.heartbeat_age(), 3))
        if self.sink is not None:
            self._m_dropped.set(self.sink.dropped())
        slo = getattr(self.scheduler, "slo", None)
        if slo:
            for klass, snap in slo.snapshot().items():
                label = klass or "default"
                self._m_burn.labels(klass=label).set(snap["burn_rate"])
                self._m_attain.labels(klass=label).set(snap["attainment"])

    # -- endpoint payloads ---------------------------------------------------

    def metrics_text(self):
        self._refresh_gauges()
        return self.registry.render()

    def health(self):
        ready, age = self.ready(), self.heartbeat_age()
        live = age < self.stale_heartbeat_s
        return {
            "ready": ready,
            "live": live,
            "heartbeat_age_s": round(age, 3),
        }, (200 if ready and live else 503)

    def status(self):
        sched = self.scheduler
        summary = getattr(sched, "trace_summary", None)
        slo = getattr(sched, "slo", None)
        snap = summary.snapshot() if summary is not None else {}
        depths = (sched.queue_depths()
                  if hasattr(sched, "queue_depths") else {})
        return {
            "ready": self.ready(),
            "heartbeat_age_s": round(self.heartbeat_age(), 3),
            "queues": depths,
            "pending": sum(depths.values()),
            "requests": snap.get("count", 0),
            "classes": snap.get("classes", {}),
            "tail": snap.get("tail"),
            "slo": slo.snapshot() if slo else {},
            "telemetry_dropped": (self.sink.dropped()
                                  if self.sink is not None else 0),
        }

    def profile(self, seconds):
        """Capture ``seconds`` of jax profiler trace; returns the
        directory holding the capture. Single-flight: a second request
        while one runs gets a 409."""
        seconds = min(max(float(str(seconds)), 0.1), MAX_PROFILE_S)
        if not self._profile_lock.acquire(blocking=False):
            raise ProfileBusy("a profile capture is already running")
        try:
            import jax

            out = tempfile.mkdtemp(prefix="rmd-profilez-")
            jax.profiler.start_trace(out)
            time.sleep(seconds)
            jax.profiler.stop_trace()
            return {"dir": out, "seconds": seconds}
        finally:
            self._profile_lock.release()


class ProfileBusy(RuntimeError):
    pass


class _Handler(BaseHTTPRequestHandler):
    observer = None  # bound by serve_observer via subclass attribute

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _send(self, code, body, content_type="application/json"):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code, payload):
        self._send(code, json.dumps(payload, indent=2) + "\n")

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        obs = self.observer
        try:
            if url.path == "/metrics":
                self._send(200, obs.metrics_text(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/healthz":
                payload, code = obs.health()
                self._send_json(code, payload)
            elif url.path == "/statusz":
                self._send_json(200, obs.status())
            elif url.path == "/profilez":
                qs = parse_qs(url.query)
                seconds = qs.get("seconds", [DEFAULT_PROFILE_S])[0]
                self._send_json(200, obs.profile(seconds))
            else:
                self._send_json(404, {"error": f"no route {url.path}"})
        except ProfileBusy as e:
            self._send_json(409, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - scrape must not kill serve
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})


class ObserverServer:
    """The bound HTTP server + its daemon thread."""

    def __init__(self, observer, port, host="127.0.0.1"):
        handler = type("BoundHandler", (_Handler,), {"observer": observer})
        self.observer = observer
        self.httpd = ThreadingHTTPServer((host, int(port)), handler)  # graftlint: disable=host-sync -- TCP port number, not a device value
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-observe",
            daemon=True)

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5.0)

    @property
    def url(self):
        return f"http://{self.httpd.server_address[0]}:{self.port}"


def serve_observer(session, scheduler, port, sink=None, registry=None):
    """Build and start the observability server; returns the
    :class:`ObserverServer` (``.port`` resolves port 0)."""
    obs = Observer(session, scheduler, sink=sink, registry=registry)
    return ObserverServer(obs, port).start()
