"""Live observability HTTP plane for one serve replica.

The HTTP server itself (routes, daemon thread, profile capture) is the
shared sidecar in :mod:`..telemetry.sidecar` — the trainer binds the
same server — and this module keeps only the serve-side observer:

- ``/metrics`` — Prometheus text exposition of the ``rmd_*`` registry
  (telemetry.metrics), with the scrape-time gauges (queue depth,
  dropped telemetry events, readiness, per-class SLO burn) refreshed
  just before render;
- ``/healthz`` — readiness (warm pool complete: every bucket's program
  compiled or AOT-loaded) and liveness (dispatch-loop heartbeat age
  under the threshold); 200 only when both hold, 503 otherwise, JSON
  body either way — the router's drain signal;
- ``/statusz`` — JSON snapshot: per-lane queue depths, shed/error
  counts, per-class p50/p99 plus the slowest-decile critical-path
  breakdown (telemetry.trace.TraceSummary), SLO windows;
- ``/profilez?seconds=N`` — on-demand ``jax.profiler`` capture to a
  fresh directory (the generalized form of the train ``--profile``
  hook), single-flight and capped so a scrape loop can't stack
  captures.

The server binds ``127.0.0.1`` (an observability sidecar, not the
serving API) and ``port=0`` picks an ephemeral port (tests).
"""

import threading

from ..telemetry import metrics as metrics_mod
from ..telemetry import sidecar
from ..telemetry.sidecar import (  # noqa: F401 - back-compat re-exports
    DEFAULT_PROFILE_S,
    MAX_PROFILE_S,
    STALE_HEARTBEAT_S,
    ProfileBusy,
)

# the handler/server formerly defined here; kept importable under the
# old names so callers and tests bind serve observers unchanged
_Handler = sidecar.Handler


class Observer:
    """Aggregates one replica's live state for the HTTP plane and keeps
    the scrape-time gauges fresh."""

    def __init__(self, session, scheduler, sink=None, registry=None,
                 stale_heartbeat_s=STALE_HEARTBEAT_S):
        self.session = session
        self.scheduler = scheduler
        self.sink = sink
        self.registry = registry or metrics_mod.registry()
        self.stale_heartbeat_s = float(stale_heartbeat_s)  # graftlint: disable=host-sync -- config scalar, not a device value
        self._draining = False
        self._profile_lock = threading.Lock()
        self._m_ready = self.registry.gauge(
            "rmd_serve_ready", "replica readiness (warm pool complete)")
        self._m_heartbeat = self.registry.gauge(
            "rmd_serve_heartbeat_age_seconds",
            "seconds since the dispatch loop last went around")
        self._m_dropped = self.registry.gauge(
            "rmd_telemetry_dropped_total",
            "telemetry events shed by the bounded non-blocking buffer")
        self._m_burn = self.registry.gauge(
            "rmd_slo_burn_rate",
            "per-class SLO burn rate over the rolling window", ("klass",))
        self._m_attain = self.registry.gauge(
            "rmd_slo_attainment",
            "per-class SLO attainment over the rolling window", ("klass",))

    # -- state ---------------------------------------------------------------

    def ready(self):
        return bool(getattr(self.session, "ready", False))

    def heartbeat_age(self):
        age = getattr(self.scheduler, "heartbeat_age", None)
        return age() if age else 0.0

    def live(self):
        return self.heartbeat_age() < self.stale_heartbeat_s

    def draining(self):
        return self._draining

    def begin_drain(self):
        """Flip the replica into draining: /healthz goes 503 with a
        ``draining`` body so external probes and the fleet router share
        one signal. In-flight and queued requests still complete (the
        scheduler keeps dispatching); only *routing* decisions change.
        Idempotent; returns True on the first transition."""
        first = not self._draining
        self._draining = True
        return first

    def _refresh_gauges(self):
        self._m_ready.set(1.0 if self.ready() else 0.0)
        self._m_heartbeat.set(round(self.heartbeat_age(), 3))
        if self.sink is not None:
            self._m_dropped.set(self.sink.dropped())
        slo = getattr(self.scheduler, "slo", None)
        if slo:
            for klass, snap in slo.snapshot().items():
                label = klass or "default"
                self._m_burn.labels(klass=label).set(snap["burn_rate"])
                self._m_attain.labels(klass=label).set(snap["attainment"])

    # -- endpoint payloads ---------------------------------------------------

    def metrics_text(self):
        self._refresh_gauges()
        return self.registry.render()

    def health(self):
        ready, age = self.ready(), self.heartbeat_age()
        live = age < self.stale_heartbeat_s
        payload = {
            "ready": ready,
            "live": live,
            "heartbeat_age_s": round(age, 3),
        }
        if self._draining:
            # a draining replica is deliberately unhealthy to probes:
            # finish what it holds, take nothing new
            payload["draining"] = True
            return payload, 503
        return payload, (200 if ready and live else 503)

    def status(self):
        sched = self.scheduler
        summary = getattr(sched, "trace_summary", None)
        slo = getattr(sched, "slo", None)
        snap = summary.snapshot() if summary is not None else {}
        depths = (sched.queue_depths()
                  if hasattr(sched, "queue_depths") else {})
        return {
            "ready": self.ready(),
            "draining": self._draining,
            "heartbeat_age_s": round(self.heartbeat_age(), 3),
            "queues": depths,
            "pending": sum(depths.values()),
            "requests": snap.get("count", 0),
            "compiles": (self.session.compiles()
                         if hasattr(self.session, "compiles") else None),
            "classes": snap.get("classes", {}),
            "tail": snap.get("tail"),
            "slo": slo.snapshot() if slo else {},
            "telemetry_dropped": (self.sink.dropped()
                                  if self.sink is not None else 0),
        }

    def profile(self, seconds):
        """Capture ``seconds`` of jax profiler trace; returns the
        directory holding the capture plus an inline graftprof
        attribution summary (``RMD_PROFILE_ATTRIBUTION``).
        Single-flight: a second request while one runs gets a 409."""
        return sidecar.capture_profile(self._profile_lock, seconds,
                                       registry=self.registry)


class ObserverServer(sidecar.SidecarServer):
    """The bound HTTP server + its daemon thread (shared sidecar)."""

    def __init__(self, observer, port, host="127.0.0.1"):
        super().__init__(observer, port, host=host,
                         thread_name="serve-observe")


def serve_observer(session, scheduler, port, sink=None, registry=None):
    """Build and start the observability server; returns the
    :class:`ObserverServer` (``.port`` resolves port 0)."""
    obs = Observer(session, scheduler, sink=sink, registry=registry)
    return ObserverServer(obs, port).start()
