"""Continuous-batching request scheduler for the serving path.

One dispatch thread pulls batches from the :class:`BucketBatcher` and
runs them through a :class:`~.session.ServeSession`; callers submit image
pairs from any thread and block on the returned :class:`Ticket`. Three
invariants the tests pin:

- **The dispatch loop never stalls.** Overload sheds at admission with a
  typed :class:`ServeRejected` (bounded per-bucket queues); a request
  that fails mid-flight (fault-injected decode error, device failure)
  completes its ticket with a typed :class:`ServeError` while the rest of
  its batch — and the loop — carry on.
- **No batch poisoning.** Per-request failures are removed from the
  batch before assembly; the surviving requests still dispatch (refilled
  to the full batch size by tiling, so they keep the same compiled
  program).
- **Sticky per-client ordering.** Responses release to each client in
  submission order: a finished ticket whose predecessor (same client) is
  still in flight is held until the predecessor completes, so clients
  can stream results without reordering buffers.

With a video session (``ServeSession(video=True)``) a client id is also
a *sticky video session*: ``submit(..., sequence=True)`` requests ride
their own batcher lanes onto the warm-start program, seeded per member
from the bounded TTL-evicted :class:`~..video.SessionCache` (previous
frame's coarse carry, keyed by client). A member without a usable carry
gets a zero row — bit-exact with the plain cold rung — so cache
eviction and resolution switches degrade, never corrupt.
``submit(..., products=True)`` additionally dispatches the batch's
reversed pairs through the *same* compiled program (no new shapes) and
attaches fw/bw occlusion masks + confidence to the result.

This module is host-side only (no jax import — device work lives in the
session); per-request telemetry lands as ``serve`` events: ``request``
(success, with admission/queue/dispatch/device spans), ``error``,
``reject``, and per-dispatch ``batch`` records.
"""

import threading
import time

import numpy as np

from .. import telemetry
from ..telemetry import metrics as metrics_mod
from ..telemetry import slo as slo_mod
from ..telemetry import trace as trace_mod
from ..testing import faults
from ..utils import env
from .batcher import (BucketBatcher, FlowRequest, FlowResult, ServeError,
                      ServeRejected)

# the dispatch loop wakes at least this often even when idle, so the
# liveness heartbeat (observe.py /healthz) keeps advancing
_HEARTBEAT_WAKE_S = 1.0


class Ticket:
    """Caller handle for one admitted request: blocks on :meth:`result`
    until the scheduler releases the response (in per-client submission
    order)."""

    def __init__(self, rid, client):
        self.rid = rid
        self.client = client
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _complete(self, result=None, error=None):
        self._result = result
        self._error = error
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The :class:`FlowResult`, or raises the request's typed
        :class:`ServeError`; ``TimeoutError`` if nothing arrives in
        ``timeout`` seconds."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.rid} still in flight "
                               f"after {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result


class Scheduler:
    """Admission control + dispatch loop over one serve session.

    ``batch_size``/``max_wait_ms``/``queue_limit`` default to the
    session's batch size and the ``RMD_SERVE_MAX_WAIT_MS`` /
    ``RMD_SERVE_QUEUE`` knobs.
    """

    def __init__(self, session, batch_size=None, max_wait_ms=None,
                 queue_limit=None):
        if batch_size is None:
            batch_size = session.batch_size
        if max_wait_ms is None:
            max_wait_ms = env.get_float("RMD_SERVE_MAX_WAIT_MS")
        if queue_limit is None:
            queue_limit = env.get_int("RMD_SERVE_QUEUE")
        self.session = session
        self.batcher = BucketBatcher(session.buckets, batch_size, queue_limit)
        self.max_wait_s = float(max_wait_ms) / 1e3

        # live observability plane: per-request trace summary, per-class
        # SLO burn windows (empty unless RMD_SLO_* targets are set), and
        # the rmd_serve_* metrics every instrumentation point feeds
        self.trace_summary = trace_mod.TraceSummary()
        self.slo = slo_mod.SLOTracker()
        self._heartbeat = time.monotonic()
        reg = metrics_mod.registry()
        self._m_requests = reg.counter(
            "rmd_serve_requests_total", "completed serve requests",
            ("klass", "bucket"))
        self._m_errors = reg.counter(
            "rmd_serve_errors_total", "failed serve requests by typed kind",
            ("error",))
        self._m_shed = reg.counter(
            "rmd_serve_shed_total", "admission rejections by reason",
            ("reason",))
        self._m_batches = reg.counter(
            "rmd_serve_batches_total", "dispatched device batches",
            ("bucket", "klass"))
        self._m_fill = reg.counter(
            "rmd_serve_fill_slots_total",
            "pad-tile fill slots dispatched in partial batches")
        self._m_latency = reg.histogram(
            "rmd_serve_request_latency_seconds",
            "end-to-end request latency (submit to release)", ("klass",))
        self._m_depth = reg.gauge(
            "rmd_serve_queue_depth", "queued requests across all lanes")

        # video sessions: per-client warm-start carry, bounded + TTL
        # (hits/misses/evictions surface as rmd_serve_session_* metrics)
        self.sessions = None
        self._carry_factor = None  # (fy, fx) image-to-coarse-grid ratio
        if getattr(session, "video", False):
            from ..video import SessionCache

            self.sessions = SessionCache()

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rid = 0
        self._seq = {}            # client -> next sequence number to assign
        self._release_next = {}   # client -> next sequence number to release
        self._held = {}           # client -> {seq: (request, result, error)}
        self._stopping = False
        self._thread = None

    # -- admission (caller threads) -----------------------------------------

    def submit(self, img1, img2, client="default", klass=None,
               sequence=False, products=False):
        """Admit one raw (un-normalized f32 HWC) image pair.

        ``klass`` picks the latency class (``ladder.CLASSES``) when the
        session serves an iteration ladder — defaulting to ``balanced``;
        requests only batch with same-class neighbors. Without a ladder
        the class must stay unset.

        ``sequence=True`` marks a video-session frame: the request is
        warm-started from the client's cached carry and routed to the
        fast rung (``klass`` is ignored — warm-start requests ride the
        warm program by construction). Needs a video session.
        ``products=True`` additionally returns fw/bw occlusion +
        confidence on the result.

        Returns a :class:`Ticket` on acceptance. Raises synchronously:
        :class:`ServeError` (``malformed``/``oversized``/
        ``unknown_class``/``no_video``) when the payload can never be
        served, :class:`ServeRejected` (``queue_full``/``shutdown``)
        when the system sheds it — admission is where backpressure
        surfaces, the dispatch loop never blocks on overload.
        """
        t0 = time.perf_counter()
        with self._lock:
            rid = self._rid
            self._rid += 1

        try:
            if sequence:
                if self.sessions is None:
                    raise ServeError(
                        "no_video",
                        "sequence requests need a video session "
                        "(serve --video)")
                # warm-start frames always enter at the fast rung; the
                # warm program rides its own batcher lanes per bucket
                klass = ("fast" if getattr(self.session, "ladder", None)
                         is not None else "")
            else:
                klass = self._validate_klass(klass)
            self._validate(rid, img1, img2)
            h, w = int(img1.shape[0]), int(img1.shape[1])
            bucket = self.batcher.assign(h, w)
            if bucket is None or faults.fire("serve_oversized", index=rid):
                raise ServeError(
                    "oversized",
                    f"{h}x{w} fits no bucket ({self.session.buckets.describe()})")
        except ServeError as e:
            # field name is 'error' (not 'kind'): the envelope's 'kind'
            # slot is the event kind itself
            self._m_errors.labels(error=e.kind).inc()
            telemetry.get().emit("serve", event="error", rid=rid,
                                 client=client, error=e.kind)
            raise

        e1, e2 = self.batcher.encode_pair(img1, img2, bucket,
                                          self.session.encode_image)
        return self._enqueue(rid, client, bucket, (h, w), e1, e2, t0,
                             klass, sequence, products)

    def submit_encoded(self, e1, e2, shape, client="default", klass=None,
                       sequence=False, products=False):
        """Admit one *pre-encoded* pair: bucket-shaped arrays already in
        the session's wire dtype (the fleet front-end path — the client
        or router encoded at the edge, the bytes land on device
        untouched). ``shape`` is the original (H, W) the response crops
        to; the bucket is the arrays' spatial extent and must be one of
        the configured buckets. Same typed error/shed contract as
        :meth:`submit`.
        """
        t0 = time.perf_counter()
        with self._lock:
            rid = self._rid
            self._rid += 1

        try:
            if sequence:
                if self.sessions is None:
                    raise ServeError(
                        "no_video",
                        "sequence requests need a video session "
                        "(serve --video)")
                klass = ("fast" if getattr(self.session, "ladder", None)
                         is not None else "")
            else:
                klass = self._validate_klass(klass)
            for img in (e1, e2):
                if not isinstance(img, np.ndarray) or img.ndim != 3 \
                        or img.shape[-1] != 3:
                    raise ServeError(
                        "malformed",
                        f"expected bucket-shaped HWC wire arrays, got "
                        f"{getattr(img, 'shape', type(img).__name__)}")
            if e1.shape != e2.shape:
                raise ServeError(
                    "malformed", f"pair shapes differ: {e1.shape} vs "
                                 f"{e2.shape}")
            want = getattr(self.session, "image_dtype", None)
            if want is not None and e1.dtype != want():
                raise ServeError(
                    "malformed",
                    f"wire dtype {e1.dtype} does not match the "
                    f"session's {want()}")
            bucket = (int(e1.shape[0]), int(e1.shape[1]))
            if bucket not in self.session.buckets.sizes:
                raise ServeError(
                    "oversized",
                    f"{bucket[0]}x{bucket[1]} is not a configured "
                    f"bucket ({self.session.buckets.describe()})")
            h, w = int(shape[0]), int(shape[1])
            if h > bucket[0] or w > bucket[1] or h < 1 or w < 1:
                raise ServeError(
                    "malformed",
                    f"crop shape {h}x{w} outside bucket "
                    f"{bucket[0]}x{bucket[1]}")
        except ServeError as e:
            self._m_errors.labels(error=e.kind).inc()
            telemetry.get().emit("serve", event="error", rid=rid,
                                 client=client, error=e.kind)
            raise

        return self._enqueue(rid, client, bucket, (h, w), e1, e2, t0,
                             klass, sequence, products)

    def _enqueue(self, rid, client, bucket, shape, e1, e2, t0, klass,
                 sequence, products):
        ticket = Ticket(rid, client)
        rtrace = trace_mod.RequestTrace(klass=klass, bucket=bucket)
        rtrace.mark("submit", t0)
        req = FlowRequest(rid=rid, client=client, seq=0, bucket=bucket,
                          shape=shape, img1=e1, img2=e2, ticket=ticket,
                          t_submit=t0, klass=klass,
                          sequence=bool(sequence), products=bool(products),
                          trace=rtrace)

        with self._cond:
            if self._stopping:
                self._m_shed.labels(reason="shutdown").inc()
                telemetry.get().emit("serve", event="reject", rid=rid,
                                     client=client, reason="shutdown")
                raise ServeRejected("shutdown")
            req.spans["admission"] = time.perf_counter() - t0
            if not self.batcher.offer(req):
                self._m_shed.labels(reason="queue_full").inc()
                telemetry.get().emit(
                    "serve", event="reject", rid=rid, client=client,
                    reason="queue_full", bucket=f"{bucket[0]}x{bucket[1]}")
                raise ServeRejected(
                    "queue_full",
                    f"bucket {bucket[0]}x{bucket[1]} queue at bound "
                    f"({self.batcher.queue_limit})")
            rtrace.mark("enqueue", req.t_enqueue)
            self._m_depth.set(self.batcher.pending())
            req.seq = self._seq.get(client, 0)
            self._seq[client] = req.seq + 1
            self._cond.notify()
        return ticket

    def _validate_klass(self, klass):
        from . import ladder as ladder_mod

        has_ladder = getattr(self.session, "ladder", None) is not None
        if klass is None:
            return "balanced" if has_ladder else ""
        if not has_ladder:
            raise ServeError(
                "unknown_class",
                f"latency class {klass!r} needs a session with an "
                f"iteration ladder (serve --ladder)")
        if klass not in ladder_mod.CLASSES:
            raise ServeError(
                "unknown_class",
                f"{klass!r} is not one of {'/'.join(ladder_mod.CLASSES)}")
        return klass

    def _validate(self, rid, img1, img2):
        if faults.fire("serve_malformed", index=rid):
            raise ServeError("malformed", "fault injected")
        for img in (img1, img2):
            if not isinstance(img, np.ndarray) or img.ndim != 3 \
                    or img.shape[-1] != 3:
                raise ServeError(
                    "malformed",
                    f"expected HWC RGB arrays, got "
                    f"{getattr(img, 'shape', type(img).__name__)}")
        if img1.shape != img2.shape:
            raise ServeError(
                "malformed", f"pair shapes differ: {img1.shape} vs "
                             f"{img2.shape}")

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True):
        """Stop admitting; by default drain queued requests (partials
        dispatch immediately), otherwise fail them with a typed error."""
        with self._cond:
            self._stopping = True
            if not drain:
                flushed = []
                while True:
                    bucket, batch = self.batcher.take(
                        time.perf_counter(), 0.0, drain=True)
                    if bucket is None:
                        break
                    flushed.extend(batch)
                self._cond.notify_all()
            else:
                flushed = []
                self._cond.notify_all()
        for r in flushed:
            self._complete(r, error=ServeError("internal", "shutdown"))
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def pending(self):
        with self._lock:
            return self.batcher.pending()

    def heartbeat_age(self):
        """Seconds since the dispatch loop last went around — the
        /healthz liveness signal (the loop wakes at least every
        ``_HEARTBEAT_WAKE_S`` even when idle)."""
        return time.monotonic() - self._heartbeat

    def queue_depths(self):
        """Per-lane queue depths (``HxW[/klass]`` -> count)."""
        with self._lock:
            return self.batcher.depths()

    # -- dispatch loop -------------------------------------------------------

    def _loop(self):
        while True:
            with self._cond:
                while True:
                    self._heartbeat = time.monotonic()
                    now = time.perf_counter()
                    bucket, batch = self.batcher.take(
                        now, self.max_wait_s, drain=self._stopping)
                    if bucket is not None:
                        break
                    if self._stopping:
                        return
                    deadline = batch  # (None, deadline) overload of take()
                    # idle waits are capped so the liveness heartbeat
                    # keeps advancing with nothing queued
                    timeout = (_HEARTBEAT_WAKE_S if deadline is None
                               else min(_HEARTBEAT_WAKE_S,
                                        max(0.0, deadline - now)))
                    self._cond.wait(timeout)
            try:
                self._dispatch(bucket, batch)
            except Exception as e:  # noqa: BLE001 - loop must survive
                for r in batch:
                    self._complete(r, error=ServeError("internal", str(e)))

    def _dispatch(self, bucket, batch):
        t0 = time.perf_counter()

        # per-request decode faults: remove the poisoned request, keep the
        # rest of the batch (assemble refills to the full size by tiling)
        live = []
        for r in batch:
            if faults.fire("serve_decode_error", index=r.rid):
                self._complete(
                    r, error=ServeError("decode", "fault injected"))
            else:
                live.append(r)
        if not live:
            return
        klass = live[0].klass  # lanes are same-class by construction
        # test stand-in sessions may not expose a program fingerprint
        fingerprint = getattr(self.session, "program_fingerprint", None)
        btrace = trace_mod.BatchTrace(
            bucket, klass,
            program=fingerprint(klass) if fingerprint else None)
        btrace.t_start = t0
        for r in live:
            r.spans["queue"] = t0 - r.t_enqueue
            if r.trace is not None:
                r.trace.mark("dispatch", t0)
                btrace.link(r.trace)

        img1, img2, fill = self.batcher.assemble(live)
        btrace.fill = fill
        c0 = self.session.compiles()
        sequence = live[0].sequence  # lanes are same-sequence-ness too
        warm_rows = [None] * len(live)
        state = None
        if sequence:
            carry, warm_rows = self._gather_carry(live, bucket, fill)
            flow, state, info = self.session.run_video(img1, img2, carry)
        elif klass:
            flow, info = self.session.run_ladder(img1, img2, klass)
        else:
            flow, info = self.session.run(img1, img2), None
        products = any(r.products for r in live)
        flow_bw = None
        if products:
            # fw/bw products: the reversed pairs ride the *same*
            # compiled program (same shapes — zero new programs); video
            # batches reverse cold, a carry has no meaning backwards
            if sequence:
                bw_dev, _, _ = self.session.run_video(img2, img1)
            elif klass:
                bw_dev, _ = self.session.run_ladder(img2, img1, klass)
            else:
                bw_dev = self.session.run(img2, img1)
        t1 = time.perf_counter()
        flow = self.session.fetch(flow)
        if products:
            flow_bw = self.session.fetch(bw_dev)
        if sequence:
            self._store_carry(live, bucket, state)
        t2 = time.perf_counter()

        tele = telemetry.get()
        batch_event = dict(
            bucket=f"{bucket[0]}x{bucket[1]}", size=len(live), fill=fill,
            compiles=self.session.compiles() - c0,
            seconds=round(t1 - t0, 6))
        if info is not None:
            batch_event.update(klass=klass, rungs=info["rungs"],
                               iterations=info["iterations"])
        if sequence:
            batch_event.update(
                video=True,
                warm_members=sum(1 for row in warm_rows if row is not None))
        if products:
            batch_event.update(products=True)
        tele.emit("serve", event="batch", **batch_event)
        btrace.finish()
        tele.emit("trace", event="batch", **btrace.record())
        self._m_batches.labels(
            bucket=f"{bucket[0]}x{bucket[1]}", klass=klass).inc()
        if fill > 0:
            self._m_fill.inc(fill)
        self._m_depth.set(self.batcher.pending())

        for i, r in enumerate(live):
            h, w = r.shape
            r.spans["dispatch"] = t1 - t0
            r.spans["device"] = t2 - t1
            if r.trace is not None:
                r.trace.mark("launched", t1)
                r.trace.mark("fetched", t2)
            occ = conf = None
            if r.products and flow_bw is not None:
                from ..video.products import fw_bw_products

                occ, conf = fw_bw_products(flow[i, :h, :w, :],
                                           flow_bw[i, :h, :w, :])
            self._complete(r, result=FlowResult(
                rid=r.rid, client=r.client, bucket=bucket, shape=r.shape,
                flow=flow[i, :h, :w, :], spans=r.spans, klass=klass,
                iterations=(info["iterations"] if info else 0),
                warm=warm_rows[i] is not None,
                occlusion=occ, confidence=conf))

    # -- video session carry -------------------------------------------------

    def _carry_shape(self, bucket):
        """Expected coarse-carry row shape for ``bucket``, or None until
        the model's downsampling factor has been observed (before any
        video dispatch the cache is necessarily empty)."""
        if self._carry_factor is None:
            return None
        fy, fx = self._carry_factor
        return (int(round(bucket[0] / fy)), int(round(bucket[1] / fx)), 2)

    def carry_shapes(self):
        """Every configured bucket's expected carry shape — what an
        imported session-handoff snapshot must match — or None until the
        model's downsampling factor has been observed (then the
        cache's shape-checked lookup is the only guard)."""
        if self._carry_factor is None:
            return None
        return {self._carry_shape(b) for b in self.session.buckets.sizes}

    def _gather_carry(self, live, bucket, fill):
        """Per-member cached carries stacked into one batch array.

        Members without a usable carry (new client, TTL-evicted,
        resolution switch) get zero rows — the warm program is bit-exact
        with the cold rung on zeros, so a partial-warm batch is always
        correct. Returns ``(carry | None, per-member rows)``; None when
        no member is warm (the batch runs the plain cold rung)."""
        expected = self._carry_shape(bucket)
        rows = [self.sessions.get(r.client, expected) for r in live]
        have = [row for row in rows if row is not None]
        if not have:
            return None, rows
        proto = have[0]
        carry = np.stack([row if row is not None else np.zeros_like(proto)
                          for row in rows])
        if fill > 0:
            carry = np.concatenate(
                [carry, np.repeat(carry[-1:], fill, axis=0)])
        return carry, rows

    def _store_carry(self, live, bucket, state):
        """Store each member's fresh coarse-flow carry for its client
        (fill rows are dropped); the first store also pins the
        image-to-coarse-grid factor the shape check needs."""
        coarse = self.session.fetch(state["flow"])
        if self._carry_factor is None:
            self._carry_factor = (bucket[0] / coarse.shape[1],
                                  bucket[1] / coarse.shape[2])
        for i, r in enumerate(live):
            self.sessions.put(r.client, coarse[i])

    # -- completion / sticky per-client release ------------------------------

    def _complete(self, req, result=None, error=None):
        with self._lock:
            held = self._held.setdefault(req.client, {})
            held[req.seq] = (req, result, error)
            nxt = self._release_next.get(req.client, 0)
            ready = []
            while nxt in held:
                ready.append(held.pop(nxt))
                nxt += 1
            self._release_next[req.client] = nxt
        for r, res, err in ready:
            total = time.perf_counter() - r.t_submit
            tele = telemetry.get()
            if err is None:
                res.spans["total"] = total
                extra = ({"klass": res.klass, "iterations": res.iterations}
                         if res.klass else {})
                tele.emit(
                    "serve", event="request", rid=r.rid, client=r.client,
                    bucket=f"{r.bucket[0]}x{r.bucket[1]}",
                    seconds=round(total, 6),
                    spans={k: round(v, 6) for k, v in res.spans.items()},
                    **extra)
                self._m_requests.labels(
                    klass=r.klass,
                    bucket=f"{r.bucket[0]}x{r.bucket[1]}").inc()
                self._m_latency.labels(klass=r.klass).observe(total)
                if r.trace is not None:
                    r.trace.mark("released")
                    record = r.trace.record()
                    tele.emit("trace", event="request", rid=r.rid,
                              **record)
                    self.trace_summary.add(record)
                self.slo.record(r.klass, total)
                self.slo.maybe_emit(tele)
            else:
                self._m_errors.labels(
                    error=getattr(err, "kind", "internal")).inc()
                tele.emit("serve", event="error", rid=r.rid,
                          client=r.client,
                          error=getattr(err, "kind", "internal"),
                          seconds=round(total, 6))
            r.ticket._complete(result=res, error=err)
