"""Open-loop synthetic load generator: the SLO measurement harness.

Open-loop means requests fire on a fixed wall-clock schedule regardless
of completions — the honest way to measure a service under load (a
closed loop self-throttles and hides queueing delay, the classic
coordinated-omission trap). The generator cycles through a
mixed-resolution shape list, submits raw synthetic pairs at ``rate_hz``,
collects every ticket, and reports p50/p99/mean latency, per-span means,
throughput, and the shed/error counts.

The generator is also a *well-behaved* client of the typed shed
contract: retryable sheds (``queue_full``, ``replica_unavailable`` —
backpressure that may clear) can re-submit with jittered exponential
backoff up to a bounded budget, while permanent sheds (``shutdown``,
``draining``) are never retried. Each ticket is collected under a
per-request timeout; a ticket that completes with a typed shed (the
fleet router resolves rejections at result time, not submit time) is
accounted exactly like a synchronous one.
"""

import random
import time

import numpy as np

from ..telemetry.report import _percentile
from .batcher import ServeError, ServeRejected

# shed reasons worth a client-side retry: transient backpressure, not a
# permanent state of the service
RETRYABLE_SHEDS = ("queue_full", "replica_unavailable")


def synthetic_pair(shape, rng):
    """One deterministic pseudo-random raw image pair in [0, 1)."""
    h, w = shape
    img1 = rng.random((h, w, 3), dtype=np.float32)
    img2 = rng.random((h, w, 3), dtype=np.float32)
    return img1, img2


def submit_with_retry(scheduler, img1, img2, client, klass, sequence,
                      retries, backoff_s, rejects, retried):
    """One submission with bounded jittered-backoff retry on retryable
    typed sheds; returns the ticket or None (shed accounted)."""
    for attempt in range(int(retries) + 1):
        try:
            return scheduler.submit(img1, img2, client=client, klass=klass,
                                    sequence=sequence)
        except ServeRejected as e:
            if e.reason not in RETRYABLE_SHEDS or attempt >= retries:
                rejects[e.reason] = rejects.get(e.reason, 0) + 1
                return None
            retried[0] += 1
            time.sleep(backoff_s * (2 ** attempt)
                       * random.uniform(0.5, 1.5))
    return None


def run_open_loop(scheduler, shapes, requests, rate_hz, client="loadgen",
                  seed=0, result_timeout_s=120.0, classes=None,
                  sequence=False, streams=4, retries=0,
                  retry_backoff_s=0.05):
    """Drive ``scheduler`` with ``requests`` submissions at ``rate_hz``.

    ``shapes`` is the (H, W) cycle the stream draws from (mixed
    resolutions exercise bucket quantization and partial batches);
    ``classes`` an optional latency-class cycle (ladder sessions) — the
    report then carries a per-class latency/rung breakdown. With
    ``sequence=True`` (video sessions) requests are submitted as
    ``streams`` interleaved sticky client streams — each stream pins one
    shape so its frames share a bucket and its carry stays valid — and
    the report carries a warm-hit breakdown. ``retries`` > 0 re-submits
    a retryably-shed request with jittered backoff (``retry_backoff_s``
    base, doubling per attempt) before accounting the shed; the default
    0 keeps the pure open-loop measurement (a retry bends the schedule,
    which is the client's choice, not the harness's). Returns the
    report dict (see ``summarize``); deterministic for a fixed seed,
    shape list, and class list (retry jitter excepted).
    """
    rng = np.random.default_rng(seed)
    interval = 1.0 / float(rate_hz)
    tickets = []
    rejects = {}
    errors = {}
    retried = [0]

    t_start = time.perf_counter()
    for i in range(int(requests)):
        target = t_start + i * interval
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if sequence:
            stream = i % max(1, int(streams))
            shape = shapes[stream % len(shapes)]
            name = f"{client}-{stream}"
        else:
            shape = shapes[i % len(shapes)]
            name = client
        img1, img2 = synthetic_pair(shape, rng)
        klass = classes[i % len(classes)] if classes else None
        try:
            ticket = submit_with_retry(
                scheduler, img1, img2, name, klass, sequence,
                retries, retry_backoff_s, rejects, retried)
            if ticket is not None:
                tickets.append(ticket)
        except ServeError as e:
            errors[e.kind] = errors.get(e.kind, 0) + 1

    results = []
    for ticket in tickets:
        try:
            results.append(ticket.result(timeout=result_timeout_s))
        except ServeRejected as e:
            # fleet tickets resolve sheds at result time (the router's
            # bounded retry already ran); account them with the rest
            rejects[e.reason] = rejects.get(e.reason, 0) + 1
        except TimeoutError:
            errors["timeout"] = errors.get("timeout", 0) + 1
        except ServeError as e:
            errors[e.kind] = errors.get(e.kind, 0) + 1
    wall = time.perf_counter() - t_start

    report = summarize(int(requests), results, rejects, errors, wall)
    if retried[0]:
        report["retries"] = retried[0]
    return report


def summarize(requests, results, rejects, errors, wall_s):
    """Aggregate completed :class:`FlowResult`s into the SLO report."""
    latencies = sorted(r.spans.get("total", 0.0) for r in results)
    span_names = sorted({k for r in results for k in r.spans})
    spans_ms = {}
    for name in span_names:
        vals = [r.spans[name] for r in results if name in r.spans]
        spans_ms[name] = round(1e3 * sum(vals) / len(vals), 3)

    completed = len(results)
    report = {
        "requests": requests,
        "completed": completed,
        "rejected": rejects,
        "errors": errors,
        "wall_s": round(wall_s, 3),
        "pairs_per_sec": round(completed / wall_s, 3) if wall_s > 0 else 0.0,
        "p50_ms": round(1e3 * _percentile(latencies, 0.50), 3),
        "p99_ms": round(1e3 * _percentile(latencies, 0.99), 3),
        "mean_ms": (round(1e3 * sum(latencies) / completed, 3)
                    if completed else 0.0),
        "spans_ms": spans_ms,
    }

    # ladder breakdown: per-class latency + executed-iterations histogram
    by_class = {}
    for r in results:
        if not getattr(r, "klass", ""):
            continue
        c = by_class.setdefault(r.klass, {"lat": [], "iterations": {}})
        c["lat"].append(r.spans.get("total", 0.0))
        its = c["iterations"]
        its[r.iterations] = its.get(r.iterations, 0) + 1
    if by_class:
        report["classes"] = {
            k: {
                "completed": len(c["lat"]),
                "p50_ms": round(1e3 * _percentile(sorted(c["lat"]), 0.50), 3),
                "p99_ms": round(1e3 * _percentile(sorted(c["lat"]), 0.99), 3),
                "mean_ms": round(1e3 * sum(c["lat"]) / len(c["lat"]), 3),
                "iterations": dict(sorted(c["iterations"].items())),
            } for k, c in sorted(by_class.items())
        }

    # video breakdown: warm-start hit ratio across completed frames
    warm = sum(1 for r in results if getattr(r, "warm", False))
    if warm:
        report["video"] = {"warm": warm, "cold": completed - warm}
    return report
