"""Open-loop synthetic load generator: the SLO measurement harness.

Open-loop means requests fire on a fixed wall-clock schedule regardless
of completions — the honest way to measure a service under load (a
closed loop self-throttles and hides queueing delay, the classic
coordinated-omission trap). The generator cycles through a
mixed-resolution shape list, submits raw synthetic pairs at ``rate_hz``,
collects every ticket, and reports p50/p99/mean latency, per-span means,
throughput, and the shed/error counts.
"""

import time

import numpy as np

from ..telemetry.report import _percentile
from .batcher import ServeError, ServeRejected


def synthetic_pair(shape, rng):
    """One deterministic pseudo-random raw image pair in [0, 1)."""
    h, w = shape
    img1 = rng.random((h, w, 3), dtype=np.float32)
    img2 = rng.random((h, w, 3), dtype=np.float32)
    return img1, img2


def run_open_loop(scheduler, shapes, requests, rate_hz, client="loadgen",
                  seed=0, result_timeout_s=120.0, classes=None,
                  sequence=False, streams=4):
    """Drive ``scheduler`` with ``requests`` submissions at ``rate_hz``.

    ``shapes`` is the (H, W) cycle the stream draws from (mixed
    resolutions exercise bucket quantization and partial batches);
    ``classes`` an optional latency-class cycle (ladder sessions) — the
    report then carries a per-class latency/rung breakdown. With
    ``sequence=True`` (video sessions) requests are submitted as
    ``streams`` interleaved sticky client streams — each stream pins one
    shape so its frames share a bucket and its carry stays valid — and
    the report carries a warm-hit breakdown. Returns the report dict
    (see ``summarize``); deterministic for a fixed seed, shape list, and
    class list.
    """
    rng = np.random.default_rng(seed)
    interval = 1.0 / float(rate_hz)
    tickets = []
    rejects = {}
    errors = {}

    t_start = time.perf_counter()
    for i in range(int(requests)):
        target = t_start + i * interval
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if sequence:
            stream = i % max(1, int(streams))
            shape = shapes[stream % len(shapes)]
            name = f"{client}-{stream}"
        else:
            shape = shapes[i % len(shapes)]
            name = client
        img1, img2 = synthetic_pair(shape, rng)
        klass = classes[i % len(classes)] if classes else None
        try:
            tickets.append(scheduler.submit(img1, img2, client=name,
                                            klass=klass, sequence=sequence))
        except ServeRejected as e:
            rejects[e.reason] = rejects.get(e.reason, 0) + 1
        except ServeError as e:
            errors[e.kind] = errors.get(e.kind, 0) + 1

    results = []
    for ticket in tickets:
        try:
            results.append(ticket.result(timeout=result_timeout_s))
        except ServeError as e:
            errors[e.kind] = errors.get(e.kind, 0) + 1
    wall = time.perf_counter() - t_start

    return summarize(int(requests), results, rejects, errors, wall)


def summarize(requests, results, rejects, errors, wall_s):
    """Aggregate completed :class:`FlowResult`s into the SLO report."""
    latencies = sorted(r.spans.get("total", 0.0) for r in results)
    span_names = sorted({k for r in results for k in r.spans})
    spans_ms = {}
    for name in span_names:
        vals = [r.spans[name] for r in results if name in r.spans]
        spans_ms[name] = round(1e3 * sum(vals) / len(vals), 3)

    completed = len(results)
    report = {
        "requests": requests,
        "completed": completed,
        "rejected": rejects,
        "errors": errors,
        "wall_s": round(wall_s, 3),
        "pairs_per_sec": round(completed / wall_s, 3) if wall_s > 0 else 0.0,
        "p50_ms": round(1e3 * _percentile(latencies, 0.50), 3),
        "p99_ms": round(1e3 * _percentile(latencies, 0.99), 3),
        "mean_ms": (round(1e3 * sum(latencies) / completed, 3)
                    if completed else 0.0),
        "spans_ms": spans_ms,
    }

    # ladder breakdown: per-class latency + executed-iterations histogram
    by_class = {}
    for r in results:
        if not getattr(r, "klass", ""):
            continue
        c = by_class.setdefault(r.klass, {"lat": [], "iterations": {}})
        c["lat"].append(r.spans.get("total", 0.0))
        its = c["iterations"]
        its[r.iterations] = its.get(r.iterations, 0) + 1
    if by_class:
        report["classes"] = {
            k: {
                "completed": len(c["lat"]),
                "p50_ms": round(1e3 * _percentile(sorted(c["lat"]), 0.50), 3),
                "p99_ms": round(1e3 * _percentile(sorted(c["lat"]), 0.99), 3),
                "mean_ms": round(1e3 * sum(c["lat"]) / len(c["lat"]), 3),
                "iterations": dict(sorted(c["iterations"].items())),
            } for k, c in sorted(by_class.items())
        }

    # video breakdown: warm-start hit ratio across completed frames
    warm = sum(1 for r in results if getattr(r, "warm", False))
    if warm:
        report["video"] = {"warm": warm, "cold": completed - warm}
    return report
