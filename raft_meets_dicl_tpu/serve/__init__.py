"""Flow-as-a-service: the online inference path.

Composes the training-side ingredients into a request path — canonical
``ShapeBuckets`` quantization (PR 4), compact wire formats decoded inside
the jitted program (PR 2), the compiled-program registry with AOT export
(PR 7), structured telemetry (PR 1) — behind a continuous-batching
scheduler with bounded-queue admission control:

- :mod:`.batcher` — request/result types, typed rejection/error classes,
  per-bucket coalescing with deterministic batch selection (numpy-only);
- :mod:`.scheduler` — admission, the dispatch loop, sticky per-client
  response ordering, per-request latency spans;
- :mod:`.session` — the model replica: variables, the registered eval
  program, and the warm pool of precompiled executables per
  (model, bucket, wire) triple;
- :mod:`.loadgen` — the open-loop synthetic load generator behind
  ``BENCH_SERVE=1`` and the ``serve`` CLI's built-in client;
- :mod:`.ladder` — iteration-ladder latency classes (PR 11): adaptive
  recurrence budgets over chained fixed-``iterations`` rung programs;
- :mod:`.observe` — the live observability plane (PR 13): /metrics
  (Prometheus text), /healthz readiness+liveness, /statusz snapshots,
  /profilez on-demand profiler captures.

Video streams (PR 15) ride the same path: a ``video=True`` session adds
the registered warm-start program per bucket, the scheduler keys each
client's previous-frame carry in a bounded TTL-evicted
:class:`~..video.SessionCache`, and ``submit(sequence=True)`` requests
coalesce on their own lanes onto the warm program (``products=True``
adds fw/bw occlusion + confidence from a same-program reversed
dispatch).
"""

from . import batcher, ladder, loadgen, observe, scheduler, session
from .batcher import (BucketBatcher, FlowRequest, FlowResult, ServeError,
                      ServeRejected)
from .ladder import CLASSES, LadderSpec
from .observe import Observer, ObserverServer, serve_observer
from .scheduler import Scheduler, Ticket
from .session import ServeSession

__all__ = [
    "batcher", "ladder", "loadgen", "observe", "scheduler", "session",
    "BucketBatcher", "CLASSES", "FlowRequest", "FlowResult", "LadderSpec",
    "Observer", "ObserverServer", "serve_observer",
    "ServeError", "ServeRejected", "Scheduler", "Ticket", "ServeSession",
]
