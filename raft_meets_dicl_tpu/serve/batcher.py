"""Request types + per-bucket coalescing for the serving path.

The batcher is the host-side half of continuous batching: every admitted
request is quantized onto the canonical :class:`~..models.input.ShapeBuckets`
set at admission (so its compiled program is known before it ever queues),
then coalesced with same-bucket neighbors into full device batches. A
bucket whose queue reaches the batch size dispatches immediately; a
partial batch dispatches once its oldest request has waited the configured
deadline, filled up to the full batch size by tiling the last request —
the eval-style ``pad_to=`` treatment — so it rides the full batch's
compiled program instead of compiling one per remainder size.

This module is numpy-only (no jax): everything device-side lives in the
scheduler/session.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np


class ServeRejected(RuntimeError):
    """Typed admission rejection: the request never entered the system.

    ``reason`` is the machine-readable shed class (``queue_full`` for
    backpressure). Sheds are the admission-control contract — the
    dispatch loop never stalls to absorb overload; callers retry or
    back off.
    """

    def __init__(self, reason, detail=""):
        self.reason = reason
        super().__init__(f"request rejected ({reason})"
                         + (f": {detail}" if detail else ""))


class ServeError(RuntimeError):
    """Typed per-request failure.

    ``kind`` is one of:

    - ``malformed`` — the payload failed validation at admission;
    - ``oversized`` — the pair fits no configured bucket (no compiled
      program exists for it);
    - ``decode`` — the request failed while its batch was being
      prepared/decoded (the rest of the batch is unaffected);
    - ``internal`` — the dispatch failed; the batch's requests all carry
      this error, the loop continues;
    - ``unknown_class`` — the latency class does not exist (or the
      session has no ladder);
    - ``no_video`` — a sequence request reached a session built without
      video support (``serve --video``).
    """

    def __init__(self, kind, detail=""):
        self.kind = kind
        super().__init__(f"request failed ({kind})"
                         + (f": {detail}" if detail else ""))


@dataclass
class FlowRequest:
    """One admitted image pair, already quantized and wire-encoded.

    ``img1``/``img2`` are bucket-shaped arrays in the wire dtype (the
    admission path pads raw pixels up to the bucket and encodes them, so
    the dispatch loop only stacks). ``shape`` keeps the original (H, W)
    for cropping the response.
    """

    rid: int
    client: str
    seq: int
    bucket: Tuple[int, int]
    shape: Tuple[int, int]
    img1: np.ndarray
    img2: np.ndarray
    ticket: Any
    t_submit: float
    t_enqueue: float = 0.0
    klass: str = ""  # latency class ("" = plain eval, no ladder)
    sequence: bool = False  # video-session member (warm-start eligible)
    products: bool = False  # also wants fw/bw occlusion + confidence
    spans: Dict[str, float] = field(default_factory=dict)
    trace: Any = None  # telemetry.trace.RequestTrace (None = untraced)


@dataclass
class FlowResult:
    """One served flow: cropped to the request's original extent, with
    the per-request latency spans (seconds) the telemetry event carries:
    ``admission`` (validate + quantize + encode), ``queue`` (enqueue to
    dispatch), ``dispatch`` (batch assembly + program call), ``device``
    (result fetch)."""

    rid: int
    client: str
    bucket: Tuple[int, int]
    shape: Tuple[int, int]
    flow: np.ndarray
    spans: Dict[str, float]
    klass: str = ""
    iterations: int = 0  # recurrence iterations actually executed
    warm: bool = False   # video session: started from a cached carry
    occlusion: Optional[np.ndarray] = None   # fw/bw products (H, W) bool
    confidence: Optional[np.ndarray] = None  # fw/bw products (H, W) f32


class BucketBatcher:
    """Bounded per-lane FIFO queues + deterministic batch selection.

    A lane is ``(bucket, klass, sequence)`` — requests only coalesce
    with same-bucket, same-latency-class, same-sequence-ness neighbors,
    so every dispatched batch runs one ladder policy (or the video
    warm-start program) end to end. Without a ladder or video sessions
    every request carries the empty class and lanes degenerate to plain
    per-bucket queues.

    Selection policy (documented because tests pin it): full batches
    first — among lanes holding at least ``batch_size`` requests, the
    one whose head request enqueued earliest wins (ties broken by bucket
    size then class). With no full batch, the oldest head whose wait
    exceeded the caller's deadline dispatches as a partial. Within a
    lane, order is strict FIFO. Everything keys on the monotonic
    enqueue stamp plus the lane tuple, so the same submission sequence
    always coalesces identically. ``take`` returns the *bucket* (the
    compiled-program shape); the batch's class rides on its requests.
    """

    def __init__(self, buckets, batch_size, queue_limit):
        if not buckets.sizes:
            raise ValueError(
                "serving needs explicit bucket sizes ('HxW,...'): the "
                "warm program pool is built per bucket")
        self.buckets = buckets
        self.batch_size = int(batch_size)
        self.queue_limit = int(queue_limit)
        self._queues = {(b, "", False): deque() for b in buckets.sizes}

    def assign(self, h, w) -> Optional[Tuple[int, int]]:
        """Smallest bucket fitting (h, w), or None (oversized)."""
        return self.buckets.assign(h, w)

    def encode_pair(self, img1, img2, bucket, encode):
        """Pad a raw HWC pair up to ``bucket`` and wire-encode it."""
        img1 = self.buckets.pad_image(img1, bucket)
        img2 = self.buckets.pad_image(img2, bucket)
        return encode(img1), encode(img2)

    def offer(self, request) -> bool:
        """Enqueue, or refuse (lane queue at bound — backpressure)."""
        lane = (request.bucket, getattr(request, "klass", ""),
                getattr(request, "sequence", False))
        q = self._queues.setdefault(lane, deque())
        if len(q) >= self.queue_limit:
            return False
        request.t_enqueue = time.perf_counter()
        q.append(request)
        return True

    def pending(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depths(self) -> Dict[str, int]:
        """Per-lane queue depths keyed ``HxW[/klass][/seq]`` (klass
        omitted for the empty ladderless class, ``/seq`` marking video
        session lanes) — the /statusz live snapshot."""
        out = {}
        for (bucket, klass, sequence), q in sorted(self._queues.items()):
            name = f"{bucket[0]}x{bucket[1]}"
            if klass:
                name = f"{name}/{klass}"
            if sequence:
                name = f"{name}/seq"
            out[name] = len(q)
        return out

    def take(self, now, max_wait_s, drain=False):
        """Next dispatchable batch, or the wake-up deadline.

        Returns ``(bucket, requests)`` when a batch should dispatch now,
        else ``(None, deadline)`` where ``deadline`` is the absolute
        ``perf_counter`` time the oldest partial becomes dispatchable
        (None when every queue is empty). ``drain`` dispatches partials
        immediately (shutdown flush).
        """
        full = [(q[0].t_enqueue, lane) for lane, q in self._queues.items()
                if len(q) >= self.batch_size]
        if full:
            _, lane = min(full)
            return lane[0], self._pop(lane)

        heads = [(q[0].t_enqueue, lane)
                 for lane, q in self._queues.items() if q]
        if not heads:
            return None, None
        t_head, lane = min(heads)
        if drain or now - t_head >= max_wait_s:
            return lane[0], self._pop(lane)
        return None, t_head + max_wait_s

    def _pop(self, lane):
        q = self._queues[lane]
        return [q.popleft() for _ in range(min(len(q), self.batch_size))]

    def assemble(self, requests):
        """Stack a batch's encoded pairs, filling up to ``batch_size``
        by tiling the last request (partial batches ride the full
        batch's compiled program; filled outputs are dropped by the
        response crop). Returns ``(img1, img2, fill)``."""
        img1 = np.stack([r.img1 for r in requests])
        img2 = np.stack([r.img2 for r in requests])
        fill = self.batch_size - len(requests)
        if fill > 0:
            img1 = np.concatenate([img1, np.repeat(img1[-1:], fill, axis=0)])
            img2 = np.concatenate([img2, np.repeat(img2[-1:], fill, axis=0)])
        return img1, img2, fill
