"""Mesh construction and pytree sharding helpers."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# data-parallel degree of the step function currently being built/traced
# (see set_data_axis_size) — models read this to convert global-batch
# memory estimates into per-chip ones under SPMD
_data_axis_size = 1


def set_data_axis_size(n):
    """Record the data-axis device count for subsequent model traces.

    Called by the step builders (``make_train_step``/``make_eval_step``):
    under SPMD a module traces with the GLOBAL batch, so any HBM budget
    the trace computes from shapes (e.g. raft/fs's volume dispatch,
    ``RMD_FS_VOLUME_GIB``) must be scaled by the data-parallel degree to
    describe one chip. 1 = unsharded.
    """
    global _data_axis_size
    _data_axis_size = max(1, int(n))


def data_axis_size():
    """Data-parallel degree the current trace should assume (>= 1)."""
    return _data_axis_size


def data_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D mesh over ``n_devices`` (default: all) for data parallelism."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(batch, mesh, axis_name="data"):
    """Place a host batch on the mesh, sharded along the leading axis.

    Single-process: ``batch`` is the global batch, device_put with a
    sharded layout. Multi-process (multi-host pods): ``batch`` is this
    process's LOCAL slice — the global array is assembled from every
    process's contribution (``jax.make_array_from_process_local_data``),
    so the global batch size is ``local · process_count``. Works on any
    pytree of arrays with a common leading batch dimension.
    """
    spec = NamedSharding(mesh, P(axis_name))
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                spec, np.asarray(x)),
            batch,
        )
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def batch_nbytes(batch):
    """Total bytes of a (pytree) host batch — the wire volume one
    ``shard_batch``/``device_put`` call moves across the host→device
    boundary. Telemetry records this per step as ``wire_bytes``."""
    return int(sum(x.nbytes for x in jax.tree.leaves(batch)
                   if hasattr(x, "nbytes")))


def replicate(tree, mesh):
    """Replicate a pytree (params, optimizer state) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, spec), tree)
