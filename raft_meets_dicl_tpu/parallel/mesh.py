"""Mesh construction and pytree sharding helpers.

Two mesh flavors:

- :func:`data_mesh` — the historical 1-D ``data`` mesh (pure batch
  parallelism, parameters replicated).
- :func:`make_mesh` — the 2-D ``(data × model)`` mesh for true SPMD
  scale-out: the batch shards over ``data``, wide parameter tensors
  (and their optimizer moments) shard over ``model`` via
  ``parallel.partition``. ``model=1`` degenerates to the 1-D data mesh,
  preserving the historical program bit-for-bit.
"""

import contextlib
import contextvars

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# data-parallel degree of the step function currently being built/traced —
# models read this to convert global-batch memory estimates into per-chip
# ones under SPMD. A ContextVar (not a module global) so nested/concurrent
# step builds — a train step and an eval step over different meshes, or a
# process-local validation jit interleaved with the sharded trace — can't
# leak each other's scale factor: each scope restores whatever value its
# enclosing scope had.
_data_axis = contextvars.ContextVar("rmd_data_axis_size", default=1)


@contextlib.contextmanager
def scoped_data_axis_size(n):
    """Scope the published data-parallel degree to the ``with`` body.

    Under SPMD a module traces with the GLOBAL batch, so any HBM budget
    the trace computes from shapes (e.g. raft/fs's volume dispatch,
    ``RMD_FS_VOLUME_GIB``) must be scaled by the data-parallel degree to
    describe one chip. Nested scopes restore the enclosing scope's value
    on exit (not a hard reset to 1), so a sharded trace that triggers an
    inner unsharded build — or vice versa — stays correct.
    """
    token = _data_axis.set(max(1, int(n)))
    try:
        yield
    finally:
        _data_axis.reset(token)


def set_data_axis_size(n):
    """Set the degree without scoping (legacy/test entry point).

    Prefer :func:`scoped_data_axis_size`; this exists for call sites that
    manage their own try/finally discipline.
    """
    _data_axis.set(max(1, int(n)))


def data_axis_size():
    """Data-parallel degree the current trace should assume (>= 1)."""
    return _data_axis.get()


def data_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D mesh over ``n_devices`` (default: all) for data parallelism."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def parse_mesh_spec(spec):
    """Parse a ``--mesh`` / env-config mesh spec into ``(data, model)``.

    Accepted forms:

    - ``None`` / ``''`` / ``'data'`` — pure data parallelism over all
      devices (returns ``None``: the caller builds the default 1-D mesh),
    - ``'D,M'`` or ``'DxM'`` — explicit 2-D shape, e.g. ``'4,2'``;
      ``D = -1`` means "all remaining devices" (``-1,2`` on 8 chips is
      ``(4, 2)``),
    - ``'D'`` — 1-D data mesh over exactly D devices (``(D, 1)``),
    - a mapping with ``data`` / ``model`` keys (env config form).
    """
    if spec is None:
        return None
    if isinstance(spec, dict):
        return (int(spec.get("data", -1)), int(spec.get("model", 1)))
    if isinstance(spec, (tuple, list)):
        d, m = spec
        return (int(d), int(m))
    s = str(spec).strip().lower()
    if not s or s == "data":
        return None
    parts = [p.strip() for p in s.replace("x", ",").split(",") if p.strip()]
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        raise ValueError(
            f"invalid mesh spec '{spec}': expected 'data', 'D', 'D,M' or "
            "'DxM' (e.g. '4,2'; data=-1 fills the remaining devices)"
        ) from None
    if len(dims) == 1:
        return (dims[0], 1)
    if len(dims) != 2:
        raise ValueError(
            f"invalid mesh spec '{spec}': at most two axes (data, model)")
    return (dims[0], dims[1])


def make_mesh(spec=None, devices=None, data_axis="data", model_axis="model"):
    """Build the SPMD mesh from a ``(data, model)`` spec.

    ``spec=None`` or ``model == 1`` returns the historical 1-D ``data``
    mesh over all selected devices — same axes, same device order, so the
    compiled program is bit-identical to the pre-2D-mesh path. A real
    ``model > 1`` returns a 2-D ``(data × model)`` mesh; ``data = -1``
    fills with the remaining devices.
    """
    devs = list(devices if devices is not None else jax.devices())
    if spec is None:
        return Mesh(np.array(devs), (data_axis,))

    data, model = (int(spec[0]), int(spec[1]))
    if model < 1:
        raise ValueError(f"invalid mesh model-axis size {model}")
    if data == -1:
        if len(devs) % model:
            raise ValueError(
                f"{len(devs)} devices do not divide over model={model}")
        data = len(devs) // model
    if data < 1:
        raise ValueError(f"invalid mesh data-axis size {data}")
    if data * model > len(devs):
        raise ValueError(
            f"mesh ({data}, {model}) needs {data * model} devices, "
            f"only {len(devs)} available"
        )
    devs = devs[: data * model]

    if model == 1:
        # bit-for-bit the 1-D data mesh: same program as before the 2-D
        # mesh existed (no degenerate singleton axis in the HLO shardings)
        return Mesh(np.array(devs), (data_axis,))
    return Mesh(np.array(devs).reshape(data, model),
                (data_axis, model_axis))


def mesh_data_size(mesh, axis_name="data"):
    """Size of the mesh's data axis (total devices on a 1-D mesh)."""
    if axis_name in mesh.axis_names:
        return int(mesh.shape[axis_name])
    return int(mesh.devices.size)


def shard_batch(batch, mesh, axis_name=None):
    """Place a host batch on the mesh, sharded along the leading axis.

    Single-process: ``batch`` is the global batch, device_put with a
    sharded layout. Multi-process (multi-host pods): ``batch`` is this
    process's LOCAL slice — the global array is assembled from every
    process's contribution (``jax.make_array_from_process_local_data``),
    so the global batch size is ``local · process_count``. Works on any
    pytree of arrays with a common leading batch dimension. The leading
    axis splits over EVERY mesh axis (``partition.batch_spec``): on a
    2-D mesh the ``model`` axis shards parameter storage between steps
    but carries batch slices during compute. Pass ``axis_name`` to pin
    a single axis instead.
    """
    if axis_name is None:
        names = tuple(mesh.axis_names)
        axis_name = names[0] if len(names) == 1 else names
    spec = NamedSharding(mesh, P(axis_name))
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                spec, np.asarray(x)),
            batch,
        )
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def batch_nbytes(batch):
    """Total bytes of a (pytree) host batch — the wire volume one
    ``shard_batch``/``device_put`` call moves across the host→device
    boundary. Telemetry records this per step as ``wire_bytes``."""
    return int(sum(x.nbytes for x in jax.tree.leaves(batch)
                   if hasattr(x, "nbytes")))


def replicate(tree, mesh):
    """Replicate a pytree (params, optimizer state) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, spec), tree)
