"""Mesh construction and pytree sharding helpers."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D mesh over ``n_devices`` (default: all) for data parallelism."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(batch, mesh, axis_name="data"):
    """Place a host batch on the mesh, sharded along the leading axis.

    Single-process: ``batch`` is the global batch, device_put with a
    sharded layout. Multi-process (multi-host pods): ``batch`` is this
    process's LOCAL slice — the global array is assembled from every
    process's contribution (``jax.make_array_from_process_local_data``),
    so the global batch size is ``local · process_count``. Works on any
    pytree of arrays with a common leading batch dimension.
    """
    spec = NamedSharding(mesh, P(axis_name))
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                spec, np.asarray(x)),
            batch,
        )
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def replicate(tree, mesh):
    """Replicate a pytree (params, optimizer state) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, spec), tree)
