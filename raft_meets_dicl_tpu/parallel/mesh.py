"""Mesh construction and pytree sharding helpers."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D mesh over ``n_devices`` (default: all) for data parallelism."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(batch, mesh, axis_name="data"):
    """Place a host batch on the mesh, sharded along the leading axis.

    The global batch size must divide the mesh axis size. Works on any
    pytree of arrays with a common leading batch dimension.
    """
    spec = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def replicate(tree, mesh):
    """Replicate a pytree (params, optimizer state) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, spec), tree)
