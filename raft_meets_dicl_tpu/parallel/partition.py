"""Rule-based parameter/optimizer partitioning over the SPMD mesh.

The historical mesh replicated every parameter (and both Adam moments) on
every chip: batch parallelism only, with optimizer-state HBM paid
``n_devices`` times. This module maps param/optimizer pytrees onto
``PartitionSpec``s via regex rules over the flattened param paths — the
partitioner pattern of large-model JAX trainers (SNIPPETS.md [1]–[3]):

- rules are ``(regex, PartitionSpec)`` pairs matched against
  ``'/'``-joined param paths; the first match wins. The spec is
  right-aligned to the leaf's trailing dimensions, so ``P('model')`` on
  an HWIO conv kernel shards the output-channel dim.
- defaults shard the wide feature/context-encoder and update-block conv
  kernels over the ``model`` axis; biases, norm scales, and scalars stay
  replicated.
- optimizer-state moments (Adam ``mu``/``nu`` & co. — any leaf whose
  path suffix names a parameter of the same shape) clone their param's
  spec; step counters and other scalars replicate.
- a rule whose sharded dimension does not divide by the mesh axis falls
  back to replication for that leaf — partial sharding beats a
  partitioner error on an odd channel count.

Execution model: the rules shard *storage* (ZeRO-style). The train step
all-gathers the sharded params once per step for the forward/backward —
the numerically-proven pure data-parallel compute graph, with the batch
split over every mesh device — then reduces the gradients back onto the
param shards for the (elementwise, shard-local) optimizer update. Per
chip, params and both Adam moments shrink by the model-axis factor at
rest; the transient gather is one params-sized buffer that XLA overlaps
with compute. (Letting GSPMD propagate the model axis through the conv
compute itself was measured numerically unsafe on the XLA CPU backend —
the partially-replicated concat/all-reduce path miscompiles — and the
gather-compute form is what the per-chip HBM motivation needs anyway.)

On a mesh without a ``model`` axis (or with ``model=1``) every spec
degenerates to ``P()``: the emitted program is the historical replicated
one, bit for bit.
"""

import re
import warnings

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path

# Default rules: the parameter mass sits in conv kernels — the siamese
# feature encoder, the context encoder, the recurrent update block
# (motion encoder + GRU + flow head), the convex-upsampling head, and the
# DICL matching/embedding nets. Their kernels shard output channels over
# ``model``; everything else (biases, norm affines, BN stats, scalars)
# replicates.
DEFAULT_RULES = (
    (r"(FeatureEncoder|StackEncoder|PoolEncoder|Rfpm)[^/]*/.*kernel$",
     P("model")),
    (r"(UpdateBlock|MotionEncoder|RecurrentBlock|SepConvGru|ConvGru)"
     r"[^/]*/.*kernel$", P("model")),
    (r"(FlowHead|Up8Network|UpNetwork|MatchingNet|PairEmbedding|DapNetwork)"
     r"[^/]*/.*kernel$", P("model")),
    (r".*", P()),
)


def _path_str(path):
    """``'/'``-joined flattened pytree path (dict keys, attr names,
    sequence indices)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


def batch_spec(mesh):
    """Batch PartitionSpec: the leading dim splits over EVERY mesh axis.

    On the 1-D mesh this is the historical ``P('data')`` (exactly that
    object form — a 1-tuple wrapper is not spec-identical and would make
    jit reshard already-placed batches). On a 2-D ``(data × model)``
    mesh the batch splits over both axes — under the gather-compute
    execution model the ``model`` axis carries batch slices during
    compute (it only shards parameter *storage* between steps), so all
    ``data × model`` devices contribute data parallelism and no
    activation is ever partially replicated.
    """
    names = tuple(mesh.axis_names)
    return P(names[0] if len(names) == 1 else names)


def data_sharding(mesh, axis_name=None):
    """Batch sharding: leading dim split over the mesh (see
    :func:`batch_spec`); pass ``axis_name`` to pin a single axis."""
    if axis_name is not None:
        return NamedSharding(mesh, P(axis_name))
    return NamedSharding(mesh, batch_spec(mesh))


def replicated(mesh):
    """Fully-replicated sharding on ``mesh``."""
    return NamedSharding(mesh, P())


def is_sharded(sharding_tree):
    """True when any leaf of a sharding pytree actually partitions —
    i.e. the tree is not the degenerate fully-replicated layout. The
    step builders use this to skip the gather/reduce constraints (and
    keep the historical program bit-for-bit) when there is nothing to
    gather."""
    leaves = jax.tree.leaves(
        sharding_tree, is_leaf=lambda x: isinstance(x, NamedSharding))
    return any(isinstance(s, NamedSharding) and tuple(s.spec)
               for s in leaves)


class Partitioner:
    """Maps params/optimizer/TrainState pytrees onto mesh shardings.

    One instance per mesh; the step builders and the evaluation path both
    ask it for their shardings instead of hand-constructing
    ``NamedSharding``s, so a sharded-parameter layout propagates
    everywhere at once.
    """

    def __init__(self, mesh, rules=None, data_axis="data",
                 model_axis="model"):
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.rules = [(re.compile(rx), spec)
                      for rx, spec in (DEFAULT_RULES if rules is None
                                       else rules)]

    # -- axis geometry -----------------------------------------------------

    @property
    def data_size(self):
        if self.data_axis in self.mesh.axis_names:
            return int(self.mesh.shape[self.data_axis])
        return int(self.mesh.devices.size)

    @property
    def model_size(self):
        if self.model_axis in self.mesh.axis_names:
            return int(self.mesh.shape[self.model_axis])
        return 1

    # -- spec resolution ---------------------------------------------------

    def spec(self, name, shape):
        """PartitionSpec for one named leaf of the given shape."""
        shape = tuple(shape)
        if self.model_size <= 1 or len(shape) == 0 \
                or int(np.prod(shape)) == 1:
            return P()
        for rx, spec in self.rules:
            if rx.search(name):
                return self._fit(spec, shape)
        return P()

    def _fit(self, spec, shape):
        """Right-align ``spec`` to the leaf's trailing dims; fall back to
        replication when a sharded dim does not divide by its axis."""
        parts = tuple(spec)
        if len(parts) > len(shape):
            return P()
        full = (None,) * (len(shape) - len(parts)) + parts
        for dim, axis in zip(shape, full):
            if axis is None:
                continue
            names = (axis,) if isinstance(axis, str) else tuple(axis)
            size = int(np.prod([self.mesh.shape[n] for n in names]))
            if size and dim % size:
                return P()
        while full and full[-1] is None:
            full = full[:-1]
        return P(*full)

    # -- sharding trees ----------------------------------------------------

    def param_shardings(self, params):
        """NamedSharding pytree for a parameter tree (rule-matched)."""
        return self._map_named(params, self.spec)

    def opt_shardings(self, opt_state, params):
        """NamedSharding pytree for an optimizer state.

        Moment buffers clone their parameter's spec: any opt-state leaf
        whose flattened-path *suffix* names a parameter of identical
        shape (Adam's ``mu``/``nu`` subtrees mirror the param tree under
        their own prefix) inherits that parameter's spec; every other
        leaf — step counts, EMA scalars, clip state — replicates.
        """
        by_path = {}
        for path, leaf in tree_flatten_with_path(params)[0]:
            name = _path_str(path)
            by_path[name] = (tuple(leaf.shape), self.spec(name, leaf.shape))

        def opt_spec(name, shape):
            parts = name.split("/")
            for i in range(len(parts)):
                cand = "/".join(parts[i:])
                hit = by_path.get(cand)
                if hit is not None and hit[0] == tuple(shape):
                    return hit[1]
            return P()

        return self._map_named(opt_state, opt_spec)

    def state_shardings(self, state):
        """Full TrainState sharding: params by rules, optimizer moments
        cloned from them, batch stats and scalar counters replicated."""
        return state.replace(
            params=self.param_shardings(state.params),
            batch_stats=jax.tree.map(
                lambda _: replicated(self.mesh), state.batch_stats),
            opt_state=self.opt_shardings(state.opt_state, state.params),
            step=replicated(self.mesh),
            nonfinite_count=replicated(self.mesh),
        )

    def variables_sharding(self, variables):
        """Model-variables sharding for the eval path: params by rules,
        everything else (batch stats & co.) replicated."""
        out = {k: jax.tree.map(lambda _: replicated(self.mesh), v)
               for k, v in variables.items() if k != "params"}
        out["params"] = self.param_shardings(variables["params"])
        return out

    def batch_sharding(self):
        return data_sharding(self.mesh, self.data_axis)

    def replicated(self):
        return replicated(self.mesh)

    def _map_named(self, tree, spec_fn):
        leaves, treedef = tree_flatten_with_path(tree)
        shardings = [
            NamedSharding(self.mesh, spec_fn(_path_str(path), leaf.shape))
            for path, leaf in leaves
        ]
        return jax.tree.unflatten(treedef, shardings)

    # -- rule-coverage audit -----------------------------------------------

    def coverage(self, params):
        """Static rule-coverage audit over a parameter tree.

        Consults the raw rule list directly — bypassing the
        ``model_size <= 1`` degeneration in :meth:`spec` — so a 1-chip
        CI run still validates the rule set against a real param tree.

        A *dead rule* is one with a non-trivial spec (it was written to
        shard something) that matches zero param paths: a typo'd module
        name silently replicates everything it meant to shard.
        ``unmatched`` lists paths no rule claims at all (impossible with
        the default catch-all, but a custom rule list can drop it).
        """
        paths = [_path_str(p)
                 for p, _ in tree_flatten_with_path(params)[0]]
        counts = [0] * len(self.rules)
        unmatched = []
        for name in paths:
            for i, (rx, _spec) in enumerate(self.rules):
                if rx.search(name):
                    counts[i] += 1
                    break
            else:
                unmatched.append(name)
        dead = [rx.pattern
                for (rx, spec), n in zip(self.rules, counts)
                if n == 0 and tuple(spec)]
        return {
            "n_paths": len(paths),
            "rule_matches": [(rx.pattern, n)
                             for (rx, _), n in zip(self.rules, counts)],
            "dead_rules": dead,
            "unmatched": unmatched,
        }

    # -- placement + accounting --------------------------------------------

    def shard_state(self, state):
        """Place a TrainState according to the rules (device_put)."""
        cov = self.coverage(state.params)
        if cov["dead_rules"] or cov["unmatched"]:
            warnings.warn(
                f"partition rules audit: dead rules {cov['dead_rules']}, "
                f"unmatched paths {cov['unmatched'][:5]}"
                f"{'...' if len(cov['unmatched']) > 5 else ''} "
                f"(of {cov['n_paths']} param paths)",
                stacklevel=2)
        return jax.device_put(state, self.state_shardings(state))

    def shard_variables(self, variables):
        return jax.device_put(variables, self.variables_sharding(variables))

    def report(self, state):
        """Per-chip byte accounting for the telemetry ``sharding`` event.

        ``*_bytes_per_chip`` is what one device actually holds under the
        current placement; ``*_bytes_replicated`` is what it would hold
        fully replicated (the historical layout). The delta is the HBM
        the partitioner bought back per chip.
        """
        def account(tree):
            total = per_chip = n_sharded = n_leaves = 0
            for leaf in jax.tree.leaves(tree):
                nbytes = int(getattr(leaf, "nbytes", 0))
                total += nbytes
                n_leaves += 1
                shards = getattr(leaf, "addressable_shards", None)
                if shards:
                    dev0 = shards[0].device
                    mine = sum(int(s.data.nbytes) for s in shards
                               if s.device == dev0)
                else:
                    mine = nbytes
                per_chip += mine
                if mine < nbytes:
                    n_sharded += 1
            return total, per_chip, n_sharded, n_leaves

        p_tot, p_chip, p_sh, p_n = account(state.params)
        o_tot, o_chip, o_sh, o_n = account(state.opt_state)
        cov = self.coverage(state.params)
        return {
            "dead_rules": cov["dead_rules"],
            "unmatched_paths": len(cov["unmatched"]),
            "mesh": {name: int(self.mesh.shape[name])
                     for name in self.mesh.axis_names},
            "params_bytes_replicated": p_tot,
            "params_bytes_per_chip": p_chip,
            "params_sharded_leaves": p_sh,
            "params_leaves": p_n,
            "opt_bytes_replicated": o_tot,
            "opt_bytes_per_chip": o_chip,
            "opt_sharded_leaves": o_sh,
            "opt_leaves": o_n,
        }
