"""Distributed execution layer: device meshes, sharded train steps.

The reference's entire parallelism story is single-process
``nn.DataParallel`` (src/cmd/train.py:183-184 — scatter the batch over
GPUs, implicit NCCL). The TPU-native equivalent is SPMD over a
``jax.sharding.Mesh``: annotate the batch with a ``data`` axis sharding,
keep parameters replicated, and let XLA insert the gradient all-reduces
over ICI. The same compiled program runs single-chip, one pod slice, or
multi-host over DCN (with ``jax.distributed.initialize``) — there is no
separate code path.

Axes:
- ``data``  — batch parallelism (the reference's DataParallel equivalent)
- ``space`` — optional spatial sharding for the O(H²W²) correlation volume
  at high resolution (the framework's long-context axis)
"""

from .distributed import initialize, is_primary, process_count, process_index
from .mesh import (
    batch_nbytes, data_axis_size, data_mesh, replicate, set_data_axis_size,
    shard_batch,
)
from .train import TrainState, make_eval_step, make_train_step

__all__ = [
    "batch_nbytes", "data_axis_size", "data_mesh", "replicate",
    "set_data_axis_size", "shard_batch",
    "TrainState", "make_eval_step", "make_train_step",
    "initialize", "is_primary", "process_count", "process_index",
]
