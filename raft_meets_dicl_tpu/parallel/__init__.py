"""Distributed execution layer: device meshes, partitioned train steps.

The reference's entire parallelism story is single-process
``nn.DataParallel`` (src/cmd/train.py:183-184 — scatter the batch over
GPUs, implicit NCCL). The TPU-native equivalent is SPMD over a
``jax.sharding.Mesh``: annotate the batch with a sharded leading axis,
place parameters per the partition rules, and let XLA insert the
gradient all-reduces over ICI. The same compiled program runs
single-chip, one pod slice, or multi-host over DCN (with
``jax.distributed.initialize``) — there is no separate code path.

Axes:
- ``data``  — batch parallelism (the reference's DataParallel equivalent)
- ``model`` — parameter/optimizer *storage* sharding (ZeRO-style): the
  regex partitioner in ``partition.py`` maps the wide encoder and
  update-block kernels (and their Adam moments) onto this axis; the
  train step gathers them once per step and the batch still splits over
  every device, so per-chip HBM shrinks without touching the proven
  data-parallel compute graph. ``make_mesh((data, model))`` builds the
  2-D mesh; ``model=1`` degenerates to the historical 1-D layout
  bit-for-bit.
"""

from .distributed import initialize, is_primary, process_count, process_index
from .mesh import (
    batch_nbytes, data_axis_size, data_mesh, make_mesh, mesh_data_size,
    parse_mesh_spec, replicate, scoped_data_axis_size, set_data_axis_size,
    shard_batch,
)
from .partition import DEFAULT_RULES, Partitioner, data_sharding, replicated
from .train import TrainState, make_eval_step, make_train_step

__all__ = [
    "batch_nbytes", "data_axis_size", "data_mesh", "make_mesh",
    "mesh_data_size", "parse_mesh_spec", "replicate",
    "scoped_data_axis_size", "set_data_axis_size", "shard_batch",
    "DEFAULT_RULES", "Partitioner", "data_sharding", "replicated",
    "TrainState", "make_eval_step", "make_train_step",
    "initialize", "is_primary", "process_count", "process_index",
]
