"""Sharded train/eval step builders.

One jitted SPMD program: parameters replicated, batch sharded over the
``data`` mesh axis. The loss is a global mean, so XLA's partitioner emits
the psum/all-reduce over ICI by itself — the explicit NCCL choreography the
reference delegates to ``nn.DataParallel`` doesn't exist here.

Gradient clipping and accumulation are optax transforms configured by the
strategy layer; this module only owns the step function shape.
"""

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ..telemetry import instrument_jit
from .mesh import set_data_axis_size


def _with_data_axis(n, fn):
    """Scope the published data-parallel degree to ``fn``'s calls.

    The model traces inside the first call of the jitted function, so the
    degree must be pinned around the call, not at build time — otherwise
    an interleaved unsharded trace (e.g. the inspector's process-local
    validation jit) would read a stale value. Resets to 1 on exit so
    unsharded traces always see the unsharded degree.
    """

    def wrapped(*args, **kwargs):
        set_data_axis_size(n)
        try:
            return fn(*args, **kwargs)
        finally:
            set_data_axis_size(1)

    return wrapped


class TrainState(struct.PyTreeNode):
    """Everything the train step carries: params, BN stats, optimizer.

    ``nonfinite_count`` is the cumulative number of optimizer updates the
    skip-guard refused to apply (see ``make_train_step(nonfinite='skip')``)
    — living on device, it rides along for free and lets the host read
    "how many steps tripped since the last fetch" with the same amortized
    fetch that resolves the finite flag, instead of a per-step sync.
    """

    params: Any
    batch_stats: Any
    opt_state: Any
    step: jax.Array
    nonfinite_count: jax.Array

    @classmethod
    def create(cls, variables, tx):
        params = variables["params"]
        return cls(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
            nonfinite_count=jnp.zeros((), jnp.int32),
        )

    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


def make_train_step(model, loss_fn, tx, mesh=None, loss_args=None,
                    model_args=None, donate=True, external_lr=False,
                    with_grads=False, wire=None, nonfinite=None):
    """Build the jitted training step.

    Static per-stage configuration (``model_args``, ``loss_args``) is baked
    in — a new stage builds a new step function, recompiling as the
    reference re-builds its optimizer per stage.

    With ``external_lr`` the step takes the learning rate as its second
    argument and scales the optimizer's (lr-less) updates by ``-lr`` — the
    strategy layer's host-side schedulers drive it. Without it, ``tx`` must
    contain its own lr scaling.

    With ``mesh``, input/output shardings are annotated: state replicated,
    batch split on the leading axis over ``data``.

    ``with_grads`` adds the raw gradient pytree to ``aux`` for inspection
    (gradient-statistics metrics). Off by default: returning grads keeps a
    second params-sized buffer alive past the optimizer update, defeating
    donation.

    ``wire`` (a ``models.wire.WireFormat``) makes the step accept
    wire-format batches: compact-dtype images that are dequantized and
    clip/range-normalized on device, f16 flow, optionally bit-packed
    valid masks. The host-side pipeline must then skip normalization
    (``InputSpec.apply(..., normalize=False)``).

    ``nonfinite='skip'`` compiles the skip-step discipline of dynamic
    loss scaling (Micikevicius et al. 2018) into the step: when the
    final flow or the post-clip update tree contains a non-finite value,
    the params/batch-stats/optimizer update is dropped on device (the
    previous state carries forward bit-identically) and
    ``state.nonfinite_count`` increments. ``aux['finite']`` then means
    "this step's update applied"; detection needs no extra host sync.
    The default (None) keeps the unguarded update: NaNs are absorbing
    through the optimizer state, which is what the ``raise`` policy's
    amortized trip detection relies on.
    """
    loss_args = dict(loss_args or {})
    model_args = dict(model_args or {})
    guard = nonfinite == "skip"

    def step(state, lr, img1, img2, flow, valid):
        if wire is not None:
            img1, img2, flow, valid = wire.decode(img1, img2, flow, valid)

        def compute_loss(params):
            out, new_bs = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                img1, img2, train=True, **model_args,
            )
            result = model.get_adapter().wrap_result(out, img1.shape[1:3])
            l = loss_fn(model, result.output(), flow, valid, **loss_args)
            return l, (new_bs, result.final())

        (loss, (new_bs, final)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if external_lr:
            updates = jax.tree.map(lambda u: -lr * u, updates)
        new_params = optax.apply_updates(state.params, updates)

        finite = jnp.all(jnp.isfinite(final))
        nf_count = state.nonfinite_count

        if guard:
            # the update tree is where every poison ends up (NaN grads ->
            # NaN moments -> NaN updates; NaN lr -> NaN updates), so one
            # reduce over it catches grad/optimizer/lr poison before the
            # params do — checking it alongside the flow keeps batch_stats
            # poison (via a NaN loss/forward) covered too
            ok = finite
            for leaf in jax.tree.leaves(updates):
                ok &= jnp.all(jnp.isfinite(leaf))

            def keep(new, old):
                return jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o), new, old)

            new_params = keep(new_params, state.params)
            new_bs = keep(new_bs, state.batch_stats)
            new_opt = keep(new_opt, state.opt_state)
            finite = ok
            nf_count = nf_count + jnp.where(ok, 0, 1).astype(jnp.int32)

        new_state = state.replace(
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
            step=state.step + 1,
            nonfinite_count=nf_count,
        )
        aux = {
            "loss": loss,
            "final": final,
            "finite": finite,
            "nonfinite_count": nf_count,
        }
        if with_grads:
            aux["grads"] = grads
        return new_state, aux

    if external_lr:
        public = step
        n_lead = 2  # (state, lr, ...)
    else:
        # bind a dummy lr so the public signature stays (state, batch...)
        def public(state, img1, img2, flow, valid):
            return step(state, 0.0, img1, img2, flow, valid)

        n_lead = 1

    # instrument_jit: a passthrough label wrapper so telemetry attributes
    # this function's (re)compiles to 'train_step' in compile events
    if mesh is None:
        return instrument_jit(
            "train_step",
            jax.jit(public, donate_argnums=(0,) if donate else ()))

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    aux_shardings = {"loss": repl, "final": data, "finite": repl,
                     "nonfinite_count": repl}
    if with_grads:
        aux_shardings["grads"] = repl

    in_shardings = (repl,) + (None,) * (n_lead - 1) + (data,) * 4
    return instrument_jit("train_step", _with_data_axis(
        mesh.devices.size,
        jax.jit(
            public,
            in_shardings=in_shardings,
            out_shardings=(repl, aux_shardings),
            donate_argnums=(0,) if donate else (),
        )))


def make_eval_step(model, mesh=None, model_args=None, wire=None):
    """Build the jitted inference step returning the final flow.

    ``wire`` decodes compact-dtype images on device (see
    ``make_train_step``); flow/valid never cross into the eval step.
    """
    model_args = dict(model_args or {})

    def step(variables, img1, img2):
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        out = model.apply(variables, img1, img2, train=False, **model_args)
        result = model.get_adapter().wrap_result(out, img1.shape[1:3])
        return result.final()

    if mesh is None:
        return instrument_jit("eval_step", jax.jit(step))

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    return instrument_jit("eval_step", _with_data_axis(
        mesh.devices.size,
        jax.jit(step, in_shardings=(repl, data, data), out_shardings=data)))
