"""Sharded train/eval step builders.

One jitted SPMD program: the batch shards over the ``data`` mesh axis and
the parameters live wherever the partitioner put them — fully replicated
on the historical 1-D mesh, or sharded over ``model`` on a 2-D
``(data × model)`` mesh (``parallel.partition``). The loss is a global
mean, so XLA's partitioner emits the psum/all-reduce over ICI by itself —
the explicit NCCL choreography the reference delegates to
``nn.DataParallel`` doesn't exist here.

Gradient clipping is an optax transform configured by the strategy layer.
Gradient accumulation has two forms: the legacy host-driven
``optax.MultiSteps`` (k step calls per optimizer update), and the in-step
``accumulate=k`` — a ``lax.scan`` over k microbatches summing gradients
before one optimizer apply, which buys k× effective batch for one extra
params-sized buffer instead of k× activation HBM.
"""

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ..compile import register_step
from . import partition
from .mesh import scoped_data_axis_size


def _with_data_axis(n, fn):
    """Scope the published data-parallel degree to ``fn``'s calls.

    The model traces inside the first call of the jitted function, so the
    degree must be pinned around the call, not at build time — otherwise
    an interleaved unsharded trace (e.g. the inspector's process-local
    validation jit) would read a stale value. ``scoped_data_axis_size``
    restores the enclosing scope's degree on exit, so nested/concurrent
    step builds over different meshes can't leak into each other.
    """

    def wrapped(*args, **kwargs):
        with scoped_data_axis_size(n):
            return fn(*args, **kwargs)

    inner_lower = getattr(fn, "lower", None)
    if inner_lower is not None:
        # AOT entry point: tracing happens inside lower(), so it needs
        # the same scoped degree as a live call
        def lower(*args, **kwargs):
            with scoped_data_axis_size(n):
                return inner_lower(*args, **kwargs)

        wrapped.lower = lower
    return wrapped


class TrainState(struct.PyTreeNode):
    """Everything the train step carries: params, BN stats, optimizer.

    ``nonfinite_count`` is the cumulative number of optimizer updates the
    skip-guard refused to apply (see ``make_train_step(nonfinite='skip')``)
    — living on device, it rides along for free and lets the host read
    "how many steps tripped since the last fetch" with the same amortized
    fetch that resolves the finite flag, instead of a per-step sync.
    """

    params: Any
    batch_stats: Any
    opt_state: Any
    step: jax.Array
    nonfinite_count: jax.Array

    @classmethod
    def create(cls, variables, tx):
        params = variables["params"]
        return cls(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
            nonfinite_count=jnp.zeros((), jnp.int32),
        )

    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


def make_train_step(model, loss_fn, tx, mesh=None, loss_args=None,
                    model_args=None, donate=True, external_lr=False,
                    with_grads=False, wire=None, nonfinite=None,
                    state_sharding=None, accumulate=1, key=None,
                    augment=None):
    """Build the jitted training step, registered as a compiled program.

    Static per-stage configuration (``model_args``, ``loss_args``) is baked
    in — a new stage builds a new step function, recompiling as the
    reference re-builds its optimizer per stage.

    With ``external_lr`` the step takes the learning rate as its second
    argument and scales the optimizer's (lr-less) updates by ``-lr`` — the
    strategy layer's host-side schedulers drive it. Without it, ``tx`` must
    contain its own lr scaling.

    With ``mesh``, input/output shardings are annotated: the batch splits
    on the leading axis over every mesh axis; the state follows
    ``state_sharding`` — a ``TrainState``-shaped pytree of
    ``NamedSharding``s from ``partition.Partitioner.state_shardings``
    (None keeps the historical fully-replicated layout). A genuinely
    sharded layout runs ZeRO-style: params all-gather to replicated for
    the forward/backward, gradients reduce back onto the shards, and the
    optimizer update stays shard-local — params and moments pay per-chip
    HBM divided by the model-axis size at rest. ``donate`` keeps
    donating the (possibly sharded) state buffers to their successors.

    ``accumulate=k`` compiles in-step gradient accumulation: the step
    takes a ``k·B`` batch, ``lax.scan``s over k microbatches of B
    (summing gradients, chaining batch-stats updates), and applies ONE
    optimizer update from the averaged gradients — k× effective batch at
    one microbatch's activation memory. The batch's leading dim must be
    divisible by k (and, under a mesh, each microbatch by the data-axis
    size).

    ``with_grads`` adds the raw gradient pytree to ``aux`` for inspection
    (gradient-statistics metrics). Off by default: returning grads keeps a
    second params-sized buffer alive past the optimizer update, defeating
    donation.

    ``wire`` (a ``models.wire.WireFormat``) makes the step accept
    wire-format batches: compact-dtype images that are dequantized and
    clip/range-normalized on device, f16 flow, optionally bit-packed
    valid masks. The host-side pipeline must then skip normalization
    (``InputSpec.apply(..., normalize=False)``).

    ``nonfinite='skip'`` compiles the skip-step discipline of dynamic
    loss scaling (Micikevicius et al. 2018) into the step: when the
    final flow or the post-clip update tree contains a non-finite value,
    the params/batch-stats/optimizer update is dropped on device (the
    previous state carries forward bit-identically) and
    ``state.nonfinite_count`` increments. ``aux['finite']`` then means
    "this step's update applied"; detection needs no extra host sync.
    The default (None) keeps the unguarded update: NaNs are absorbing
    through the optimizer state, which is what the ``raise`` policy's
    amortized trip detection relies on.

    ``key`` (a ``compile.ProgramKey``) registers the step under a stable
    identity — deduped in the process-wide registry and, when the AOT
    store is enabled, round-tripped through serialized executables so a
    repeat boot compiles nothing. Without a key the step is registered
    anonymously: compile events still attribute to 'train_step', but the
    program is private to the caller (the right default here, since the
    ``tx``/``loss_fn`` closures have no stable identity of their own).

    ``augment`` (a ``data.device_augment.DeviceAugment``) compiles the
    augmentation pipeline into the step: the public signature grows two
    trailing arguments ``(sample_ids [B] uint32, epoch int32)``, and the
    decoded batch is warped/jittered on device under per-sample keys
    derived from ``(sample_id, epoch)`` — deterministic and resumable.
    The augmented program registers as a flag variant
    (``augment=<token>`` appended to ``key``); ``augment=None`` keeps the
    historical signature and key byte-identical, so existing registered
    programs, pins, and AOT artifacts are untouched.
    """
    loss_args = dict(loss_args or {})
    model_args = dict(model_args or {})
    guard = nonfinite == "skip"
    accumulate = max(1, int(accumulate))

    # the augmented step is a distinct program: extend a caller key that
    # doesn't already carry the flag (mirrors make_eval_step's args flag)
    if (augment is not None and key is not None
            and not any(n == "augment" for n, _ in key.flags)):
        from ..compile import ProgramKey, flag_items

        key = ProgramKey(kind=key.kind, model=key.model,
                         flags=key.flags
                         + flag_items(augment=augment.describe()))

    # gather-compute only when the layout actually shards something: the
    # degenerate all-replicated sharding keeps the historical program
    # (and its compiled artifact) bit-for-bit
    gather = (mesh is not None and state_sharding is not None
              and partition.is_sharded(state_sharding.params))
    repl_one = partition.replicated(mesh) if mesh is not None else None
    bspec = partition.batch_spec(mesh) if mesh is not None else None

    def forward(params, batch_stats, img1, img2, flow, valid, keys=None):
        if wire is not None:
            img1, img2, flow, valid = wire.decode(img1, img2, flow, valid)
        if augment is not None:
            # on-device augmentation of the decoded (normalized) batch,
            # keyed per sample — inside the grad-free data path, XLA
            # schedules it alongside the forward's first convs
            img1, img2, flow, valid = augment.apply(
                keys, img1, img2, flow, valid)

        def compute_loss(p):
            out, new_bs = model.apply(
                {"params": p, "batch_stats": batch_stats},
                img1, img2, train=True, **model_args,
            )
            result = model.get_adapter().wrap_result(out, img1.shape[1:3])
            l = loss_fn(model, result.output(), flow, valid, **loss_args)
            return l, (new_bs, result.final())

        return jax.value_and_grad(compute_loss, has_aux=True)(params)

    def step(state, lr, img1, img2, flow, valid, sample_ids=None,
             epoch=None):
        # ZeRO-style gather: one all-gather of the sharded params for the
        # compute graph; XLA overlaps it with the first encoder convs
        params = (jax.lax.with_sharding_constraint(state.params, repl_one)
                  if gather else state.params)

        keys = (augment.batch_keys(sample_ids, epoch)
                if augment is not None else None)

        if accumulate == 1:
            (loss, (new_bs, final)), grads = forward(
                params, state.batch_stats, img1, img2, flow, valid, keys)
        else:
            # k microbatches through one scan: gradients sum into a
            # params-sized accumulator, batch stats chain microbatch to
            # microbatch (the same sequential update k separate steps
            # would apply), finals stack so aux keeps the full-batch
            # contract for the host-side metrics
            def split(x):
                x = x.reshape((accumulate, x.shape[0] // accumulate)
                              + x.shape[1:])
                if mesh is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, P(None, *bspec)))
                return x

            micro = jax.tree.map(split, (img1, img2, flow, valid))
            if augment is not None:
                # per-sample keys split with their samples; re-derive the
                # leading-axis layout the same way the batch does
                micro = micro + (split(keys),)

            def body(carry, mb):
                bs, gsum, lsum = carry
                (l, (new_bs, fin)), g = forward(params, bs, *mb)
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (new_bs, gsum, lsum + l), fin

            zeros = jax.tree.map(jnp.zeros_like, params)
            (new_bs, gsum, lsum), finals = jax.lax.scan(
                body,
                (state.batch_stats, zeros, jnp.zeros((), jnp.float32)),
                micro,
            )
            # each microbatch loss is a mean over its (equal-sized)
            # slice, so the mean of means is the big-batch mean — and
            # the averaged gradient sum is its gradient
            grads = jax.tree.map(lambda g: g / accumulate, gsum)
            loss = lsum / accumulate
            final = finals.reshape((-1,) + finals.shape[2:])

        if gather:
            # reduce the gradients back onto the param shards; from here
            # on the optimizer update is elementwise and shard-local
            grads = jax.lax.with_sharding_constraint(
                grads, state_sharding.params)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if external_lr:
            updates = jax.tree.map(lambda u: -lr * u, updates)
        new_params = optax.apply_updates(state.params, updates)

        finite = jnp.all(jnp.isfinite(final))
        nf_count = state.nonfinite_count

        if guard:
            # the update tree is where every poison ends up (NaN grads ->
            # NaN moments -> NaN updates; NaN lr -> NaN updates), so one
            # reduce over it catches grad/optimizer/lr poison before the
            # params do — checking it alongside the flow keeps batch_stats
            # poison (via a NaN loss/forward) covered too
            ok = finite
            for leaf in jax.tree.leaves(updates):
                ok &= jnp.all(jnp.isfinite(leaf))

            def keep(new, old):
                return jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o), new, old)

            new_params = keep(new_params, state.params)
            new_bs = keep(new_bs, state.batch_stats)
            new_opt = keep(new_opt, state.opt_state)
            finite = ok
            nf_count = nf_count + jnp.where(ok, 0, 1).astype(jnp.int32)

        new_state = state.replace(
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
            step=state.step + 1,
            nonfinite_count=nf_count,
        )
        aux = {
            "loss": loss,
            "final": final,
            "finite": finite,
            "nonfinite_count": nf_count,
            # in-step global norms: two elementwise reductions fused
            # into the compiled step, fetched host-side only at the
            # amortized finite-check cadence (observability gauges)
            "grad_norm": optax.global_norm(grads),
            "update_norm": optax.global_norm(updates),
        }
        if with_grads:
            aux["grads"] = grads
        return new_state, aux

    if external_lr:
        if augment is not None:
            # exact-arity wrapper: jit sharding specs match positionally
            def public(state, lr, img1, img2, flow, valid, sample_ids,
                       epoch):
                return step(state, lr, img1, img2, flow, valid,
                            sample_ids, epoch)
        else:
            public = step
        n_lead = 2  # (state, lr, ...)
    else:
        # bind a dummy lr so the public signature stays (state, batch...)
        if augment is not None:
            def public(state, img1, img2, flow, valid, sample_ids, epoch):
                return step(state, 0.0, img1, img2, flow, valid,
                            sample_ids, epoch)
        else:
            def public(state, img1, img2, flow, valid):
                return step(state, 0.0, img1, img2, flow, valid)

        n_lead = 1

    # register_step: the registry Program attributes this function's
    # (re)compiles to 'train_step' in compile events, counts them
    # per-program, and (stable key + AOT store on) owns the serialized
    # executables
    if mesh is None:
        prog = register_step(
            "train_step",
            jax.jit(public, donate_argnums=(0,) if donate else ()),
            key=key)
        if augment is not None:
            prog.augment = augment
        return prog

    repl = partition.replicated(mesh)
    data = partition.data_sharding(mesh)
    state_in = state_sharding if state_sharding is not None else repl
    aux_shardings = {"loss": repl, "final": data, "finite": repl,
                     "nonfinite_count": repl, "grad_norm": repl,
                     "update_norm": repl}
    if with_grads:
        # gradients shard exactly like the parameters they differentiate
        aux_shardings["grads"] = (state_sharding.params
                                  if gather else repl)

    in_shardings = (state_in,) + (None,) * (n_lead - 1) + (data,) * 4
    if augment is not None:
        # sample ids shard with their samples; the epoch scalar replicates
        in_shardings = in_shardings + (data, None)
    prog = register_step("train_step", _with_data_axis(
        mesh.devices.size,
        jax.jit(
            public,
            in_shardings=in_shardings,
            out_shardings=(state_in, aux_shardings),
            donate_argnums=(0,) if donate else (),
        )), key=key)
    if augment is not None:
        prog.augment = augment
    return prog


def make_eval_step(model, mesh=None, model_args=None, wire=None,
                   variables_sharding=None, key=None):
    """Build the jitted inference step returning the final flow.

    ``wire`` decodes compact-dtype images on device (see
    ``make_train_step``). ``variables_sharding`` (a variables-shaped
    pytree of ``NamedSharding``s, e.g. from
    ``partition.Partitioner.variables_sharding``) lets the eval step
    take model-sharded parameters directly — they gather to replicated
    inside the step; None keeps them replicated. ``key`` registers the
    step under a stable ``compile.ProgramKey`` (dedupe + AOT), as in
    ``make_train_step``.
    """
    model_args = dict(model_args or {})

    # a caller-provided key must encode the *effective* model arguments
    # (config defaults merged under explicit overrides, exactly how
    # Model.apply resolves them): without this, e.g. a non-default
    # ``iterations`` count silently shares the default program's key —
    # and its AOT artifact — with the default-count model
    if key is not None and not any(n == "args" for n, _ in key.flags):
        from ..compile import ProgramKey, flag_items
        from ..evaluation import static_args_key

        args_key = static_args_key(
            dict(getattr(model, "arguments", {})) | model_args)
        if args_key is None:
            key = None  # unkeyable (array-valued) args: never dedupe
        else:
            key = ProgramKey(kind=key.kind, model=key.model,
                             flags=key.flags + flag_items(args=args_key))

    gather = (mesh is not None and variables_sharding is not None
              and partition.is_sharded(variables_sharding))
    repl_one = partition.replicated(mesh) if mesh is not None else None

    def step(variables, img1, img2):
        if gather:
            variables = jax.lax.with_sharding_constraint(variables, repl_one)
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        out = model.apply(variables, img1, img2, train=False, **model_args)
        result = model.get_adapter().wrap_result(out, img1.shape[1:3])
        return result.final()

    if mesh is None:
        return register_step("eval_step", jax.jit(step), key=key)

    repl = partition.replicated(mesh)
    data = partition.data_sharding(mesh)
    variables_in = (variables_sharding if variables_sharding is not None
                    else repl)
    return register_step("eval_step", _with_data_axis(
        mesh.devices.size,
        jax.jit(step, in_shardings=(variables_in, data, data),
                out_shardings=data)), key=key)
