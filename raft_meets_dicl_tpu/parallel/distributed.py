"""Multi-process (multi-host) execution setup.

The reference scales with single-host ``nn.DataParallel``
(src/cmd/train.py:183-184); the TPU-native equivalent at pod scale is
multi-process JAX: one process per host, ``jax.distributed.initialize``
to form the global runtime, a global mesh over all chips, and
per-process input feeding (each host loads only its slice of the batch,
assembled into one global array via
``jax.make_array_from_process_local_data`` — see mesh.shard_batch).

Launch contract (scripts/cluster/train.sh): on TPU pods the coordinator
address/process count/process id are discovered by libtpu, so
``initialize()`` with no arguments is enough; other setups pass them
explicitly or via env (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES,
JAX_PROCESS_ID).
"""

import logging


def initialize(coordinator=None, num_processes=None, process_id=None,
               local_device_ids=None):
    """Join (or form) the multi-process JAX runtime.

    Must run before anything touches a jax backend. No-op when the
    runtime is already initialized.
    """
    import jax

    # explicit already-initialized check — matching initialize()'s error
    # message text is brittle across jax versions and could mask real
    # failures. The state singleton is private API, so its import is
    # guarded: if it moves, we just lose the fast-path skip.
    already = False
    try:
        from jax._src.distributed import global_state

        already = global_state.client is not None
    except ImportError:
        pass

    if already:
        logging.warning("jax.distributed already initialized; skipping")
    else:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            )
        except RuntimeError as e:
            # belt-and-braces for the case the private check above could
            # not run: jax raises 'should only be called once' on re-init
            if "once" in str(e) or "already initialized" in str(e):
                logging.warning(f"jax.distributed already initialized: {e}")
            else:
                raise

    import jax as _jax  # backend comes up on first query

    logging.info(
        f"distributed: process {_jax.process_index()}/{_jax.process_count()}, "
        f"{_jax.local_device_count()} local of {_jax.device_count()} devices"
    )


def process_count():
    import jax

    return jax.process_count()


def process_index():
    import jax

    return jax.process_index()


def is_primary():
    """True on the process that owns logging / checkpoint / report writes."""
    import jax

    return jax.process_index() == 0
