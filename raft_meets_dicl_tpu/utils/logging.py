"""Logging setup and stage/epoch-scoped loggers.

Console + optional file logging, and a ``Logger`` wrapper that prefixes
messages with training progress (stage/epoch/step), mirroring the reference's
behavior (src/utils/logging.py:52-129). Progress display degrades from tqdm
to plain log lines when stdout is not a TTY (cluster runs).
"""

import logging as _logging
import sys

_root = _logging.getLogger("rmdtpu")


def setup(file=None, level=_logging.INFO):
    _root.setLevel(level)
    _root.handlers.clear()

    fmt = _logging.Formatter("[%(asctime)s] [%(levelname)s] %(message)s", datefmt="%Y-%m-%d %H:%M:%S")

    sh = _logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    _root.addHandler(sh)

    if file is not None:
        fh = _logging.FileHandler(file)
        fh.setFormatter(fmt)
        _root.addHandler(fh)

    return Logger()


class Logger:
    """Logger with an optional context prefix (e.g. 'stage 0/3, epoch 1/10')."""

    def __init__(self, pfx=""):
        self.pfx = pfx

    def new(self, pfx, sep=", "):
        return Logger(self.pfx + sep + pfx if self.pfx else pfx)

    def _fmt(self, msg):
        return f"{self.pfx}: {msg}" if self.pfx else msg

    def debug(self, msg):
        _root.debug(self._fmt(msg))

    def info(self, msg):
        _root.info(self._fmt(msg))

    def warn(self, msg):
        _root.warning(self._fmt(msg))

    warning = warn

    def error(self, msg):
        _root.error(self._fmt(msg))


def progress(iterable, total=None, unit="it", leave=False, desc=None):
    """tqdm progress bar on TTYs, plain passthrough otherwise."""
    try:
        from tqdm import tqdm

        if sys.stdout.isatty():
            return tqdm(iterable, total=total, unit=unit, leave=leave, desc=desc)
    except ImportError:
        pass
    return iterable
