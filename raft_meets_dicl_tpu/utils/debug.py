"""Debug helpers: pdb-on-exception wrapper (reference src/utils/debug.py:1-19)."""

import pdb
import sys
import traceback


def run(fn, *args, debug=False, **kwargs):
    """Run ``fn``; on exception optionally drop into pdb post-mortem."""
    if not debug:
        return fn(*args, **kwargs)

    try:
        return fn(*args, **kwargs)
    except Exception:
        traceback.print_exc()
        _, _, tb = sys.exc_info()
        pdb.post_mortem(tb)
        raise
