"""Safe arithmetic expression evaluator for config values.

Configs may contain arithmetic over named variables, e.g. a scheduler's
``total_steps: '{n_epochs} * {n_batches} + 100'`` or checkpoint compare keys
``'{m_EndPointError_mean}'``. Variables are substituted via ``str.format``
and the result is evaluated by walking a restricted Python AST — only
numeric literals and arithmetic operators are allowed (parity with reference
src/utils/expr.py:5-33).
"""

import ast
import operator

_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
}

_UNARYOPS = {
    ast.UAdd: operator.pos,
    ast.USub: operator.neg,
}


def _eval_node(node):
    if isinstance(node, ast.Expression):
        return _eval_node(node.body)
    if isinstance(node, ast.Constant):
        if isinstance(node.value, (int, float)):
            return node.value
        raise ValueError(f"invalid constant in expression: {node.value!r}")
    if isinstance(node, ast.BinOp):
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise ValueError(f"operator not allowed: {type(node.op).__name__}")
        return op(_eval_node(node.left), _eval_node(node.right))
    if isinstance(node, ast.UnaryOp):
        op = _UNARYOPS.get(type(node.op))
        if op is None:
            raise ValueError(f"operator not allowed: {type(node.op).__name__}")
        return op(_eval_node(node.operand))
    if isinstance(node, ast.Call):
        # allow min/max/round/int/float/abs for convenience in configs
        if isinstance(node.func, ast.Name) and node.func.id in ("min", "max", "round", "int", "float", "abs"):
            fn = {"min": min, "max": max, "round": round, "int": int, "float": float, "abs": abs}[node.func.id]
            return fn(*[_eval_node(a) for a in node.args])
        raise ValueError("function calls not allowed in expression")
    raise ValueError(f"invalid expression node: {type(node).__name__}")


def eval_math_expr(expr, args=None, **kwargs):
    """Evaluate an arithmetic expression, substituting ``{name}`` variables.

    Accepts plain numbers (returned as-is) and strings; variables may be
    passed as a dict (reference signature, src/utils/expr.py:5) or kwargs::

        eval_math_expr('{n_epochs} * {n_batches}', {'n_epochs': 2, 'n_batches': 50})
    """
    if isinstance(expr, (int, float)):
        return expr

    vars = dict(args or {}) | kwargs
    expr = str(expr).format_map(vars)
    tree = ast.parse(expr, mode="eval")
    return _eval_node(tree)
