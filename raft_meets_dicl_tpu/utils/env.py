"""Central registry for ``RMD_*`` environment knobs.

Every environment variable the framework reads is declared here — name,
type, default, one-line doc, and the README section it belongs to — and
every read site goes through the typed accessors below instead of
touching ``os.environ`` directly. That buys three things:

1. **One source of truth.** The README's environment-knob table is
   generated from this registry (``readme_table()``); a knob that exists
   in code but not in the table (or the reverse) cannot happen silently —
   ``graftlint``'s ``env-knob``/``env-docs`` rules fail on direct
   ``os.environ`` reads of ``RMD_*`` names outside this module, on names
   read but not registered, and on a README table that drifted from the
   registry.
2. **Uniform semantics.** Default-on switches (``RMD_TELEMETRY=0``
   disables), default-off flags (``RMD_DEBUG_MEM=1`` enables), and typed
   values (int/float/str) each parse exactly one way, instead of every
   call site re-inventing ``!= "0"`` vs ``bool(get(...))``.
3. **Greppability.** ``env.get_bool("RMD_PREFETCH")`` names the knob as
   a literal, so the registry-completeness check (and a human) can find
   every consumer.

This module must stay dependency-free (no jax/numpy): it is imported by
loader worker processes and by the lint framework itself.
"""

import os
from dataclasses import dataclass

# knob kinds:
#   switch — default-on boolean; only the literal "0" disables
#   flag   — default-off boolean; any non-empty value enables
#   str    — raw string (default may be None)
#   int    — integer with default
#   float  — float with default
_KINDS = ("switch", "flag", "str", "int", "float")


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str
    default: object
    doc: str
    section: str

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown knob kind '{self.kind}'")


def _k(name, kind, default, doc, section):
    return (name, Knob(name, kind, default, doc, section))


KNOBS = dict([
    # -- telemetry ---------------------------------------------------------
    _k("RMD_TELEMETRY", "switch", True,
       "kill switch for the telemetry sink and jax.monitoring listeners",
       "telemetry"),
    _k("RMD_DEBUG_MEM", "flag", False,
       "print per-epoch memory snapshots even with telemetry disabled",
       "telemetry"),
    _k("RMD_FINITE_CHECK_EVERY", "int", 10,
       "amortized cadence (steps) of the device finiteness fetch / "
       "pipeline-drain sample", "telemetry"),
    _k("RMD_TELEMETRY_BUFFER", "int", 4096,
       "bounded event-queue capacity of the non-blocking serve sink; "
       "overflow drops events and counts them", "telemetry"),
    _k("RMD_TELEMETRY_MAX_MB", "float", 0.0,
       "rotate events.jsonl to <path>.1 past this size in MiB (0 = "
       "never rotate)", "telemetry"),
    _k("RMD_GOODPUT", "switch", True,
       "account the run's wall clock into goodput classes (productive/"
       "compile/data-starved/checkpoint/eval/resume-replay/preempted); "
       "0 disables the ledger", "telemetry"),
    _k("RMD_BLACKBOX_STEPS", "int", 64,
       "flight-recorder ring size: last N step traces kept in memory "
       "for the crash/SIGTERM postmortem bundle", "telemetry"),
    _k("RMD_TRAIN_METRICS_PORT", "int", 0,
       "trainer observability HTTP port (/metrics, /healthz, /statusz, "
       "/profilez); unset = off, 0 = ephemeral; CLI --metrics-port "
       "wins", "telemetry"),
    _k("RMD_PROFILE_KEEP", "int", 3,
       "retained /profilez capture directories: older rmd-profilez-* "
       "temp dirs are evicted on each capture", "telemetry"),
    _k("RMD_PROFILE_ATTRIBUTION", "switch", True,
       "attach a graftprof device-time attribution summary (and "
       "rmd_prof_* gauges) to /profilez responses and train --profile "
       "captures; 0 returns the artifact path only", "telemetry"),
    # -- input pipeline ----------------------------------------------------
    _k("RMD_WIRE_FORMAT", "str", None,
       "host-to-device wire format preset (f32 | bf16 | u8); CLI "
       "--wire-format wins", "input"),
    _k("RMD_WIRE_BF16", "switch", True,
       "legacy bf16 image put for mixed-precision models when no wire "
       "format is configured", "input"),
    _k("RMD_LOADER_PROCS", "int", 0,
       "decode worker processes (0 = thread pool); CLI --loader-procs "
       "wins", "input"),
    _k("RMD_LOADER_MP", "str", "fork",
       "multiprocessing start method for the decode pool", "input"),
    _k("RMD_LOADER_RETRIES", "int", 2,
       "per-sample decode retries before neighbor substitution", "input"),
    _k("RMD_BAD_SAMPLE_BUDGET", "int", 16,
       "substituted-sample budget per loader before aborting (0 disables "
       "healing)", "input"),
    _k("RMD_LOADER_TIMEOUT", "float", 300.0,
       "total seconds to wait for one sample before declaring the decode "
       "pool wedged", "input"),
    _k("RMD_LOADER_POLL", "float", 5.0,
       "decode-pool queue poll interval (dead-worker detection latency)",
       "input"),
    _k("RMD_LOADER_RESPAWNS", "int", 3,
       "dead decode workers respawned before the pool raises PoolBroken",
       "input"),
    _k("RMD_EVAL_BUCKETS", "str", None,
       "shape-bucket spec for evaluation/validation ('group' or "
       "'HxW,HxW,...')", "input"),
    _k("RMD_DEVICE_AUG", "flag", False,
       "compile the augmentation pipeline into the train step (on-device "
       "data engine); env-config 'augment:' section tunes it", "input"),
    _k("RMD_SYNTH_LAYERS", "int", 4,
       "default moving-layer count for the synthetic scene generator "
       "(data 'type: synth'; per-source 'layers:' wins)", "input"),
    _k("RMD_SYNTH_SEED", "int", 0,
       "default base seed of the synthetic scene generator (per-source "
       "'seed:' wins)", "input"),
    # -- training loop -----------------------------------------------------
    _k("RMD_PREFETCH", "switch", True,
       "double-buffered host-to-device prefetch (0 = synchronous "
       "transfer, bit-identical)", "training"),
    _k("RMD_PREFETCH_DEPTH", "int", 2,
       "how many batches ahead the prefetch worker runs", "training"),
    _k("RMD_PREFETCH_PUT", "switch", True,
       "perform the device_put inside the prefetch worker (0 = put on "
       "the consumer thread)", "training"),
    _k("RMD_NONFINITE", "str", None,
       "non-finite step policy (raise | skip | rollback); CLI "
       "--nonfinite wins", "training"),
    _k("RMD_ASYNC_CHECKPOINT", "switch", True,
       "background checkpoint serialization/write (0 = synchronous "
       "save)", "training"),
    # -- SPMD / parallel ---------------------------------------------------
    _k("RMD_MESH", "str", None,
       "mesh spec 'DATA,MODEL' (or 'data'); CLI --mesh wins", "parallel"),
    _k("RMD_ACCUMULATE", "str", None,
       "in-step gradient accumulation factor; CLI --accumulate wins",
       "parallel"),
    # -- compile / AOT -----------------------------------------------------
    _k("RMD_COMPILE_CACHE", "str", None,
       "persistent XLA compile-cache directory (default "
       "<repo>/.jax_cache)", "compile"),
    _k("RMD_COMPILE_CACHE_DIR", "str", None,
       "legacy alias of RMD_COMPILE_CACHE", "compile"),
    _k("RMD_NO_COMPILE_CACHE", "flag", False,
       "disable the persistent XLA compile cache entirely", "compile"),
    _k("RMD_AOT", "switch", True,
       "AOT serialized-executable program store (0 disables)", "compile"),
    _k("RMD_AOT_DIR", "str", None,
       "relocate the AOT program store (default "
       "<compile-cache>/programs)", "compile"),
    # -- model fast paths --------------------------------------------------
    _k("RMD_DICL_FAST", "switch", True,
       "level-batched MatchingNets + fused Pallas window sampler (0 = "
       "reference loop)", "models"),
    _k("RMD_WCP_BAND", "switch", True,
       "band-sharing windowed-correlation Pallas kernel (0 = per-row "
       "form)", "models"),
    _k("RMD_FS_VOLUME_GIB", "float", 4.0,
       "raft/fs correlation-volume HBM budget steering the "
       "volume/windowed dispatch (per chip)", "models"),
    _k("RMD_ITERATIONS", "int", 0,
       "recurrence iteration override for evaluation (0 = model "
       "default); CLI --iterations wins", "models"),
    # -- serving -----------------------------------------------------------
    _k("RMD_SERVE_BUCKETS", "str", None,
       "canonical request shapes for the serve command ('HxW,HxW,...'); "
       "CLI --buckets / config wins", "serve"),
    _k("RMD_SERVE_BATCH", "int", 4,
       "serve device batch size per dispatch; CLI --batch-size / config "
       "wins", "serve"),
    _k("RMD_SERVE_MAX_WAIT_MS", "float", 50.0,
       "max milliseconds a partial batch waits before dispatching padded "
       "onto the full batch's program", "serve"),
    _k("RMD_SERVE_QUEUE", "int", 64,
       "per-bucket admission queue bound; requests beyond it shed with a "
       "typed queue_full rejection", "serve"),
    _k("RMD_LADDER", "str", "4,8,12",
       "iteration-ladder rung budgets for serve latency classes; CLI "
       "--ladder / config wins", "serve"),
    _k("RMD_LADDER_THRESHOLD", "float", 0.1,
       "flow-delta norm (coarse-grid px) below which the balanced class "
       "stops escalating rungs", "serve"),
    _k("RMD_QUANT", "str", None,
       "quantized matching tier for the fast serve class and video warm "
       "frames ('u8' or 'i8'; unset/off = full precision); CLI --quant "
       "/ config wins", "serve"),
    _k("RMD_QUANT_CLIP", "float", 1.0,
       "fraction of the per-level abs-max mapped onto the quantized "
       "range (values beyond it saturate); <1 trades outlier clipping "
       "for finer steps on the bulk", "serve"),
    _k("RMD_METRICS_PORT", "int", 0,
       "serve observability HTTP port (/metrics, /healthz, /statusz, "
       "/profilez); 0 = off; CLI --metrics-port wins", "serve"),
    _k("RMD_SLO_FAST_MS", "float", 0.0,
       "end-to-end latency SLO target (ms) for the fast ladder class "
       "(0 = untracked)", "serve"),
    _k("RMD_SLO_BALANCED_MS", "float", 0.0,
       "end-to-end latency SLO target (ms) for the balanced ladder "
       "class (0 = untracked)", "serve"),
    _k("RMD_SLO_QUALITY_MS", "float", 0.0,
       "end-to-end latency SLO target (ms) for the quality ladder "
       "class (0 = untracked)", "serve"),
    _k("RMD_SLO_DEFAULT_MS", "float", 0.0,
       "latency SLO target (ms) for ladderless requests and classes "
       "without their own RMD_SLO_* target (0 = untracked)", "serve"),
    _k("RMD_SLO_OBJECTIVE", "float", 0.99,
       "SLO attainment objective; burn_rate = (1-attainment)/"
       "(1-objective), >1 means the window misses it", "serve"),
    _k("RMD_SLO_WINDOW_S", "float", 60.0,
       "rolling SLO burn-rate window (seconds)", "serve"),
    _k("RMD_VIDEO_SESSIONS", "int", 64,
       "bounded per-client video session cache capacity in the serve "
       "scheduler (LRU past it)", "serve"),
    _k("RMD_VIDEO_SESSION_TTL_S", "float", 30.0,
       "idle seconds before a video session's warm-start state is "
       "TTL-evicted", "serve"),
    _k("RMD_VIDEO_WARM_ITERATIONS", "int", 4,
       "warm-start program iteration budget for ladderless video serve "
       "sessions (with --ladder the bottom rung wins)", "serve"),
    # -- serving fleet -----------------------------------------------------
    _k("RMD_FLEET_REPLICAS", "int", 2,
       "replica process count for the serving fleet (serve --fleet); "
       "CLI --fleet wins", "fleet"),
    _k("RMD_FLEET_RETRIES", "int", 2,
       "router retry budget per request on safe failures (connection "
       "refused/reset, replica shed) before the typed fleet shed",
       "fleet"),
    _k("RMD_FLEET_TIMEOUT_MS", "float", 30000.0,
       "per-request router deadline (ms) covering dispatch + retries; "
       "past it the request fails with a typed replica_unavailable",
       "fleet"),
    _k("RMD_FLEET_BURN_DRAIN", "float", 2.0,
       "SLO burn rate above which the router drains a replica (hands "
       "off its sticky sessions, stops routing to it, recycles it)",
       "fleet"),
    _k("RMD_FLEET_BACKOFF_MS", "float", 500.0,
       "supervisor restart backoff base (ms); doubles per consecutive "
       "crash, capped at 30 s, +-25% jitter", "fleet"),
    _k("RMD_FLEET_HEALTH_S", "float", 0.5,
       "router/supervisor health poll interval (seconds): /healthz "
       "liveness + /statusz SLO burn per replica", "fleet"),
    # -- fault injection / harness -----------------------------------------
    _k("RMD_FAULT", "str", "",
       "deterministic fault injection spec (testing.faults)", "faults"),
    _k("RMD_FAULT_STATE", "str", None,
       "directory sharing fired-once fault state across processes",
       "faults"),
    _k("RMD_DRYRUN_BUDGET_S", "float", 420.0,
       "wall-clock budget for the __graft_entry__ multi-chip dry run",
       "faults"),
])

_SECTIONS = ("telemetry", "input", "training", "parallel", "compile",
             "models", "serve", "fleet", "faults")


def knob(name):
    """The :class:`Knob` declaration for ``name`` (KeyError if absent)."""
    return KNOBS[name]


def raw(name):
    """The raw environment string for a registered knob, or None.

    The escape hatch for call sites that need "was it set at all"
    precedence logic (CLI > env var > config); everything else should use
    the typed accessors.
    """
    KNOBS[name]
    return os.environ.get(name)


def is_set(name):
    """Whether the knob is present in the environment at all."""
    KNOBS[name]
    return name in os.environ


def get(name):
    """Typed value of a registered knob, falling back to its default."""
    k = KNOBS[name]
    v = os.environ.get(name)
    if k.kind == "switch":
        return v != "0"
    if k.kind == "flag":
        return bool(v)
    if v is None or (v == "" and k.kind != "str"):
        return k.default
    if k.kind == "int":
        return int(v)
    if k.kind == "float":
        return float(v)
    return v


def get_bool(name):
    """Boolean knob (switch or flag)."""
    k = KNOBS[name]
    if k.kind not in ("switch", "flag"):
        raise TypeError(f"{name} is a {k.kind} knob, not a boolean")
    return get(name)


def get_int(name):
    return int(get(name))


def get_float(name):
    return float(get(name))


def get_str(name):
    v = get(name)
    return v if v is None else str(v)


# -- README table generation -------------------------------------------------

TABLE_BEGIN = "<!-- env-knobs:begin (generated by utils/env.py) -->"
TABLE_END = "<!-- env-knobs:end -->"


def _default_repr(k):
    if k.kind == "switch":
        return "on"
    if k.kind == "flag":
        return "off"
    if k.default is None:
        return "-"
    if k.kind == "str" and k.default == "":
        return "-"
    return str(k.default)


def readme_table():
    """The generated markdown knob table (without the begin/end markers).

    ``scripts/graftlint.py --fix-knob-table`` writes this between the
    markers in README.md; the ``env-docs`` lint rule fails when the
    committed table drifts from the registry.
    """
    lines = ["| Knob | Type | Default | Effect |", "|---|---|---|---|"]
    for section in _SECTIONS:
        knobs = [k for k in KNOBS.values() if k.section == section]
        if not knobs:
            continue
        lines.append(f"| **{section}** | | | |")
        for k in sorted(knobs, key=lambda k: k.name):
            lines.append(
                f"| `{k.name}` | {k.kind} | {_default_repr(k)} | {k.doc} |")
    return "\n".join(lines)


def splice_readme(text):
    """Return ``text`` with the region between the knob-table markers
    replaced by the current :func:`readme_table` output. Raises
    ValueError when the markers are missing or out of order."""
    begin = text.find(TABLE_BEGIN)
    end = text.find(TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        raise ValueError(
            f"README knob-table markers missing ({TABLE_BEGIN!r} ... "
            f"{TABLE_END!r})")
    head = text[:begin + len(TABLE_BEGIN)]
    tail = text[end:]
    return head + "\n" + readme_table() + "\n" + tail
