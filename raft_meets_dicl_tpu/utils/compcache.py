"""Persistent XLA compilation cache.

Large models on the tunneled TPU compile service take ~10 min cold; the
persistent cache (verified working through the remote compile path)
brings repeat compiles down to seconds. Enabled by default for the CLI
and ``bench.py``; opt out with ``RMD_NO_COMPILE_CACHE=1``.

The cache directory resolves ``--compile-cache`` (CLI) >
``RMD_COMPILE_CACHE`` (or the legacy ``RMD_COMPILE_CACHE_DIR``) >
the repo-local ``.jax_cache`` default; the effective directory is
published in the run's ``boot`` telemetry event instead of being a
silent default, and the AOT program store (``compile.aot``) keeps its
``programs/`` directory next to it.

The reference has no equivalent (torch eager needs none); this is the
TPU-native answer to its "start training immediately" property.
"""

import os

from . import env

DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")

# the directory the last enable_persistent_cache() call actually
# configured (None: disabled or never enabled) — for the boot event
_effective = None


def effective_dir():
    """The configured cache directory, or None when the cache is off."""
    return _effective


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point jax at an on-disk compilation cache; returns the dir or None.

    Must run before the first backend use. Failures are non-fatal: the
    cache is an optimization, never a correctness dependency.
    """
    global _effective
    if env.get_bool("RMD_NO_COMPILE_CACHE"):
        _effective = None
        return None

    path = (path
            or env.raw("RMD_COMPILE_CACHE")
            or env.raw("RMD_COMPILE_CACHE_DIR")
            or DEFAULT_DIR)
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: even small entries add up across the zoo
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _effective = path
        return path
    except Exception:  # noqa: BLE001 - never block startup on cache setup
        return None
