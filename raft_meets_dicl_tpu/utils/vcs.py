"""Version-control introspection for reproducibility dumps.

Records the current git HEAD hash (and dirty state) into run configs, like
the reference (src/utils/vcs.py:6). Gracefully degrades outside a repo.
"""

import subprocess
from pathlib import Path


def get_git_head_hash(path=None):
    try:
        cwd = Path(path) if path is not None else Path(__file__).parent
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True, text=True, timeout=10
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return "<unknown>"
