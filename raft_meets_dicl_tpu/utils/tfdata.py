"""TensorBoard event-file reading (reference src/utils/tfdata.py:25).

Loads scalar series from event files written by the framework's
SummaryWriter (or any TB writer) into pandas DataFrames.
"""

import numpy as np


def _tensor_to_np(tensor):
    from tensorboard.compat.proto import types_pb2

    if tensor.dtype == types_pb2.DT_FLOAT:
        values = np.array(tensor.float_val, dtype=np.single)
    elif tensor.dtype == types_pb2.DT_DOUBLE:
        values = np.array(tensor.double_val, dtype=np.double)
    else:
        raise NotImplementedError(f"unsupported tensor dtype {tensor.dtype}")

    if len(tensor.tensor_shape.dim) == 0:
        return values.item()

    raise NotImplementedError("non-scalar tensors are not supported")


def tfdata_scalars_to_pandas(file, tags=None):
    """Scalar events of one TB event file → DataFrame(tag, step, time, value).

    Handles both representations: migrated tensors with scalar data-class
    metadata (what current writers emit) and legacy ``simple_value``.
    """
    # local imports: pandas/tensorboard are offline-analysis deps, not
    # runtime deps of the package
    import pandas as pd
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )
    from tensorboard.compat.proto import summary_pb2

    records = []
    for event in EventFileLoader(str(file)).Load():
        if not event.HasField("summary"):
            continue

        for value in event.summary.value:
            if tags is not None and value.tag not in tags:
                continue

            if value.HasField("simple_value"):
                scalar = value.simple_value
            elif (value.metadata.data_class
                  == summary_pb2.DataClass.DATA_CLASS_SCALAR):
                scalar = _tensor_to_np(value.tensor)
            else:
                continue

            records.append({
                "tag": value.tag,
                "step": event.step,
                "time": event.wall_time,
                "value": scalar,
            })

    return pd.DataFrame.from_records(records)
