"""RNG seed management with JAX key discipline.

The reference seeds python/numpy/torch/cuda RNGs (src/utils/seeds.py:11-41).
On TPU the device-side RNG is functional: ``apply()`` seeds the host RNGs
(python ``random``, ``numpy``) and returns a root ``jax.random`` PRNG key
from the ``jax`` seed. The key is threaded explicitly through model init and
augmentation-free device code; host-side augmentation uses numpy.
"""

import random
import secrets

import numpy as np


class Seeds:
    @classmethod
    def new_random(cls):
        return cls(
            python=secrets.randbits(32),
            numpy=secrets.randbits(32),
            jax=secrets.randbits(32),
        )

    @classmethod
    def from_config(cls, cfg):
        cfg = cfg or {}
        return cls(
            python=cfg.get("python", 0),
            numpy=cfg.get("numpy", 0),
            jax=cfg.get("jax", cfg.get("torch", 0)),  # accept legacy 'torch' key
        )

    def __init__(self, python, numpy, jax):
        self.python = int(python)
        self.numpy = int(numpy)
        self.jax = int(jax)

    def get_config(self):
        return {"python": self.python, "numpy": self.numpy, "jax": self.jax}

    def apply(self):
        """Seed host RNGs and return the root JAX PRNG key."""
        import jax as _jax

        random.seed(self.python)
        np.random.seed(self.numpy % (2**32))
        return _jax.random.PRNGKey(self.jax)


def random_seeds():
    return Seeds.new_random()


def from_config(cfg):
    return Seeds.from_config(cfg)
