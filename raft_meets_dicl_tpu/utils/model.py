"""Model utilities: parameter counting over pytrees (reference src/utils/model.py:5)."""

import jax
import numpy as np


def count_parameters(params):
    """Total number of scalar parameters in a pytree of arrays."""
    leaves = jax.tree_util.tree_leaves(params)
    return int(sum(np.prod(leaf.shape) for leaf in leaves if hasattr(leaf, "shape")))
