"""Config load/store utilities.

YAML or JSON is selected by file extension; loading preserves key order and
storing YAML keeps insertion order (parity with reference
src/utils/config.py:17-60). Every layer of the framework round-trips through
``from_config`` / ``get_config`` — this module is the single place files are
touched.
"""

import json
from pathlib import Path

import yaml


class _OrderedDumper(yaml.SafeDumper):
    pass


def _dict_representer(dumper, data):
    return dumper.represent_mapping(yaml.resolver.BaseResolver.DEFAULT_MAPPING_TAG, data.items())


_OrderedDumper.add_representer(dict, _dict_representer)


def load(path):
    """Load a YAML/JSON config file (by extension) into plain dicts/lists."""
    path = Path(path)

    with open(path, "r") as fd:
        if path.suffix in (".yaml", ".yml"):
            return yaml.safe_load(fd)
        elif path.suffix == ".json":
            return json.load(fd)
        else:
            # default to YAML, it is a JSON superset
            return yaml.safe_load(fd)


def store(path, cfg):
    """Store a config to a YAML/JSON file (by extension), preserving order."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)

    with open(path, "w") as fd:
        if path.suffix == ".json":
            json.dump(cfg, fd, indent=2)
        else:
            yaml.dump(cfg, fd, Dumper=_OrderedDumper, default_flow_style=False, sort_keys=False)


def to_string(cfg, fmt="json"):
    if fmt == "json":
        return json.dumps(cfg, indent=2)
    return yaml.dump(cfg, Dumper=_OrderedDumper, default_flow_style=False, sort_keys=False)


def resolve_path(base_file, rel):
    """Resolve ``rel`` relative to the directory of the referencing config file.

    The config corpus is a graph of files referencing each other by relative
    path (reference src/data/config.py:45-57, src/strategy/config.py:8-40);
    paths always resolve relative to the *referencing* file.
    """
    rel = Path(rel)
    if rel.is_absolute():
        return rel
    return (Path(base_file).parent / rel).resolve()
