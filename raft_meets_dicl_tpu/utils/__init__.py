from . import config, debug, expr, logging, model, seeds, tfdata, vcs

__all__ = ["config", "debug", "expr", "logging", "model", "seeds", "tfdata",
           "vcs"]
