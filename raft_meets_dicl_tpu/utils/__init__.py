from . import config, debug, expr, logging, model, seeds, vcs

__all__ = ["config", "debug", "expr", "logging", "model", "seeds", "vcs"]
