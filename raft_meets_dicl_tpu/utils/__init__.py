"""Utility modules, loaded lazily (PEP 562).

Lazy so that light-weight consumers — decode worker processes, the lint
framework, ``testing.faults`` — can import ``utils.env`` (dependency-free
by contract) without dragging in ``utils.model``'s jax import.
"""

import importlib

_SUBMODULES = ("config", "debug", "env", "expr", "logging", "model", "seeds",
               "tfdata", "vcs")

__all__ = list(_SUBMODULES)


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module '{__name__}' has no attribute '{name}'")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))
