from . import config
from . import expr
from . import logging
from . import seeds
