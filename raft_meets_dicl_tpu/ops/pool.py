"""Window pooling for NHWC tensors (reference uses ``F.avg_pool2d`` /
``F.max_pool2d`` for correlation pyramids and pooled encoders,
src/models/impls/raft.py:42, src/models/common/encoders/pool/*)."""

import jax.numpy as jnp
from jax import lax


def avg_pool2d(x, window=2, stride=None):
    """Average pool over the H, W axes of an (..., H, W, C) tensor."""
    stride = stride or window
    n = x.ndim
    dims = [1] * n
    strides = [1] * n
    dims[-3] = dims[-2] = window
    strides[-3] = strides[-2] = stride
    summed = lax.reduce_window(x, 0.0, lax.add, tuple(dims), tuple(strides), "VALID")
    return summed / (window * window)


def max_pool2d(x, window=2, stride=None):
    stride = stride or window
    n = x.ndim
    dims = [1] * n
    strides = [1] * n
    dims[-3] = dims[-2] = window
    strides[-3] = strides[-2] = stride
    return lax.reduce_window(x, -jnp.inf, lax.max, tuple(dims), tuple(strides), "VALID")
