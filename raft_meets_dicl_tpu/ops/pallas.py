"""Pallas TPU kernels for profiled hot paths.

The XLA-composite ops in this package are the default implementation;
kernels here replace the ones where profiling on real hardware showed the
compiler-scheduled form paying large materialization/layout costs.

``convex_combine_8x`` — the RAFT convex-upsampling mask combine
(reference Up8Network core, src/models/impls/raft.py:313-331). The
XLA form (softmax + einsum over a (N, h, w, 64, 9) mask) materializes
~750 MB of f32 intermediates with layout copies per training step at the
bench config (batch 6, 400x720, 12 iterations — the mask is built for
all iterations at once); profiled at ~70 ms/step of the 425 ms total.
The kernel fuses softmax and combine per row tile: only the 576-channel
logits are read and the 128-channel result written, nothing else touches
HBM. Forward and backward are both Pallas; the VJP recomputes the
softmax from the saved logits instead of storing probabilities.

Layout contract (matches torch RAFT's ``view(b, 1, 9, 8, 8, h, w)``):
logits channels are neighbor-major ``k * 64 + s`` (k = 3x3 neighbor
row-major, s = subpixel ``r * 8 + c``); outputs are ``chan * 64 + s``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

_TILE = 512
_K = 9       # 3x3 neighbors
_S = 64      # 8x8 subpixels
_C = 2       # flow channels


def _softmax_slices(logits, inv_temp):
    """Grouped softmax over the 9 neighbor blocks of (T, 576) logits,
    returned as unnormalized exps + reciprocal of the partition sum —
    column-slice arithmetic only (no reshapes: Mosaic-friendly)."""
    xs = [logits[:, _S * k: _S * (k + 1)] * inv_temp for k in range(_K)]
    m = xs[0]
    for k in range(1, _K):
        m = jnp.maximum(m, xs[k])
    es = [jnp.exp(x - m) for x in xs]
    denom = es[0]
    for k in range(1, _K):
        denom = denom + es[k]
    return es, 1.0 / denom


def _fwd_kernel(logits_ref, win_ref, out_ref, *, inv_temp):
    x = logits_ref[:].astype(jnp.float32)   # (T, 576)
    w = win_ref[:].astype(jnp.float32)      # (T, 18), layout k*2 + c

    es, inv = _softmax_slices(x, inv_temp)

    acc0 = es[0] * w[:, 0:1]
    acc1 = es[0] * w[:, 1:2]
    for k in range(1, _K):
        acc0 = acc0 + es[k] * w[:, 2 * k: 2 * k + 1]
        acc1 = acc1 + es[k] * w[:, 2 * k + 1: 2 * k + 2]

    out_ref[:, 0:_S] = acc0 * inv
    out_ref[:, _S: 2 * _S] = acc1 * inv


def _bwd_kernel(logits_ref, win_ref, dout_ref, dlogits_ref, dwin_ref, *,
                inv_temp):
    x = logits_ref[:].astype(jnp.float32)
    w = win_ref[:].astype(jnp.float32)
    d0 = dout_ref[:, 0:_S]
    d1 = dout_ref[:, _S: 2 * _S]

    es, inv = _softmax_slices(x, inv_temp)

    ps, dps, dwin_cols = [], [], []
    s_acc = None
    for k in range(_K):
        p_k = es[k] * inv
        dp_k = d0 * w[:, 2 * k: 2 * k + 1] + d1 * w[:, 2 * k + 1: 2 * k + 2]
        dwin_cols.append(jnp.sum(p_k * d0, axis=1, keepdims=True))
        dwin_cols.append(jnp.sum(p_k * d1, axis=1, keepdims=True))
        term = p_k * dp_k
        s_acc = term if s_acc is None else s_acc + term  # Σ_k p_k·dp_k
        ps.append(p_k)
        dps.append(dp_k)

    dl = [ps[k] * (dps[k] - s_acc) * inv_temp for k in range(_K)]
    dlogits_ref[:] = jnp.concatenate(dl, axis=1).astype(dlogits_ref.dtype)
    dwin_ref[:] = jnp.concatenate(dwin_cols, axis=1)


def _pad_rows(x, tile):
    m = x.shape[0]
    pad = (-m) % tile
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x, m


def _run_fwd(logits2d, win2d, inv_temp, interpret=False):
    logits2d, m = _pad_rows(logits2d, _TILE)
    win2d, _ = _pad_rows(win2d, _TILE)
    grid = (logits2d.shape[0] // _TILE,)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, inv_temp=inv_temp),
        out_shape=jax.ShapeDtypeStruct((logits2d.shape[0], _C * _S),
                                       jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE, _K * _S), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE, _K * _C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE, _C * _S), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024),
        interpret=interpret,
    )(logits2d, win2d)
    return out[:m]


def _run_bwd(logits2d, win2d, dout2d, inv_temp, interpret=False):
    logits2d, m = _pad_rows(logits2d, _TILE)
    win2d, _ = _pad_rows(win2d, _TILE)
    dout2d, _ = _pad_rows(dout2d, _TILE)
    grid = (logits2d.shape[0] // _TILE,)

    dlogits, dwin = pl.pallas_call(
        functools.partial(_bwd_kernel, inv_temp=inv_temp),
        out_shape=(
            jax.ShapeDtypeStruct(logits2d.shape, logits2d.dtype),
            jax.ShapeDtypeStruct(win2d.shape, jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE, _K * _S), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE, _K * _C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE, _C * _S), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((_TILE, _K * _S), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE, _K * _C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        # f32 callers (the ctf family runs un-mixed) land just past the
        # 16M default with double buffering
        compiler_params=_CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024),
        interpret=interpret,
    )(logits2d, win2d, dout2d)
    return dlogits[:m], dwin[:m]


def _run_fwd_interpret(logits2d, win2d, inv_temp):
    """Interpreter-mode forward (kernel correctness tests off-TPU)."""
    return _run_fwd(logits2d, win2d, inv_temp, interpret=True)


def _run_bwd_interpret(logits2d, win2d, dout2d, inv_temp):
    """Interpreter-mode backward (kernel correctness tests off-TPU)."""
    return _run_bwd(logits2d, win2d, dout2d, inv_temp, interpret=True)


def _combine_reference(logits2d, win2d, inv_temp):
    """XLA fallback with identical semantics (used off-TPU and as the
    numerical reference in tests)."""
    x = logits2d.astype(jnp.float32).reshape(-1, _K, _S) * inv_temp
    p = jax.nn.softmax(x, axis=1)                      # (M, 9, 64)
    w = win2d.astype(jnp.float32).reshape(-1, _K, _C)  # (M, 9, 2)
    out = jnp.einsum("mks,mkc->mcs", p, w)
    return out.reshape(-1, _C * _S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _combine(logits2d, win2d, inv_temp):
    if jax.default_backend() == "tpu":
        return _run_fwd(logits2d, win2d, inv_temp)
    return _combine_reference(logits2d, win2d, inv_temp)


def _combine_fwd(logits2d, win2d, inv_temp):
    return _combine(logits2d, win2d, inv_temp), (logits2d, win2d)


def _combine_bwd(inv_temp, res, dout):
    logits2d, win2d = res
    if jax.default_backend() == "tpu":
        dlogits, dwin = _run_bwd(logits2d, win2d, dout, inv_temp)
        return dlogits, dwin

    def f(lg, wn):
        return _combine_reference(lg, wn, inv_temp)

    _, vjp = jax.vjp(f, logits2d, win2d)
    dlogits, dwin = vjp(dout.astype(jnp.float32))
    return dlogits.astype(logits2d.dtype), dwin.astype(jnp.float32)


_combine.defvjp(_combine_fwd, _combine_bwd)


# ---------------------------------------------------------------------------
# Fused windowed correlation over a feature pyramid.
#
# Mathematical identity with the RAFT all-pairs volume path: pooling and
# bilinear interpolation are both linear in f2, so
#     lookup(pyramid(all_pairs(f1, f2)), coords)
#   = windowed_correlation(f1, avg_pool^l(f2), coords / 2^l)   per level.
# The kernel computes the right-hand side directly — the O(H²W²) volume is
# never materialized, its pyramid never built, and the backward pass
# accumulates into the (tiny) pooled feature maps instead of carrying
# volume-sized gradients through the iteration scan. This is also the
# long-spatial-context kernel (SURVEY §5.7): memory is O(B·H·W·C)
# regardless of resolution, which is what makes 1080p training fit.
#
# Channel order of the output: (level, dx, dy) — identical to
# ops.corr.lookup_pyramid and the reference CorrBlock (raft.py:57-92).

# The slab's x-start is rounded down to a multiple of 8 (Mosaic requires
# statically-provable sublane alignment for dynamic slices); the kernel
# reads a widened 8-aligned slab and folds the residual shift s = x0 - x8
# into a small per-position selection matrix built from iotas. _XW is the
# widened slab width: ceil((k+1) + 7, 8) for r=4 → 24.
_XW = 24


# Band-sharing chunk parameters: _PB consecutive positions share one
# (k+9, _XBW, C) slab read + one MXU contraction when their windows
# overlap enough (the flow-smooth case); otherwise the chunk falls back
# to the per-position path. _XBW covers the (k+1)-lane window + ≤7-lane
# alignment residual + ≤8 lanes of x-spread for radius ≤ 7.
_XBW = 32
_PB = 8


def _wcp_pads(radius):
    """(lo, hi_y, hi_x) zero-padding of the f2 maps so every clamped,
    8-aligned window is a plain in-bounds slice: x-starts lie in
    [0, lo + dim] after clamping centers to [-(r+1), dim+r], and the
    widened slab extends _XW (per-position) / _XBW with k+9 rows
    (band-shared) past the start."""
    lo = 2 * radius + 1
    return lo, 2 * radius + 10, _XBW


def _wcp_window(cx, cy, lvl, dim_h, dim_w, radius):
    """Clamped window start indices (into the padded map), the 8-aligned
    x-start + residual shift, and the bilinear fractions."""
    scale = float(2 ** lvl)
    r = radius
    cx = cx / scale
    cy = cy / scale
    # centers whose whole window is out of bounds clamp to positions whose
    # sampled values are all zero (padding) — grid_sample zero semantics
    cx = jnp.clip(cx, -(r + 1.0), dim_w - 1.0 + r + 1.0)
    cy = jnp.clip(cy, -(r + 1.0), dim_h - 1.0 + r + 1.0)
    x0f = jnp.floor(cx)
    y0f = jnp.floor(cy)
    lo = 2 * r + 1
    x0 = x0f.astype(jnp.int32) - r + lo
    y0 = y0f.astype(jnp.int32) - r + lo
    x8 = pl.multiple_of((x0 // 8) * 8, 8)
    return x8, x0 - x8, y0, cx - x0f, cy - y0f


def _x_select(s, fx, k):
    """(_XW, k) selection-and-lerp matrix: column dx picks lanes s+dx and
    s+dx+1 with the bilinear weights — the dynamic lane shift expressed as
    arithmetic instead of an (unsupported) dynamic lane slice."""
    ix = jax.lax.broadcasted_iota(jnp.int32, (_XW, k), 0)
    dxi = jax.lax.broadcasted_iota(jnp.int32, (_XW, k), 1)
    return (jnp.where(ix == dxi + s, 1.0 - fx, 0.0)
            + jnp.where(ix == dxi + s + 1, fx, 0.0))


def _wcp_fwd_kernel(coords_ref, f1_ref, *f2_refs_and_out, radius, dims):
    f2_refs = f2_refs_and_out[:-1]
    out_ref = f2_refs_and_out[-1]
    k = 2 * radius + 1
    kk = k * k
    n_j = f1_ref.shape[2]

    def body(j, _):
        f1j = f1_ref[0, 0, j].astype(jnp.float32)      # (1, C)
        cx = coords_ref[0, 0, j, 0]
        cy = coords_ref[0, 0, j, 1]
        for lvl, f2_ref in enumerate(f2_refs):
            h2, w2 = dims[lvl]
            x8, s, y0, fx, fy = _wcp_window(cx, cy, lvl, h2, w2, radius)

            slab = f2_ref[0, pl.ds(y0, k + 1), pl.ds(x8, _XW), :]
            d = jnp.sum(slab.astype(jnp.float32) * f1j[None, :, :],
                        axis=-1)                       # (k+1, _XW): (y, x)
            t = (1.0 - fy) * d[0:k, :] + fy * d[1:k + 1, :]   # (k, _XW)
            m = _x_select(s, fx, k)                           # (_XW, k)
            v = jnp.sum(t[:, :, None] * m[None, :, :], axis=1)  # (dy, dx)
            vt = v.T                                            # (dx, dy)
            out_ref[0, 0, j, lvl * k:(lvl + 1) * k, :] = vt
        return 0

    jax.lax.fori_loop(0, n_j, body, 0)


def _wcp_fwd_band_kernel(coords_ref, f1_ref, *f2_refs_and_out, radius,
                        dims):
    """Band-shared forward: chunks of _PB consecutive positions.

    Shared path per chunk·level — the bandwidth fix for the per-position
    kernel (PERF.md round 4: slab reads were 8x redundant for smooth
    flow):
      1. ONE (k+9, _XBW, C) slab read;
      2. ONE MXU contraction against the chunk's stacked f1 rows
         ((k+9)·_XBW, C) x (C, _PB);
      3. bilinear windows resolved with arithmetic selection masks —
         y as a pair-lerp plus pure row-selection (static dy loop), x as
         the lerped lane-selection (static dx loop) — no dynamic lane
         slicing, the constraint that killed the round-4 j-vectorization
         attempts.
    The per-position fallback (identical math to _wcp_fwd_kernel) runs
    whenever the chunk's window spread exceeds the shared slab.
    """
    f2_refs = f2_refs_and_out[:-1]
    out_ref = f2_refs_and_out[-1]
    k = 2 * radius + 1
    yb = k + 9
    n_c = f1_ref.shape[2]

    def chunk(ci, _):
        f1c = f1_ref[0, 0, ci].astype(jnp.float32)          # (_PB, C)

        for lvl, f2_ref in enumerate(f2_refs):
            h2, w2 = dims[lvl]
            xs, ys, fxs, fys, xb8, ymin, fits = _wcp_band_params(
                coords_ref, ci, lvl, h2, w2, radius)

            def shared(lvl=lvl, f2_ref=f2_ref, xs=xs, ys=ys, fxs=fxs,
                       fys=fys, xb8=xb8, ymin=ymin):
                slab = f2_ref[0, pl.ds(ymin, yb), pl.ds(xb8, _XBW), :]
                s2 = slab.astype(jnp.float32).reshape(yb * _XBW, -1)
                d = jax.lax.dot_general(
                    s2, f1c, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)     # (yb*_XBW, _PB)
                d3 = d.reshape(yb, _XBW, _PB)

                fyv = jnp.stack(fys).reshape(1, 1, _PB)
                t = (1.0 - fyv) * d3[0:yb - 1] + fyv * d3[1:yb]

                syv = jnp.stack([y - ymin for y in ys]).reshape(1, 1, _PB)
                iy = jax.lax.broadcasted_iota(jnp.int32, (yb - 1, 1, _PB), 0)
                e = jnp.stack([
                    jnp.sum(jnp.where(iy == syv + dy, t, 0.0), axis=0)
                    for dy in range(k)
                ])                                          # (k_dy, _XBW, _PB)

                sxv = jnp.stack([x - xb8 for x in xs]).reshape(1, 1, _PB)
                fxv = jnp.stack(fxs).reshape(1, 1, _PB)
                ix = jax.lax.broadcasted_iota(jnp.int32, (1, _XBW, _PB), 1)
                return jnp.stack([
                    jnp.sum(((ix == sxv + dx) * (1.0 - fxv)
                             + (ix == sxv + dx + 1) * fxv) * e, axis=1)
                    for dx in range(k)
                ])                                          # (k_dx, k_dy, _PB)

            def fallback(lvl=lvl, f2_ref=f2_ref, xs=xs, ys=ys, fxs=fxs,
                         fys=fys):
                vs = []
                for p in range(_PB):
                    x8p = pl.multiple_of((xs[p] // 8) * 8, 8)
                    sp = xs[p] - x8p
                    slab = f2_ref[0, pl.ds(ys[p], k + 1),
                                  pl.ds(x8p, _XW), :]
                    dd = jnp.sum(
                        slab.astype(jnp.float32)
                        * f1c[p:p + 1, :][None, :, :], axis=-1)
                    t = (1.0 - fys[p]) * dd[0:k, :] + fys[p] * dd[1:k + 1, :]
                    m = _x_select(sp, fxs[p], k)
                    v = jnp.sum(t[:, :, None] * m[None, :, :], axis=1)
                    vs.append(v.T)                          # (k_dx, k_dy)
                return jnp.stack(vs, axis=-1)               # (k, k, _PB)

            v = jax.lax.cond(fits, shared, fallback)
            for p in range(_PB):
                out_ref[0, 0, ci * _PB + p,
                        lvl * k:(lvl + 1) * k, :] = v[:, :, p]
        return 0

    jax.lax.fori_loop(0, n_c, chunk, 0)


def _unlerp(dout_ref, j, lvl, s, fx, fy, radius):
    """Transpose of the window lerps: spread the (dy, dx) cost gradient of
    position j at level lvl onto the widened (k+1, _XW) slab."""
    k = 2 * radius + 1
    dv = dout_ref[0, 0, j, lvl * k:(lvl + 1) * k, :].T  # (dy, dx)
    m = _x_select(s, fx, k)                             # (_XW, k)
    dt = jnp.sum(dv[:, None, :] * m[None, :, :], axis=2)  # (k, _XW)
    zr = jnp.zeros((1, _XW), jnp.float32)
    return ((1.0 - fy) * jnp.concatenate([dt, zr], axis=0)
            + fy * jnp.concatenate([zr, dt], axis=0))     # (k+1, _XW)


def _wcp_band_params(coords_ref, ci, lvl, h2, w2, radius):
    """Per-chunk window parameters + the shared-slab fit predicate."""
    k = 2 * radius + 1
    xs, ys, fxs, fys = [], [], [], []
    for p in range(_PB):
        cx = coords_ref[0, 0, ci * _PB + p, 0]
        cy = coords_ref[0, 0, ci * _PB + p, 1]
        x8, s, y0, fx, fy = _wcp_window(cx, cy, lvl, h2, w2, radius)
        xs.append(x8 + s)
        ys.append(y0)
        fxs.append(fx)
        fys.append(fy)
    xmin = functools.reduce(jnp.minimum, xs)
    xmax = functools.reduce(jnp.maximum, xs)
    ymin = functools.reduce(jnp.minimum, ys)
    ymax = functools.reduce(jnp.maximum, ys)
    xb8 = pl.multiple_of((xmin // 8) * 8, 8)
    fits = jnp.logical_and(xmax - xb8 <= _XBW - 1 - (k + 1),
                           ymax - ymin <= 8)
    return xs, ys, fxs, fys, xb8, ymin, fits


def _wcp_band_dv(dout_ref, ci, lvl, radius):
    """The chunk's (k_dx, k_dy, _PB) output-gradient stack."""
    k = 2 * radius + 1
    return jnp.stack([
        dout_ref[0, 0, ci * _PB + p, lvl * k:(lvl + 1) * k, :]
        for p in range(_PB)
    ], axis=-1)


def _wcp_band_dD3(dv, xs, ys, fxs, fys, xb8, ymin, radius):
    """Transpose of the band forward's selection/lerp chain: spread the
    (k, k, _PB) cost gradients onto the shared (k+9, _XBW) slab grid."""
    k = 2 * radius + 1
    yb = k + 9

    sxv = jnp.stack([x - xb8 for x in xs]).reshape(1, 1, _PB)
    fxv = jnp.stack(fxs).reshape(1, 1, _PB)
    ix = jax.lax.broadcasted_iota(jnp.int32, (1, _XBW, _PB), 1)
    de = sum(
        ((ix == sxv + dx) * (1.0 - fxv) + (ix == sxv + dx + 1) * fxv)
        * dv[dx][:, None, :]
        for dx in range(k)
    )                                               # (k_dy, _XBW, _PB)

    syv = jnp.stack([y - ymin for y in ys]).reshape(1, 1, _PB)
    iy = jax.lax.broadcasted_iota(jnp.int32, (yb - 1, 1, _PB), 0)
    dt = sum(
        jnp.where(iy == syv + dy, de[dy][None, :, :], 0.0)
        for dy in range(k)
    )                                               # (yb-1, _XBW, _PB)

    fyv = jnp.stack(fys).reshape(1, 1, _PB)
    zr = jnp.zeros((1, _XBW, _PB), jnp.float32)
    return ((1.0 - fyv) * jnp.concatenate([dt, zr], axis=0)
            + fyv * jnp.concatenate([zr, dt], axis=0))  # (yb, _XBW, _PB)


def _wcp_bwd_df1_band_kernel(coords_ref, dout_ref, *f2_refs_and_out,
                             radius, dims):
    """Band-shared df1: per chunk·level ONE slab read and ONE MXU
    contraction dD3^T(yb*_XBW, _PB) x slab(yb*_XBW, C) -> (_PB, C)."""
    f2_refs = f2_refs_and_out[:-1]
    df1_ref = f2_refs_and_out[-1]
    k = 2 * radius + 1
    yb = k + 9
    n_c = df1_ref.shape[2]

    def chunk(ci, _):
        acc = jnp.zeros((_PB, f2_refs[0].shape[-1]), jnp.float32)
        for lvl, f2_ref in enumerate(f2_refs):
            h2, w2 = dims[lvl]
            xs, ys, fxs, fys, xb8, ymin, fits = _wcp_band_params(
                coords_ref, ci, lvl, h2, w2, radius)
            dv = _wcp_band_dv(dout_ref, ci, lvl, radius)

            def shared(f2_ref=f2_ref, xs=xs, ys=ys, fxs=fxs, fys=fys,
                       xb8=xb8, ymin=ymin, dv=dv):
                dd3 = _wcp_band_dD3(dv, xs, ys, fxs, fys, xb8, ymin,
                                    radius)
                slab = f2_ref[0, pl.ds(ymin, yb), pl.ds(xb8, _XBW), :]
                s2 = slab.astype(jnp.float32).reshape(yb * _XBW, -1)
                return jax.lax.dot_general(
                    dd3.reshape(yb * _XBW, _PB), s2,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)     # (_PB, C)

            def fallback(f2_ref=f2_ref, xs=xs, ys=ys, fxs=fxs, fys=fys,
                         dv=dv, lvl=lvl):
                outs = []
                for p in range(_PB):
                    x8p = pl.multiple_of((xs[p] // 8) * 8, 8)
                    sp = xs[p] - x8p
                    m = _x_select(sp, fxs[p], k)
                    dvp = dv[:, :, p].T                     # (k_dy, k_dx)
                    dt = jnp.sum(dvp[:, None, :] * m[None, :, :], axis=2)
                    zr = jnp.zeros((1, _XW), jnp.float32)
                    dd = ((1.0 - fys[p])
                          * jnp.concatenate([dt, zr], axis=0)
                          + fys[p] * jnp.concatenate([zr, dt], axis=0))
                    slab = f2_ref[0, pl.ds(ys[p], k + 1),
                                  pl.ds(x8p, _XW), :]
                    part = jnp.sum(dd[:, :, None]
                                   * slab.astype(jnp.float32), axis=(0, 1))
                    outs.append(part)
                return jnp.stack(outs)                      # (_PB, C)

            acc = acc + jax.lax.cond(fits, shared, fallback)
        df1_ref[0, 0, ci] = acc
        return 0

    jax.lax.fori_loop(0, n_c, chunk, 0)


def _wcp_bwd_df2_band_kernel(coords_ref, f1_ref, dout_ref, df2_ref, *,
                             radius, lvl, dims):
    """Band-shared df2 for ONE level: per chunk ONE MXU outer product
    dD3(yb*_XBW, _PB) x f1c(_PB, C) accumulated into the shared slab."""
    k = 2 * radius + 1
    yb = k + 9
    n_c = f1_ref.shape[2]
    h2, w2 = dims
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        df2_ref[:] = jnp.zeros_like(df2_ref)

    def chunk(ci, _):
        f1c = f1_ref[0, 0, ci].astype(jnp.float32)          # (_PB, C)
        xs, ys, fxs, fys, xb8, ymin, fits = _wcp_band_params(
            coords_ref, ci, lvl, h2, w2, radius)
        dv = _wcp_band_dv(dout_ref, ci, 0, radius)

        def shared():
            dd3 = _wcp_band_dD3(dv, xs, ys, fxs, fys, xb8, ymin, radius)
            ds2 = jax.lax.dot_general(
                dd3.reshape(yb * _XBW, _PB), f1c,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # (yb*_XBW, C)
            df2_ref[0, pl.ds(ymin, yb), pl.ds(xb8, _XBW), :] += (
                ds2.reshape(yb, _XBW, -1))

        def fallback():
            for p in range(_PB):
                x8p = pl.multiple_of((xs[p] // 8) * 8, 8)
                sp = xs[p] - x8p
                m = _x_select(sp, fxs[p], k)
                dvp = dv[:, :, p].T                         # (k_dy, k_dx)
                dt = jnp.sum(dvp[:, None, :] * m[None, :, :], axis=2)
                zr = jnp.zeros((1, _XW), jnp.float32)
                dd = ((1.0 - fys[p]) * jnp.concatenate([dt, zr], axis=0)
                      + fys[p] * jnp.concatenate([zr, dt], axis=0))
                df2_ref[0, pl.ds(ys[p], k + 1), pl.ds(x8p, _XW), :] += (
                    dd[:, :, None] * f1c[p:p + 1, :][None, :, :])

        jax.lax.cond(fits, shared, fallback)
        return 0

    jax.lax.fori_loop(0, n_c, chunk, 0)


def _wcp_bwd_df1_kernel(coords_ref, dout_ref, *f2_refs_and_out, radius,
                        dims):
    """df1 over all levels (reads the f2 maps, touches no df2 state —
    split from the df2 kernel so each stays under the VMEM budget)."""
    f2_refs = f2_refs_and_out[:-1]
    df1_ref = f2_refs_and_out[-1]
    k = 2 * radius + 1
    n_j = df1_ref.shape[2]

    def body(j, _):
        cx = coords_ref[0, 0, j, 0]
        cy = coords_ref[0, 0, j, 1]
        acc = None
        for lvl, f2_ref in enumerate(f2_refs):
            h2, w2 = dims[lvl]
            x8, s, y0, fx, fy = _wcp_window(cx, cy, lvl, h2, w2, radius)
            dd = _unlerp(dout_ref, j, lvl, s, fx, fy, radius)

            slab = f2_ref[0, pl.ds(y0, k + 1), pl.ds(x8, _XW), :]
            part = jnp.sum(dd[:, :, None] * slab.astype(jnp.float32), axis=0)
            part = jnp.sum(part, axis=0, keepdims=True)   # (1, C)
            acc = part if acc is None else acc + part
        df1_ref[0, 0, j] = acc
        return 0

    jax.lax.fori_loop(0, n_j, body, 0)


def _wcp_bwd_df2_kernel(coords_ref, f1_ref, dout_ref, df2_ref, *, radius,
                        lvl, dims):
    """df2 for ONE pyramid level, accumulated across the i-grid (the
    output block is indexed by b only and stays resident in VMEM).
    ``dout_ref`` carries only this level's (k, k) channel block."""
    k = 2 * radius + 1
    n_j = f1_ref.shape[2]
    h2, w2 = dims
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        df2_ref[:] = jnp.zeros_like(df2_ref)

    def body(j, _):
        f1j = f1_ref[0, 0, j].astype(jnp.float32)      # (1, C)
        cx = coords_ref[0, 0, j, 0]
        cy = coords_ref[0, 0, j, 1]
        x8, s, y0, fx, fy = _wcp_window(cx, cy, lvl, h2, w2, radius)
        dd = _unlerp(dout_ref, j, 0, s, fx, fy, radius)

        df2_ref[0, pl.ds(y0, k + 1), pl.ds(x8, _XW), :] += (
            dd[:, :, None] * f1j[None, :, :])
        return 0

    jax.lax.fori_loop(0, n_j, body, 0)


def _wcp_pad_f2(f2_levels, radius):
    lo, hi_y, hi_x = _wcp_pads(radius)
    return tuple(
        jnp.pad(f2, ((0, 0), (lo, hi_y), (lo, hi_x), (0, 0)))
        for f2 in f2_levels
    )


def _wcp_fwd_interpret(f1, f2_levels, coords, radius, band=None):
    """Interpreter-mode forward (kernel correctness tests off-TPU)."""
    return _wcp_fwd_tpu(f1, tuple(f2_levels), coords, radius,
                        interpret=True, band=band)


def _wcp_bwd_interpret(f1, f2_levels, coords, dout, radius, band=None):
    """Interpreter-mode backward (kernel correctness tests off-TPU)."""
    return _wcp_bwd_tpu(f1, tuple(f2_levels), coords, dout, radius,
                        interpret=True, band=band)


def _wcp_fwd_tpu(f1, f2_levels, coords, radius, interpret=False,
                 band=None):
    b, n_i, n_j, c = f1.shape
    k = 2 * radius + 1
    n_lvl = len(f2_levels)
    dims = tuple((f2.shape[1], f2.shape[2]) for f2 in f2_levels)
    f2p = _wcp_pad_f2(f2_levels, radius)
    if band is None:
        band = _wcp_band_enabled()

    if band:
        # pad the position axis to whole chunks; padded positions sample
        # around coord 0 (in-bounds garbage) and are sliced off below
        n_jp = -(-n_j // _PB) * _PB
        if n_jp != n_j:
            f1 = jnp.pad(f1, ((0, 0), (0, 0), (0, n_jp - n_j), (0, 0)))
            coords = jnp.pad(coords,
                             ((0, 0), (0, 0), (0, n_jp - n_j), (0, 0)))
        f1r = f1.reshape(b, n_i, n_jp // _PB, _PB, c)
        kernel = functools.partial(_wcp_fwd_band_kernel, radius=radius,
                                   dims=dims)
        f1_spec = pl.BlockSpec((1, 1, n_jp // _PB, _PB, c),
                               lambda bi, ii: (bi, ii, 0, 0, 0),
                               memory_space=pltpu.VMEM)
    else:
        n_jp = n_j
        # j rides an untiled axis (the dummy sublane dim keeps the
        # last-two dims static so per-position dynamic indexing is legal)
        f1r = f1.reshape(b, n_i, n_j, 1, c)
        kernel = functools.partial(_wcp_fwd_kernel, radius=radius,
                                   dims=dims)
        f1_spec = pl.BlockSpec((1, 1, n_j, 1, c),
                               lambda bi, ii: (bi, ii, 0, 0, 0),
                               memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_i, n_jp, n_lvl * k, k),
                                       jnp.float32),
        grid=(b, n_i),
        in_specs=[
            pl.BlockSpec((1, 1, n_jp, 2), lambda bi, ii: (bi, ii, 0, 0),
                         memory_space=pltpu.SMEM),
            f1_spec,
        ] + [
            pl.BlockSpec((1,) + f2.shape[1:], lambda bi, ii: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM)
            for f2 in f2p
        ],
        out_specs=pl.BlockSpec((1, 1, n_jp, n_lvl * k, k),
                               lambda bi, ii: (bi, ii, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(coords, f1r, *f2p)
    out = out[:, :, :n_j]
    # (level, dx, dy) channel flatten — (L*k, k) row-major is exactly that
    return out.reshape(b, n_i, n_j, n_lvl * k * k)


def _wcp_band_enabled():
    from ..utils import env

    return env.get_bool("RMD_WCP_BAND")


def _wcp_bwd_tpu(f1, f2_levels, coords, dout, radius, interpret=False,
                 band=None):
    b, n_i, n_j, c = f1.shape
    lo, _hi_y, _hi_x = _wcp_pads(radius)
    f2p = _wcp_pad_f2(f2_levels, radius)
    dims = tuple((f2.shape[1], f2.shape[2]) for f2 in f2_levels)
    if band is None:
        band = _wcp_band_enabled()

    k = 2 * radius + 1
    n_lvl = len(f2_levels)

    if band:
        # whole-chunk padding; padded positions carry zero dout and
        # coords 0 (in-bounds), so they contribute nothing to df1/df2
        n_jp = -(-n_j // _PB) * _PB
        if n_jp != n_j:
            pad = ((0, 0), (0, 0), (0, n_jp - n_j), (0, 0))
            f1 = jnp.pad(f1, pad)
            coords = jnp.pad(coords, pad)
            dout = jnp.pad(dout, pad)
        f1r = f1.reshape(b, n_i, n_jp // _PB, _PB, c)
        row_spec = pl.BlockSpec((1, 1, n_jp // _PB, _PB, c),
                                lambda bi, ii: (bi, ii, 0, 0, 0),
                                memory_space=pltpu.VMEM)
        df1_kernel = functools.partial(_wcp_bwd_df1_band_kernel,
                                       radius=radius, dims=dims)
        df2_kernel = _wcp_bwd_df2_band_kernel
        df1_shape = (b, n_i, n_jp // _PB, _PB, c)
    else:
        n_jp = n_j
        f1r = f1.reshape(b, n_i, n_j, 1, c)
        row_spec = pl.BlockSpec((1, 1, n_j, 1, c),
                                lambda bi, ii: (bi, ii, 0, 0, 0),
                                memory_space=pltpu.VMEM)
        df1_kernel = functools.partial(_wcp_bwd_df1_kernel, radius=radius,
                                       dims=dims)
        df2_kernel = _wcp_bwd_df2_kernel
        df1_shape = (b, n_i, n_j, 1, c)

    doutr = dout.reshape(b, n_i, n_jp, n_lvl * k, k)

    coords_spec = pl.BlockSpec((1, 1, n_jp, 2),
                               lambda bi, ii: (bi, ii, 0, 0),
                               memory_space=pltpu.SMEM)
    dout_spec = pl.BlockSpec((1, 1, n_jp, n_lvl * k, k),
                             lambda bi, ii: (bi, ii, 0, 0, 0),
                             memory_space=pltpu.VMEM)

    df1 = pl.pallas_call(
        df1_kernel,
        out_shape=jax.ShapeDtypeStruct(df1_shape, jnp.float32),
        grid=(b, n_i),
        in_specs=[coords_spec, dout_spec] + [
            pl.BlockSpec((1,) + f2.shape[1:], lambda bi, ii: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM)
            for f2 in f2p
        ],
        out_specs=row_spec,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(coords, doutr, *f2p).reshape(b, n_i, n_jp, c)[:, :, :n_j]

    df2_out = []
    for lvl, f2 in enumerate(f2p):
        # pass only this level's dout columns; raise the scoped-vmem cap —
        # the accumulated df2 block (revisited across the i-grid) plus its
        # pipeline double-buffer exceed the default budget at level 0
        dout_l = doutr[:, :, :, lvl * k:(lvl + 1) * k, :]
        dout_l_spec = pl.BlockSpec((1, 1, n_jp, k, k),
                                   lambda bi, ii: (bi, ii, 0, 0, 0),
                                   memory_space=pltpu.VMEM)
        df2_l = pl.pallas_call(
            functools.partial(df2_kernel, radius=radius, lvl=lvl,
                              dims=dims[lvl]),
            out_shape=jax.ShapeDtypeStruct(f2.shape, jnp.float32),
            grid=(b, n_i),
            in_specs=[coords_spec, row_spec, dout_l_spec],
            out_specs=pl.BlockSpec((1,) + f2.shape[1:],
                                   lambda bi, ii: (bi, 0, 0, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=_CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=interpret,
        )(coords, f1r, dout_l)

        # strip the padding back off
        h2, w2 = dims[lvl]
        df2_out.append(df2_l[:, lo:lo + h2, lo:lo + w2, :])

    return df1, tuple(df2_out)


def _wcp_reference(f1, f2_levels, coords, radius):
    """XLA fallback: per-level windowed correlation (exact same math)."""
    from .corr import windowed_correlation

    out = [
        windowed_correlation(f1, f2, coords, radius, float(2 ** lvl),
                             normalize=False)
        for lvl, f2 in enumerate(f2_levels)
    ]
    return jnp.concatenate(out, axis=-1)


def _wcp_fits_vmem(f1, f2_levels, radius):
    """Static shape check: the kernel holds one (b, i)-row of state plus
    every padded f2 map in VMEM; beyond ~64M even the raised compiler
    budget cannot place it, so oversized shapes take the XLA path.

    Also gates on radius: the widened slab width _XW covers the
    (k+1)-lane window plus the ≤7-lane alignment shift only for
    radius ≤ 7 — beyond that the x-selection matrix would silently drop
    the last lerp lane, so larger radii take the (exact) XLA path too.
    """
    if radius > 7:
        return False
    lo, hi_y, hi_x = _wcp_pads(radius)
    k = 2 * radius + 1
    n_lvl = len(f2_levels)
    n_j, c = f1.shape[2], f1.shape[3]
    itemsize = 2 if f1.dtype == jnp.bfloat16 else 4
    total = n_j * (n_lvl * k + 8) * 128 * 4        # out block (padded)
    total += n_j * 8 * c * itemsize                # f1 row block
    for f2 in f2_levels:
        total += (f2.shape[1] + lo + hi_y) * (f2.shape[2] + lo + hi_x) \
            * c * itemsize
    return total <= 64 * 1024 * 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _wcp(f1, f2_levels, coords, radius):
    if jax.default_backend() == "tpu" and _wcp_fits_vmem(f1, f2_levels,
                                                        radius):
        return _wcp_fwd_tpu(f1, f2_levels, coords, radius)
    return _wcp_reference(f1, f2_levels, coords, radius)


def _wcp_vjp_fwd(f1, f2_levels, coords, radius):
    return _wcp(f1, f2_levels, coords, radius), (f1, f2_levels, coords)


def _wcp_vjp_bwd(radius, res, dout):
    f1, f2_levels, coords = res
    if jax.default_backend() == "tpu" and _wcp_fits_vmem(f1, f2_levels,
                                                        radius):
        df1, df2 = _wcp_bwd_tpu(f1, f2_levels, coords, dout, radius)
    else:
        def f(f1_, f2_):
            return _wcp_reference(f1_, f2_, coords, radius)

        _, vjp = jax.vjp(f, f1, f2_levels)
        df1, df2 = vjp(dout)
    df1 = df1.astype(f1.dtype)
    df2 = tuple(g.astype(f2.dtype) for g, f2 in zip(df2, f2_levels))
    # coords are stop_gradient'ed by every caller (the RAFT iteration
    # detaches them); returning zeros keeps the vjp total
    return df1, df2, jnp.zeros_like(coords)


_wcp.defvjp(_wcp_vjp_fwd, _wcp_vjp_bwd)


def windowed_corr_pyramid(f1, f2_levels, coords, radius=4, mask_costs=(),
                          normalize=True):
    """Fused multi-level windowed correlation (B, H, W, L·(2r+1)²).

    f1: (B, H, W, C) frame-1 features; f2_levels: tuple of frame-2 feature
    maps, level l at 1/2^l of f1's resolution (level 0 same-res); coords:
    (B, H, W, 2) level-0 window centers. Output channels are ordered
    (level, dx, dy) and normalized by sqrt(C) — drop-in identical to
    ``lookup_pyramid(correlation_pyramid(all_pairs_correlation(f1, f2)))``
    without ever building the volume. ``mask_costs`` zeroes whole levels
    by pyramid level id (l + 3), like the reference (raft.py:86).
    """
    c = f1.shape[-1]
    k = 2 * radius + 1
    if normalize:
        f1 = (f1 / jnp.sqrt(jnp.asarray(c, jnp.float32))).astype(f1.dtype)

    out = _wcp(f1, tuple(f2_levels), coords, radius)

    if mask_costs:
        keep = jnp.concatenate([
            jnp.full((k * k,), 0.0 if lvl + 3 in mask_costs else 1.0,
                     jnp.float32)
            for lvl in range(len(f2_levels))
        ])
        out = out * keep
    return out


# ---------------------------------------------------------------------------
# Fused DICL window sampler.
#
# The DICL-family matching path samples the full (2r+1)² displaced feature
# window per position (``ops.sample.sample_window``) — not a dot-product
# readout like the windowed correlation above, but the raw (k, k, C) window
# the MatchingNet then convolves. The XLA form gathers one (k+1)² integer
# patch per position through HBM (a giant take_along_axis) and materializes
# it before the two lerps; this kernel reuses the proven 8-aligned-slab
# machinery of the windowed correlation (``_wcp_window`` / ``_x_select`` /
# ``_wcp_pads``) to keep the patch and both separable lerps in VMEM: per
# position it reads one (k+1, _XW, C) slab, lerps y as a static row pair,
# resolves x per static dx via the arithmetic lane-selection matrix, and
# writes the (k², C) window row — nothing patch-sized ever touches HBM.
#
# The custom VJP accumulates the window gradient back into the padded f2
# map (transpose of the two lerps), mirroring ``_wcp_bwd_df2_kernel``.
# Coordinates get a zero gradient: every caller (the corr modules inside
# the RAFT iteration) stop-gradients the lookup centers, exactly like the
# windowed-correlation kernel's contract.


def _sw_fwd_kernel(coords_ref, f2_ref, out_ref, *, radius, dims):
    k = 2 * radius + 1
    h2, w2 = dims
    n_j = out_ref.shape[2]

    def body(j, _):
        cx = coords_ref[0, 0, j, 0]
        cy = coords_ref[0, 0, j, 1]
        x8, s, y0, fx, fy = _wcp_window(cx, cy, 0, h2, w2, radius)

        slab = f2_ref[0, pl.ds(y0, k + 1), pl.ds(x8, _XW), :]
        slab = slab.astype(jnp.float32)                 # (k+1, _XW, C)
        t = (1.0 - fy) * slab[0:k] + fy * slab[1:k + 1]  # (k_dy, _XW, C)
        m = _x_select(s, fx, k)                          # (_XW, k_dx)

        # dx-major rows: column dx of m lerps lanes s+dx / s+dx+1
        rows = [
            jnp.sum(t * m[None, :, dx:dx + 1], axis=1)   # (k_dy, C)
            for dx in range(k)
        ]
        out_ref[0, 0, j] = jnp.concatenate(rows, axis=0)  # (k², C) (dx, dy)
        return 0

    jax.lax.fori_loop(0, n_j, body, 0)


def _sw_bwd_kernel(coords_ref, dout_ref, df2_ref, *, radius, dims):
    """df2 accumulated across the i-grid (the padded output block is
    indexed by b only and stays resident in VMEM, like
    ``_wcp_bwd_df2_kernel``)."""
    k = 2 * radius + 1
    h2, w2 = dims
    n_j = dout_ref.shape[2]
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        df2_ref[:] = jnp.zeros_like(df2_ref)

    def body(j, _):
        cx = coords_ref[0, 0, j, 0]
        cy = coords_ref[0, 0, j, 1]
        x8, s, y0, fx, fy = _wcp_window(cx, cy, 0, h2, w2, radius)
        m = _x_select(s, fx, k)                          # (_XW, k_dx)

        dv = dout_ref[0, 0, j].astype(jnp.float32)       # (k², C) (dx, dy)
        # transpose of the x-selection: spread each dx row block over lanes
        dt = None
        for dx in range(k):
            part = (dv[dx * k:(dx + 1) * k][:, None, :]
                    * m[None, :, dx:dx + 1])             # (k_dy, _XW, C)
            dt = part if dt is None else dt + part
        zr = jnp.zeros((1, _XW, dt.shape[-1]), jnp.float32)
        dd = ((1.0 - fy) * jnp.concatenate([dt, zr], axis=0)
              + fy * jnp.concatenate([zr, dt], axis=0))  # (k+1, _XW, C)

        df2_ref[0, pl.ds(y0, k + 1), pl.ds(x8, _XW), :] += dd
        return 0

    jax.lax.fori_loop(0, n_j, body, 0)


def _sw_fwd_tpu(f2, coords, radius, interpret=False):
    b, n_i, n_j = coords.shape[:3]
    c = f2.shape[-1]
    k = 2 * radius + 1
    dims = (f2.shape[1], f2.shape[2])
    (f2p,) = _wcp_pad_f2((f2,), radius)

    out = pl.pallas_call(
        functools.partial(_sw_fwd_kernel, radius=radius, dims=dims),
        out_shape=jax.ShapeDtypeStruct((b, n_i, n_j, k * k, c),
                                       jnp.float32),
        grid=(b, n_i),
        in_specs=[
            pl.BlockSpec((1, 1, n_j, 2), lambda bi, ii: (bi, ii, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1,) + f2p.shape[1:], lambda bi, ii: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, n_j, k * k, c),
                               lambda bi, ii: (bi, ii, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(coords, f2p)
    # (b, i, j, dx·k+dy, c) → the sample_window (B, du, dv, H, W, C) layout
    out = out.reshape(b, n_i, n_j, k, k, c)
    return out.transpose(0, 3, 4, 1, 2, 5)


def _sw_bwd_tpu(f2, coords, dout, radius, interpret=False):
    b, n_i, n_j = coords.shape[:3]
    c = f2.shape[-1]
    k = 2 * radius + 1
    lo, _hi_y, _hi_x = _wcp_pads(radius)
    dims = (f2.shape[1], f2.shape[2])
    (f2p,) = _wcp_pad_f2((f2,), radius)

    # (B, du, dv, H, W, C) → the kernel's (b, i, j, dx·k+dy, c) row layout
    doutr = dout.astype(jnp.float32).transpose(0, 3, 4, 1, 2, 5)
    doutr = doutr.reshape(b, n_i, n_j, k * k, c)

    df2 = pl.pallas_call(
        functools.partial(_sw_bwd_kernel, radius=radius, dims=dims),
        out_shape=jax.ShapeDtypeStruct(f2p.shape, jnp.float32),
        grid=(b, n_i),
        in_specs=[
            pl.BlockSpec((1, 1, n_j, 2), lambda bi, ii: (bi, ii, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, n_j, k * k, c),
                         lambda bi, ii: (bi, ii, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1,) + f2p.shape[1:],
                               lambda bi, ii: (bi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(coords, doutr)

    h2, w2 = dims
    return df2[:, lo:lo + h2, lo:lo + w2, :]


def _sw_fwd_interpret(f2, coords, radius):
    """Interpreter-mode forward (kernel correctness tests off-TPU)."""
    return _sw_fwd_tpu(f2, coords, radius, interpret=True)


def _sw_bwd_interpret(f2, coords, dout, radius):
    """Interpreter-mode backward (kernel correctness tests off-TPU)."""
    return _sw_bwd_tpu(f2, coords, dout, radius, interpret=True)


def _sw_reference(f2, coords, radius):
    """XLA fallback with identical semantics (used off-TPU and as the
    numerical reference in tests)."""
    from .sample import sample_window

    return sample_window(f2, coords, radius)


def _sw_fits_vmem(f2, coords, radius):
    """Static shape check, mirroring ``_wcp_fits_vmem``: one (b, i)-row of
    output plus the padded f2 map must sit in VMEM, and the x-selection
    matrix covers the alignment shift only for radius ≤ 7."""
    if radius > 7:
        return False
    lo, hi_y, hi_x = _wcp_pads(radius)
    k = 2 * radius + 1
    n_j, c = coords.shape[2], f2.shape[-1]
    itemsize = 2 if f2.dtype == jnp.bfloat16 else 4
    total = n_j * k * k * max(c, 128) * 4              # out row (lane-padded)
    total += (f2.shape[1] + lo + hi_y) * (f2.shape[2] + lo + hi_x) \
        * c * itemsize
    return total <= 64 * 1024 * 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sw(f2, coords, radius):
    if jax.default_backend() == "tpu" and _sw_fits_vmem(f2, coords, radius):
        return _sw_fwd_tpu(f2, coords, radius)
    return _sw_reference(f2, coords, radius)


def _sw_vjp_fwd(f2, coords, radius):
    return _sw(f2, coords, radius), (f2, coords)


def _sw_vjp_bwd(radius, res, dout):
    f2, coords = res
    if jax.default_backend() == "tpu" and _sw_fits_vmem(f2, coords, radius):
        df2 = _sw_bwd_tpu(f2, coords, dout, radius)
    else:
        def f(f2_):
            return _sw_reference(f2_, jax.lax.stop_gradient(coords), radius)

        out, vjp = jax.vjp(f, f2)
        (df2,) = vjp(dout.astype(out.dtype))
    # coords are stop_gradient'ed by every caller (the RAFT iteration
    # detaches them); returning zeros keeps the vjp total
    return df2.astype(f2.dtype), jnp.zeros_like(coords)


_sw.defvjp(_sw_vjp_fwd, _sw_vjp_bwd)


def sample_window_fused(f2, coords, radius=4):
    """Fused (2r+1)² displaced-window sampler, (B, du, dv, H, W, C).

    Drop-in for ``ops.sample.sample_window`` — same zero-padding
    semantics, same (du varies dx) window layout — with the patch gather
    and both separable lerps fused in VMEM on TPU (XLA reference path
    elsewhere / for oversized shapes). Output dtype follows ``f2``; the
    kernel computes in f32 and rounds once on write. Coordinates are
    treated as non-differentiable (zero gradient): callers inside the
    recurrent estimators detach the lookup centers.
    """
    return _sw(f2, coords, radius).astype(f2.dtype)


def convex_combine_8x(mask_logits, win, temperature=4.0):
    """Fused softmax-over-neighbors + convex combine.

    mask_logits: (..., 576), channels neighbor-major ``k * 64 + s``
    (torch RAFT's native layout). win: (..., 9, 2) float32 neighbor flow
    windows. Returns (..., 128) float32, channels ``chan * 64 + s`` —
    reshape to (..., 2, 8, 8) and pixel-shuffle for the upsampled flow.
    """
    lead = mask_logits.shape[:-1]
    logits2d = mask_logits.reshape(-1, _K * _S)
    win2d = win.astype(jnp.float32).reshape(-1, _K * _C)
    out = _combine(logits2d, win2d, 1.0 / temperature)
    return out.reshape(*lead, _C * _S)
