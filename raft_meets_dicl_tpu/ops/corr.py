"""Correlation volumes: all-pairs construction, pyramid, and windowed lookup.

TPU-native redesign of the reference's ``CorrBlock``
(src/models/impls/raft.py:15-95): the all-pairs dot-product volume is a
single batched einsum that XLA tiles onto the MXU; the pyramid is a reshape
mean (no reduce-window needed for stride-2 pooling); the (2r+1)² windowed
lookup is four vectorized gathers with bilinear weights, matching
``F.grid_sample(align_corners=True)`` zero-padding semantics exactly.

Also provides the memory-light on-the-fly windowed correlation (the
reference's ``raft/fs`` strategy, src/models/impls/raft_fs.py:13-100) which
never materializes the O(H²W²) volume — the framework's answer to the
long-(spatial-)context problem at high resolution.

Conventions: features NHWC ``(B, H, W, C)``; coords ``(B, H, W, 2)`` pixel
positions with channel 0 = x, 1 = y; lookup output channels ordered
``(level, dx, dy)`` row-major — identical to the reference's channel layout
(raft.py:57-92, window axes are (dx, dy) with ``indexing='ij'``).
"""

import jax.numpy as jnp

from .quant import QuantizedLevel, zero_point


def all_pairs_correlation(fmap1, fmap2):
    """(B, H, W, C) x (B, H, W, C) -> (B, H, W, H, W) dot-product volume.

    Normalized by sqrt(C) like the reference (raft.py:33). Accumulates in
    float32 regardless of input dtype (bf16 inputs ride the MXU).
    """
    c = fmap1.shape[-1]
    corr = jnp.einsum(
        "bijc,bklc->bijkl", fmap1, fmap2, preferred_element_type=jnp.float32
    )
    return corr / jnp.sqrt(jnp.asarray(c, dtype=jnp.float32))


def _pool2x_last2(corr):
    """Average-pool the trailing two axes by 2 (reference raft.py:38-47).

    Odd trailing sizes floor like ``F.avg_pool2d`` does: the last row/column
    is dropped before the reshape-mean.
    """
    *lead, h2, w2 = corr.shape
    corr = corr[..., : h2 // 2 * 2, : w2 // 2 * 2]
    corr = corr.reshape(*lead, h2 // 2, 2, w2 // 2, 2)
    return corr.mean(axis=(-3, -1))


def correlation_pyramid(corr, num_levels=4):
    """Build the lookup pyramid: level i pools the target (last two) axes 2^i."""
    pyramid = [corr]
    for _ in range(1, num_levels):
        corr = _pool2x_last2(corr)
        pyramid.append(corr)
    return pyramid


def _pool2x_spatial(fmap):
    """Average-pool the H, W axes of a (B, H, W, C) feature map by 2
    (floor semantics like ``_pool2x_last2``). Accumulates in float32."""
    b, h, w, c = fmap.shape
    x = fmap[:, : h // 2 * 2, : w // 2 * 2].astype(jnp.float32)
    x = x.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))
    return x.astype(fmap.dtype)


def correlation_pyramid_direct(fmap1, fmap2, num_levels=4, dtype=None,
                               normalize=True):
    """Pyramid of all-pairs volumes against progressively pooled frame-2 maps.

    Mathematically identical to ``correlation_pyramid(all_pairs_correlation
    (fmap1, fmap2))`` — average pooling commutes with the dot product by
    linearity — but TPU-native: each level is one large MXU einsum against a
    tiny pooled feature map, instead of reshape/mean chains over the
    O(H²W²) volume (whose oddly-tiled intermediates cost layout copies in
    both passes; profiled ~8 ms/step at the bench config). ``dtype`` casts
    each level after the f32-accumulated einsum (bf16 under the mixed
    policy halves volume HBM traffic). ``normalize=False`` skips the
    1/sqrt(C) scale (the raft/fs lookup convention, reference
    raft_fs.py:76).
    """
    c = fmap1.shape[-1]
    scale = (1.0 / jnp.sqrt(jnp.asarray(c, jnp.float32))
             if normalize else jnp.asarray(1.0, jnp.float32))

    pyramid = []
    f2 = fmap2
    for lvl in range(num_levels):
        corr = jnp.einsum("bijc,bklc->bijkl", fmap1, f2,
                          preferred_element_type=jnp.float32) * scale
        pyramid.append(corr.astype(dtype) if dtype is not None else corr)
        if lvl + 1 < num_levels:
            f2 = _pool2x_spatial(f2)
    return pyramid


def correlation_volume(fmap1, fmap2_level, dtype=None, normalize=True):
    """Single-level all-pairs volume: (B, H1, W1, H2, W2) against one
    (possibly pooled) frame-2 map.

    The per-level building block of ``correlation_pyramid_direct`` — the
    hybrid per-level dispatch (raft/fs) materializes volumes for only the
    coarse pyramid levels whose O(H1·W1·H2·W2) cost fits the budget.
    Accumulates in float32 on the MXU; ``dtype`` casts the result.
    """
    c = fmap1.shape[-1]
    corr = jnp.einsum("bijc,bklc->bijkl", fmap1, fmap2_level,
                      preferred_element_type=jnp.float32)
    if normalize:
        corr = corr / jnp.sqrt(jnp.asarray(c, jnp.float32))
    return corr.astype(dtype) if dtype is not None else corr


def window_offsets(radius, dtype=jnp.float32):
    """(2r+1,) per-axis window offsets: -r, ..., 0, ..., r.

    The single source of truth for window sampling positions — both the 2-D
    ``window_delta`` grid and the factorized per-axis lookups derive from it.
    """
    return jnp.linspace(-radius, radius, 2 * radius + 1, dtype=dtype)


def window_delta(radius, dtype=jnp.float32):
    """(K, K, 2) window offsets; axis 0 varies x, axis 1 varies y.

    Matches the reference's ``meshgrid(dx, dy, indexing='ij')`` layout
    (raft.py:57-59): delta[a, b] = (dx_a, dy_b). This ordering defines the
    channel layout of every windowed lookup/readout in the framework —
    import it rather than re-deriving it.
    """
    d = window_offsets(radius, dtype)
    dx, dy = jnp.meshgrid(d, d, indexing="ij")
    return jnp.stack((dx, dy), axis=-1)


_window_delta = window_delta


def _interp_matrix(positions, size):
    """Bilinear interpolation matrix: hat weights over an axis.

    positions: (..., K) float sample positions along an axis of length
    ``size``. Returns (..., K, size) with ``w[..., k, i] =
    max(0, 1 - |positions[..., k] - i|)`` — exactly bilinear interpolation
    with zero padding outside (out-of-range corners simply have no column),
    matching ``F.grid_sample(align_corners=True, padding_mode='zeros')``.
    """
    idx = jnp.arange(size, dtype=positions.dtype)
    return jnp.maximum(0.0, 1.0 - jnp.abs(positions[..., None] - idx))


def _lookup_level(corr, x, y):
    """Bilinearly sample a (B, H1, W1, H2, W2) volume at per-position windows.

    x, y: (B, H1, W1, K) pixel coordinates into the W2/H2 axes (the K×K
    window factorizes into per-axis offsets). Returns (B, H1, W1, K, K)
    with axes ordered (y-window, x-window) — dy-major, see the layout
    note on the final einsum.

    TPU-first design: instead of gathering scalars (XLA gather costs ~16ns
    per index on TPU — profiled as 95% of the forward pass), the bilinear
    window lookup contracts the volume with two tiny structured
    interpolation matrices. Both contractions ride the MXU and their VJPs
    are transposed einsums (no scatter in the backward pass).

    ``corr`` may be a ``quant.QuantizedLevel`` (the quantized matching
    tier): the integer values are converted and zero-shifted in bf16 —
    a convert that fuses into the einsum operand read on TPU, so the
    HBM stream stays at the quantized width — and the symmetric scale,
    being a constant factor of the linear contraction, applies once to
    the small (B, H1, W1, K, K) output instead of the O(H²W²) volume.
    """
    if isinstance(corr, QuantizedLevel):
        values, scale = corr
        h2, w2 = values.shape[-2:]
        wy = _interp_matrix(y, h2).astype(jnp.bfloat16)
        wx = _interp_matrix(x, w2).astype(jnp.bfloat16)
        deq = (values.astype(jnp.bfloat16)
               - jnp.asarray(zero_point(values), jnp.bfloat16))
        t = jnp.einsum("bijkh,bijhw->bijkw", wy, deq,
                       preferred_element_type=jnp.float32)
        t = t.astype(jnp.bfloat16)
        out = jnp.einsum("bijkw,bijaw->bijka", t, wx,
                         preferred_element_type=jnp.float32)
        return out * scale

    h2, w2 = corr.shape[-2:]
    wy = _interp_matrix(y, h2)  # (B, H1, W1, K, H2)
    wx = _interp_matrix(x, w2)  # (B, H1, W1, K, W2)

    if corr.dtype == jnp.bfloat16:
        # under the bf16 policy the interpolation weights ride the MXU in
        # bf16 too (halves the dominant HBM read); hat weights are in [0, 1]
        # so the rounding error is benign, and accumulation stays f32
        wy = wy.astype(jnp.bfloat16)
        wx = wx.astype(jnp.bfloat16)

    t = jnp.einsum("bijkh,bijhw->bijkw", wy, corr,
                   preferred_element_type=jnp.float32)
    if corr.dtype == jnp.bfloat16:
        t = t.astype(jnp.bfloat16)
    # (dy, dx)-ordered output: both einsums then produce k-major layouts,
    # which XLA keeps without relayout copies between them (the (dx, dy)
    # order forced a transposed copy of every level in fwd and bwd)
    return jnp.einsum("bijkw,bijaw->bijka", t, wx,
                      preferred_element_type=jnp.float32)


def lookup_pyramid_levels(pyramid, coords, radius, mask_costs=(),
                          first_level=0):
    """Windowed lookup, one (B, H, W, K_dy, K_dx) tensor per pyramid level.

    The un-flattened variant of ``lookup_pyramid``: consumers that contract
    the window axes anyway (the motion encoder's 1x1 conv, the soft-argmax
    readout) take the per-level list directly — reshaping (K, K) minor dims
    to K² and concatenating levels forces XLA layout copies of
    (8,128)-tile-padded windows, profiled at ~30 ms/step at the bench
    config.

    ``first_level`` offsets the pyramid: ``pyramid[i]`` is treated as
    octave ``first_level + i`` for center scaling and ``mask_costs`` ids —
    the hybrid per-level dispatch (raft/fs) looks up only the coarse
    suffix of the pyramid through volumes.
    """
    d = window_offsets(radius, coords.dtype)

    out = []
    for i, corr in enumerate(pyramid):
        lvl = first_level + i
        centers = coords / (2**lvl)
        x = centers[..., 0:1] + d  # (B, H, W, K) window positions along W2
        y = centers[..., 1:2] + d  # (B, H, W, K) window positions along H2
        level = _lookup_level(corr, x, y)  # (..., K_dy, K_dx)
        if lvl + 3 in mask_costs:
            level = jnp.zeros_like(level)
        out.append(level)

    return out


def lookup_pyramid(pyramid, coords, radius, mask_costs=(), first_level=0):
    """Windowed lookup over all pyramid levels (reference raft.py:49-95).

    coords: (B, H, W, 2) level-0 target-pixel positions. Returns
    (B, H, W, L*(2r+1)²) with channels ordered (level, dx, dy).
    ``mask_costs`` zeroes whole levels by *pyramid level id* (i + 3, i.e.
    downsampling octave), matching the reference's convention (raft.py:86).
    """
    k = 2 * radius + 1
    levels = lookup_pyramid_levels(pyramid, coords, radius, mask_costs,
                                   first_level)
    # levels are (dy, dx)-ordered; the flat channel contract is dx-major
    return jnp.concatenate(
        [lvl.transpose(0, 1, 2, 4, 3).reshape(*coords.shape[:3], k * k)
         for lvl in levels], axis=-1)


class CorrVolume:
    """Convenience wrapper: build pyramid once, look up per GRU iteration.

    Functional equivalent of the reference ``CorrBlock`` object
    (raft.py:15-95); safe to close over inside a jitted function since it
    holds only arrays and static ints.
    """

    def __init__(self, fmap1, fmap2, num_levels=4, radius=4):
        self.num_levels = num_levels
        self.radius = radius
        corr = all_pairs_correlation(fmap1, fmap2)
        self.pyramid = correlation_pyramid(corr, num_levels)

    def __call__(self, coords, mask_costs=()):
        return lookup_pyramid(self.pyramid, coords, self.radius, mask_costs)


def windowed_correlation(fmap1, fmap2_level, coords, radius, scale,
                         normalize=True):
    """On-the-fly windowed correlation without materializing the volume.

    For each source position p with center c = coords[p]/scale, computes
    dot(f1[p], f2_level[c + d]) for d in the (2r+1)² window, with bilinear
    sampling of f2_level. Returns (B, H, W, (2r+1)²), channels (dx, dy)
    row-major. O(B·H·W·K²·C) memory instead of O(B·H²W²).

    ``normalize`` divides by sqrt(C) like the RAFT baseline volume
    (reference raft.py:33); the ``raft/fs`` variant's lookup skips it
    (reference raft_fs.py:76).
    """
    from .sample import sample_bilinear

    b, h, w, c = fmap1.shape
    k = 2 * radius + 1
    delta = _window_delta(radius, coords.dtype)

    centers = coords[:, :, :, None, None, :] / scale + delta  # (B,H,W,K,K,2)
    x = centers[..., 0].reshape(b, h, w, k * k)
    y = centers[..., 1].reshape(b, h, w, k * k)

    # sample_bilinear treats leading img dims as batch: (B, H2, W2, C) sampled
    # at (B, H*W*K*K) positions
    sampled = sample_bilinear(fmap2_level, x.reshape(b, -1), y.reshape(b, -1))
    sampled = sampled.reshape(b, h, w, k * k, c)

    corr = jnp.einsum("bhwc,bhwkc->bhwk", fmap1, sampled, preferred_element_type=jnp.float32)
    if normalize:
        corr = corr / jnp.sqrt(jnp.asarray(c, dtype=jnp.float32))
    return corr
