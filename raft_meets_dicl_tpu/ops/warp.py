"""Coordinate grids and backwards warping (reference
src/models/common/grid.py:4-12, src/models/common/warp.py:5-33). NHWC."""

import jax.numpy as jnp

from .sample import sample_bilinear


def coordinate_grid(batch, h, w, dtype=jnp.float32):
    """(B, H, W, 2) grid of absolute pixel positions, channel 0 = x, 1 = y."""
    cy, cx = jnp.meshgrid(jnp.arange(h, dtype=dtype), jnp.arange(w, dtype=dtype), indexing="ij")
    grid = jnp.stack((cx, cy), axis=-1)
    return jnp.broadcast_to(grid, (batch, h, w, 2))


def warp_backwards(img2, flow, eps=1e-5):
    """Warp img2 back to frame 1 along ``flow``; returns (warped, mask).

    img2: (B, H, W, C); flow: (B, H, W, 2). The mask flags pixels whose
    sample window lies fully inside the image (bilinear weight of valid
    pixels > 1 - eps), matching the reference's ones-image trick
    (warp.py:27-31).
    """
    b, h, w, _ = img2.shape
    pos = coordinate_grid(b, h, w, dtype=flow.dtype) + flow
    x, y = pos[..., 0], pos[..., 1]

    est = sample_bilinear(img2, x, y)
    ones = jnp.ones((b, h, w, 1), dtype=img2.dtype)
    mask = sample_bilinear(ones, x, y) > (1.0 - eps)

    return est * mask, jnp.broadcast_to(mask, est.shape)
