"""Flow upsampling: RAFT convex upsampling and align_corners bilinear resize.

Replaces the reference's ``F.unfold``-based ``Up8Network`` math
(src/models/impls/raft.py:299-331) and ``F.interpolate(mode='bilinear',
align_corners=True)`` inter-level upsampling. NHWC layout.
"""

import jax
import jax.numpy as jnp


def _neighbors3x3(x):
    """Stack the 3x3 neighborhood of each pixel: (B,H,W,C) -> (B,H,W,9,C).

    Neighbor order is (dy, dx) row-major — identical to ``F.unfold`` with a
    (3, 3) kernel and padding 1 (reference raft.py:323).
    """
    b, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    rows = []
    for dy in range(3):
        for dx in range(3):
            rows.append(xp[:, dy : dy + h, dx : dx + w, :])
    return jnp.stack(rows, axis=3)


def convex_upsample_8x(flow, mask_logits, temperature=4.0, factor=8):
    """Convex combination upsampling (reference Up8Network, raft.py:313-331).

    flow: (B, H, W, 2); mask_logits: (B, H, W, 9 * factor²) from the mask
    head, channel layout (neighbor k, sub-row r, sub-col s) — the NHWC analog
    of the reference's ``view(batch, 1, 9, 8, 8, h, w)``. Returns
    (B, H*factor, W*factor, 2). The flow is scaled by ``factor`` (coarse-grid
    displacements to fine-grid displacements).

    The softmax + combine is the fused kernel ``ops.pallas.convex_combine_8x``
    on TPU (factor 8 only); only the pixel shuffle runs in XLA.
    """
    b, h, w, c = flow.shape
    f = factor

    nbrs = _neighbors3x3(f * flow)  # (B, H, W, 9, 2)

    if f == 8:
        from .pallas import convex_combine_8x

        up = convex_combine_8x(mask_logits, nbrs, temperature)
        # pixel shuffle of the (..., c·64 + r·8 + s) channels, phrased as
        # static lane slices + stacks whose minor dims stay wide: the naive
        # rank-6 transpose pads its (8, 2) minor pair to (8, 128) tiles —
        # 64x memory inflation, ~18 ms/step profiled at the bench config
        rows = []
        for r in range(f):
            # (B, H, W, 8, 2): sub-col s minor-major, channel last
            ar = jnp.stack([up[..., 64 * ch + 8 * r : 64 * ch + 8 * (r + 1)]
                            for ch in range(c)], axis=-1)
            rows.append(ar.reshape(b, h, w * f, c))
        return jnp.stack(rows, axis=2).reshape(b, h * f, w * f, c)

    mask = mask_logits.reshape(b, h, w, 9, f, f)
    mask = jax.nn.softmax(mask / temperature, axis=3)
    up = jnp.einsum("bhwkrs,bhwkc->bhrwsc", mask, nbrs)
    return up.reshape(b, h * f, w * f, c)


def _resize_matrix(n_out, n_in, dtype=jnp.float32):
    """(n_out, n_in) align_corners=True bilinear weights: row i holds the
    hat weights of source position i * (n_in - 1) / (n_out - 1)."""
    pos = jnp.linspace(0.0, n_in - 1.0, n_out)
    idx = jnp.arange(n_in, dtype=jnp.float32)
    return jnp.maximum(0.0, 1.0 - jnp.abs(pos[:, None] - idx)).astype(dtype)


def interpolate_bilinear(x, size):
    """Bilinear resize with ``align_corners=True`` semantics, NHWC.

    Matches ``F.interpolate(x, size, mode='bilinear', align_corners=True)``:
    output pixel i samples source position i * (in - 1) / (out - 1).

    The sample grid is regular and static, so the resize is two
    contractions against small static hat-weight matrices — MXU work with
    transposed-matmul gradients. Realizing it through a positional gather
    (as grid_sample must) costs a serialized scatter-add in the backward
    pass, profiled at ~40 ms per resize at the flagship's level-2 shapes.

    Output dtype follows ``x`` (intentional: under the bf16 policy the
    hierarchical-supervision resizes feed bf16 consumers; the accumulation
    itself runs in f32 before the cast, so only the final rounding is
    dtype-dependent). Pre-round-4 the gather path returned f32-promoted
    output; loss-side callers that need f32 should cast before calling.
    """
    ho, wo = size
    hi, wi = x.shape[-3], x.shape[-2]
    if (hi, wi) == (ho, wo):
        return x

    wy = _resize_matrix(ho, hi)
    wx = _resize_matrix(wo, wi)
    out = jnp.einsum("oh,...hwc->...owc", wy, x.astype(jnp.float32))
    return jnp.einsum("pw,...owc->...opc", wx, out).astype(x.dtype)


def upsample_flow_2x(flow, scale_values=True):
    """Double flow resolution (inter-level upsampling in coarse-to-fine
    models); optionally scales displacement values by 2 to account for the
    finer grid."""
    b, h, w, _ = flow.shape
    up = interpolate_bilinear(flow, (2 * h, 2 * w))
    return 2.0 * up if scale_values else up
