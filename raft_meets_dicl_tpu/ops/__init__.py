"""TPU compute ops.

This package is the TPU-native equivalent of the reference's fused CUDA ops
(``torch.matmul`` all-pairs correlation, ``F.grid_sample`` lookups/warps,
``F.avg_pool2d`` pyramids, ``F.unfold`` convex upsampling — reference
src/models/impls/raft.py:31,42,80,323 and src/models/common/warp.py:27).

All ops use the TPU-native NHWC layout; flow fields are ``(..., H, W, 2)``
with channel 0 = horizontal (u/x) and channel 1 = vertical (v/y)
displacement. Implementations are XLA-composite by default (einsum on the
MXU, vectorized gathers) with Pallas kernels for hot paths where profiling
justifies them (see ``ops.pallas``).
"""

from .sample import grid_sample, sample_bilinear
from .pool import avg_pool2d, max_pool2d
from .corr import all_pairs_correlation, correlation_pyramid, lookup_pyramid, CorrVolume
from .quant import QuantizedLevel, quantize_level, dequantize_level, quantize_pyramid, correlation_pyramid_int8
from .upsample import convex_upsample_8x, interpolate_bilinear, upsample_flow_2x
from .warp import warp_backwards, coordinate_grid
