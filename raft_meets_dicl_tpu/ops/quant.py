"""Quantized matching tier: u8/int8 correlation volumes (inference only).

The windowed lookup is memory-bound — every GRU iteration streams the
full volume pyramid from HBM while the contraction itself uses 9/128 of
the MXU (PERF.md round 5 killed the fused-kernel alternative for
exactly this reason). The remaining lever is the *byte* side: the
volume is a similarity score, not a precision-critical activation, so
the fast latency classes store it quantized and dequantize in-register
inside the lookup einsums, shrinking the dominant HBM stream 2x versus
bf16 (4x versus f32).

Two modes, both with per-level per-sample symmetric scales:

- ``u8`` — the pyramid is computed exactly as the full-precision tier
  computes it (f32-accumulated MXU einsums, cast per the model's
  precision policy), then each level is affinely mapped onto the u8
  grid with zero point 128: ``q = round(c / s) + 128``,
  ``c ≈ (q - 128) * s``. One extra rounding step per level at build
  time; the per-iteration lookup stream is 1 byte/element.
- ``i8`` — the correlation itself runs as int8 MXU dots: features are
  range-equalized per (sample, channel) (``g1 = f1 / a``,
  ``g2 = f2 * a`` with ``a = sqrt(amax|f1| / amax|f2|)`` leaves every
  dot product invariant), quantized to int8 per sample, contracted with
  int32 accumulation, dequantized by the product of scales, and the
  resulting volume is requantized to i8 for storage. Same 1
  byte/element stream, plus the build-time einsums move 4x fewer
  operand bytes than f32.

The scale factors out of the (linear) lookup contraction, so dequant
applies once to the small (B, H, W, K, K) window output instead of the
O(H²W²) volume; the u8→bf16 convert-and-shift fuses into the einsum
operand read on TPU, keeping the HBM stream at the quantized width.
Everything here is plain jnp — XLA lowers it on any backend (the
CPU/GPU fallback path of the quant tier) and the programs AOT-export
like any other rung.

Inference-only by design: no custom VJPs, no straight-through
estimators. Training stays on the full-precision tier.
"""

from typing import NamedTuple

import jax.numpy as jnp

#: quantized-volume modes accepted by ``normalize_mode``
MODES = ("u8", "i8")

#: guard against all-zero levels (synthetic inputs, masked costs)
_EPS = 1e-12


def normalize_mode(mode):
    """Canonicalize a quant-mode spec to ``'u8'``, ``'i8'``, or ``None``.

    Accepts the CLI/env spellings (``'u8'``/``'uint8'``,
    ``'i8'``/``'int8'``/``'s8'``, and ``'off'``/``'none'``/``'0'``/empty
    for disabled); ``True`` means the default mode (``'u8'``). Raises
    ``ValueError`` on anything else so a typo'd ``RMD_QUANT`` fails loud
    at session build, not silently full-precision.
    """
    if mode is None or mode is False:
        return None
    if mode is True:
        return "u8"
    m = str(mode).strip().lower()
    if m in ("", "0", "off", "none", "false"):
        return None
    if m in ("u8", "uint8"):
        return "u8"
    if m in ("i8", "int8", "s8"):
        return "i8"
    raise ValueError(
        f"unknown quantization mode {mode!r}: expected one of "
        f"{MODES + ('off',)}")


class QuantizedLevel(NamedTuple):
    """One quantized pyramid level: integer values plus dequant scale.

    A NamedTuple of arrays only, so it traverses pytree boundaries
    (nn.scan broadcast inputs, jit arguments) like the raw volume it
    replaces. The zero point is implied by the dtype — 128 for uint8,
    0 for int8 — keeping the pytree free of static leaves.
    """

    values: jnp.ndarray  # (B, H1, W1, H2, W2) uint8 or int8
    scale: jnp.ndarray   # (B, 1, 1, 1, 1) float32, symmetric step size


def zero_point(values):
    """The implied zero point of a quantized array: 128 for u8, 0 for i8."""
    return 128 if values.dtype == jnp.uint8 else 0


def _symmetric_scale(x, axes, clip):
    """Per-sample symmetric step size: ``clip * amax / 127`` over ``axes``."""
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    return jnp.maximum(amax * clip, _EPS) / 127.0


def quantize_level(corr, mode, clip=1.0):
    """Quantize one (B, H1, W1, H2, W2) volume level to a QuantizedLevel.

    Symmetric per-sample scale (axis 0 stays independent — serve batches
    mix unrelated requests, one outlier sample must not crush another's
    resolution). ``clip`` shrinks the mapped range to a fraction of the
    observed abs-max, trading outlier saturation for finer steps on the
    bulk (``RMD_QUANT_CLIP``); values beyond the range saturate.
    """
    mode = normalize_mode(mode)
    if mode is None:
        raise ValueError("quantize_level requires an explicit mode")
    corr32 = corr.astype(jnp.float32)
    scale = _symmetric_scale(corr32, (1, 2, 3, 4), clip)
    q = jnp.round(corr32 / scale)
    if mode == "u8":
        values = jnp.clip(q + 128.0, 0.0, 255.0).astype(jnp.uint8)
    else:
        values = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return QuantizedLevel(values=values, scale=scale)


def dequantize_level(level, dtype=jnp.float32):
    """Reconstruct the float volume: ``(q - zero_point) * scale``."""
    deq = level.values.astype(jnp.float32) - zero_point(level.values)
    return (deq * level.scale).astype(dtype)


def quantize_pyramid(pyramid, mode, clip=1.0):
    """Quantize every level of a volume pyramid (the ``u8`` tier path)."""
    return [quantize_level(corr, mode, clip=clip) for corr in pyramid]


def _quantize_features(fmap, clip):
    """Per-sample int8 feature quantization for the i8 correlation dots.

    Returns ``(q, s)`` with q int8 (B, H, W, C) and s (B, 1, 1, 1) so
    ``q1 · q2 * s1 * s2`` reconstructs the float dot up to rounding.
    """
    f = fmap.astype(jnp.float32)
    scale = _symmetric_scale(f, (1, 2, 3), clip)
    q = jnp.clip(jnp.round(f / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def correlation_pyramid_int8(fmap1, fmap2, num_levels=4, normalize=True,
                             clip=1.0):
    """All-pairs pyramid where the correlation itself runs as int8 dots.

    Drop-in quantized twin of ``corr.correlation_pyramid_direct``: same
    per-level structure (one einsum against a progressively pooled
    frame-2 map), but the operands are per-sample int8 features and the
    contraction accumulates in int32 — on TPU that's the MXU's native
    int8 path at 4x less operand traffic than f32. Channel ranges of the
    two maps are equalized first (``g1 = f1 / a``, ``g2 = f2 * a``;
    every product ``g1·g2 = f1·f2`` is invariant) so one hot channel on
    either side doesn't consume the shared sample-level range. Each
    dequantized level is then requantized to i8 storage
    (``quantize_level``) for the lookup stream.

    Pooling runs on the float equalized maps (quantize-then-pool would
    compound rounding), so each level's int8 dot sees a freshly
    quantized pooled map.
    """
    from .corr import _pool2x_spatial

    f1 = fmap1.astype(jnp.float32)
    g2 = fmap2.astype(jnp.float32)
    c = f1.shape[-1]

    # per-(sample, channel) range equalizer over the spatial axes
    m1 = jnp.max(jnp.abs(f1), axis=(1, 2), keepdims=True)
    m2 = jnp.max(jnp.abs(g2), axis=(1, 2), keepdims=True)
    a = jnp.sqrt(jnp.maximum(m1, _EPS) / jnp.maximum(m2, _EPS))
    g1 = f1 / a
    g2 = g2 * a

    norm = (1.0 / jnp.sqrt(jnp.asarray(c, jnp.float32))
            if normalize else jnp.asarray(1.0, jnp.float32))
    q1, s1 = _quantize_features(g1, clip)

    pyramid = []
    for lvl in range(num_levels):
        q2, s2 = _quantize_features(g2, clip)
        acc = jnp.einsum("bijc,bklc->bijkl", q1, q2,
                         preferred_element_type=jnp.int32)
        corr = acc.astype(jnp.float32) * (s1 * s2 * norm)[..., None]
        pyramid.append(quantize_level(corr, "i8", clip=clip))
        if lvl + 1 < num_levels:
            g2 = _pool2x_spatial(g2)
    return pyramid
