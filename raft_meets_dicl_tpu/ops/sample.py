"""Bilinear sampling with exact ``torch.nn.functional.grid_sample`` semantics.

The reference leans on ``F.grid_sample(..., align_corners=True)`` (default
zero padding) for correlation-volume lookups (src/models/impls/raft.py:80),
backwards warping (src/models/common/warp.py:27), and DICL cost sampling.
EPE-parity requires matching those semantics exactly: with
``align_corners=True`` a normalized coordinate ``g`` maps to pixel position
``(g + 1) / 2 * (size - 1)``, interpolation is bilinear from the four
surrounding pixels, and any corner outside the image contributes zero.

Layout is NHWC (TPU-native); the reference is NCHW.
"""

import jax.numpy as jnp


def sample_bilinear(img, x, y):
    """Sample ``img`` at pixel coordinates with zero padding outside.

    img: (..., H, W, C) — batch dims broadcast against coordinate batch dims.
    x, y: (..., *S) float pixel coordinates (x along W, y along H).

    Returns (..., *S, C). Out-of-bounds corner contributions are zero,
    matching torch's ``padding_mode='zeros'``.
    """
    H, W, C = img.shape[-3], img.shape[-2], img.shape[-1]

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    x1 = x0 + 1
    y1 = y0 + 1

    wx1 = x - x0
    wy1 = y - y0
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    def gather(ix, iy):
        inb = (ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1)
        ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        # flatten spatial dims for a single gather
        flat = img.reshape(*img.shape[:-3], H * W, C)
        idx = iyc * W + ixc
        batch_shape = img.shape[:-3]
        sshape = ix.shape[len(batch_shape):]
        idxf = idx.reshape(*batch_shape, -1)
        vals = jnp.take_along_axis(flat, idxf[..., None], axis=-2)
        vals = vals.reshape(*batch_shape, *sshape, C)
        return vals * inb[..., None]

    out = (
        gather(x0, y0) * (wx0 * wy0)[..., None]
        + gather(x1, y0) * (wx1 * wy0)[..., None]
        + gather(x0, y1) * (wx0 * wy1)[..., None]
        + gather(x1, y1) * (wx1 * wy1)[..., None]
    )
    return out


def sample_window(f2, coords, radius):
    """Sample f2 at the (2r+1)² displaced positions around each coordinate.

    f2: (B, H2, W2, C) features; coords: (B, H, W, 2) pixel positions *into
    f2's grid* — the two resolutions may differ (multi-level lookups pass
    coarser feature maps with rescaled coordinates). Returns
    (B, du, dv, H, W, C) with zero padding outside — du varies dx.

    All (2r+1)² displacements are integer offsets from one center, so they
    share the center's bilinear fractions: instead of 4 corner gathers per
    displacement (4K² rows per position through ``sample_bilinear``), one
    (K+1)² integer patch is gathered per position and the displaced values
    come from two static-shift lerps over the patch — 3.2x fewer gather
    rows, the dominant cost of the DICL models' training step. Zero padding
    falls out of masking OOB patch entries (every sampled value is a convex
    combination of patch entries, exactly the grid_sample corner terms).

    This is the XLA form (and the reference/fallback for the fused Pallas
    kernel in ``ops.pallas.sample_window_fused``, which keeps the patch and
    both lerps in VMEM instead of gathering through HBM).
    """
    b, h, w = coords.shape[:3]
    h2, w2, c = f2.shape[-3:]
    k = 2 * radius + 1
    t = k + 1

    # patch base = top-left corner of the displacement window
    cx = coords[..., 0].reshape(b, -1) - radius      # (B, P)
    cy = coords[..., 1].reshape(b, -1) - radius
    x0f = jnp.floor(cx)
    y0f = jnp.floor(cy)
    fx = (cx - x0f)[:, None, None, :, None]          # (B, 1, 1, P, 1)
    fy = (cy - y0f)[:, None, None, :, None]

    # tap axes ordered (tx, ty) so the lerped output is (dx, dy)-major,
    # matching window_delta's du-varies-dx channel layout
    tx = jnp.arange(t, dtype=jnp.int32)[None, :, None, None]
    ty = jnp.arange(t, dtype=jnp.int32)[None, None, :, None]
    ix = x0f.astype(jnp.int32)[:, None, None, :] + tx   # (B, T, T, P)
    iy = y0f.astype(jnp.int32)[:, None, None, :] + ty
    inb = (ix >= 0) & (ix <= w2 - 1) & (iy >= 0) & (iy <= h2 - 1)
    idx = (jnp.clip(iy, 0, h2 - 1) * w2 + jnp.clip(ix, 0, w2 - 1))

    flat = f2.reshape(b, h2 * w2, c)
    patch = jnp.take_along_axis(flat, idx.reshape(b, -1)[..., None], axis=1)
    patch = patch.reshape(b, t, t, h * w, c) * inb[..., None]

    # separable lerp over the shared fractions (static shifts only)
    ylerp = (1.0 - fy) * patch[:, :, 0:k] + fy * patch[:, :, 1:t]
    win = (1.0 - fx) * ylerp[:, 0:k] + fx * ylerp[:, 1:t]
    return win.reshape(b, k, k, h, w, c)


def grid_sample(img, grid):
    """``F.grid_sample(img, grid, align_corners=True)`` equivalent, NHWC.

    img: (B, H, W, C); grid: (B, Ho, Wo, 2) normalized coords in [-1, 1],
    channel 0 = x, channel 1 = y. Returns (B, Ho, Wo, C).
    """
    H, W = img.shape[-3], img.shape[-2]
    gx = (grid[..., 0] + 1.0) * 0.5 * (W - 1)
    gy = (grid[..., 1] + 1.0) * 0.5 * (H - 1)
    return sample_bilinear(img, gx, gy)
