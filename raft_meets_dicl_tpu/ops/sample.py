"""Bilinear sampling with exact ``torch.nn.functional.grid_sample`` semantics.

The reference leans on ``F.grid_sample(..., align_corners=True)`` (default
zero padding) for correlation-volume lookups (src/models/impls/raft.py:80),
backwards warping (src/models/common/warp.py:27), and DICL cost sampling.
EPE-parity requires matching those semantics exactly: with
``align_corners=True`` a normalized coordinate ``g`` maps to pixel position
``(g + 1) / 2 * (size - 1)``, interpolation is bilinear from the four
surrounding pixels, and any corner outside the image contributes zero.

Layout is NHWC (TPU-native); the reference is NCHW.
"""

import jax.numpy as jnp


def sample_bilinear(img, x, y):
    """Sample ``img`` at pixel coordinates with zero padding outside.

    img: (..., H, W, C) — batch dims broadcast against coordinate batch dims.
    x, y: (..., *S) float pixel coordinates (x along W, y along H).

    Returns (..., *S, C). Out-of-bounds corner contributions are zero,
    matching torch's ``padding_mode='zeros'``.
    """
    H, W, C = img.shape[-3], img.shape[-2], img.shape[-1]

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    x1 = x0 + 1
    y1 = y0 + 1

    wx1 = x - x0
    wy1 = y - y0
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    def gather(ix, iy):
        inb = (ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1)
        ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        # flatten spatial dims for a single gather
        flat = img.reshape(*img.shape[:-3], H * W, C)
        idx = iyc * W + ixc
        batch_shape = img.shape[:-3]
        sshape = ix.shape[len(batch_shape):]
        idxf = idx.reshape(*batch_shape, -1)
        vals = jnp.take_along_axis(flat, idxf[..., None], axis=-2)
        vals = vals.reshape(*batch_shape, *sshape, C)
        return vals * inb[..., None]

    out = (
        gather(x0, y0) * (wx0 * wy0)[..., None]
        + gather(x1, y0) * (wx1 * wy0)[..., None]
        + gather(x0, y1) * (wx0 * wy1)[..., None]
        + gather(x1, y1) * (wx1 * wy1)[..., None]
    )
    return out


def grid_sample(img, grid):
    """``F.grid_sample(img, grid, align_corners=True)`` equivalent, NHWC.

    img: (B, H, W, C); grid: (B, Ho, Wo, 2) normalized coords in [-1, 1],
    channel 0 = x, channel 1 = y. Returns (B, Ho, Wo, C).
    """
    H, W = img.shape[-3], img.shape[-2]
    gx = (grid[..., 0] + 1.0) * 0.5 * (W - 1)
    gy = (grid[..., 1] + 1.0) * 0.5 * (H - 1)
    return sample_bilinear(img, gx, gy)
